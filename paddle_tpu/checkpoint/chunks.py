"""Content-addressed chunk store — the byte-level tier of the
checkpoint subsystem.

Arrays are split into fixed-size chunks (store.py owns the grid); each
chunk is keyed by the SHA-256 of its bytes and written once under
``<root>/chunks/<hh>/<digest>`` (sha256 over blake2b deliberately:
OpenSSL rides SHA-NI/crypto extensions on modern hosts, ~1.3 GB/s
single-thread, and hashing is the save path's compute cost). A chunk that already exists is never
rewritten — re-referencing it from a new manifest is free, which is
what makes per-step incremental checkpoints cost O(changed bytes)
instead of O(state bytes) (the Orbax/TensorStore role, reduced to a
local content-addressed blob store).

Durability contract: a chunk file is visible under its final name only
after a same-directory ``os.replace`` of a fully written temp file, so
a reader (or a crash-restore) can never observe a torn chunk; restore
re-hashes every chunk it reads (``get(verify=True)``) so silent disk
corruption surfaces as ``ChunkError``, not as garbage parameters.

No pickle anywhere (scripts/check_no_wire_pickle.py scans this tree):
chunk files are raw bytes, addressed by hash.
"""
from __future__ import annotations

import hashlib
import os
import threading

from ..observability import registry as _obs

__all__ = ["ChunkError", "ChunkStore"]

_CHUNKS_WRITTEN = _obs.counter(
    "paddle_tpu_ckpt_chunks_written_total",
    "content-addressed chunks physically written to storage")
_DEDUP_HITS = _obs.counter(
    "paddle_tpu_ckpt_chunks_dedup_hits_total",
    "chunk puts answered by an already-stored identical chunk")
_BYTES_WRITTEN = _obs.counter(
    "paddle_tpu_ckpt_bytes_written_total",
    "checkpoint bytes physically written, by tier", ["tier"])
_GC_CHUNKS = _obs.counter(
    "paddle_tpu_ckpt_gc_chunks_total",
    "unreferenced chunks deleted by retention GC")


class ChunkError(RuntimeError):
    """Missing or corrupt chunk on the restore path."""


def digest_of(data) -> str:
    return hashlib.sha256(data).hexdigest()


class ChunkStore:
    """Content-addressed blobs under ``<root>/chunks/``.

    Thread-safe: concurrent writers of the SAME digest race benignly
    (identical bytes, last rename wins); the stats counters are locked.
    """

    def __init__(self, root: str):
        self.root = root
        self.dir = os.path.join(root, "chunks")
        self._lock = threading.Lock()
        self._tmp_seq = 0
        # process-local accounting (registry counters are global; tests
        # and bench read the per-store numbers)
        self.chunks_written = 0
        self.dedup_hits = 0
        self.bytes_written = 0

    def _path(self, digest: str) -> str:
        return os.path.join(self.dir, digest[:2], digest)

    def has(self, digest: str) -> bool:
        return os.path.isfile(self._path(digest))

    def put(self, data) -> str:
        """Store bytes, return their digest. An existing identical
        chunk is re-referenced, not rewritten (the dedup hit the
        incremental-save economics stand on)."""
        data = bytes(data) if not isinstance(data, (bytes, bytearray)) \
            else data
        digest = digest_of(data)
        path = self._path(digest)
        if os.path.isfile(path):
            with self._lock:
                self.dedup_hits += 1
            _DEDUP_HITS.inc()
            # crash-test hook: dedup'd bytes count as save progress too
            # (a mostly-unchanged incremental save writes few NEW bytes
            # but must still be killable at a deterministic point)
            from ..distributed.fleet.runtime.fault_injection import \
                injector
            inj = injector()
            if inj.active:
                inj.maybe_kill_bytes(len(data))
            return digest
        d = os.path.dirname(path)
        os.makedirs(d, exist_ok=True)
        with self._lock:
            self._tmp_seq += 1
            tmp = f"{path}.tmp.{os.getpid()}.{self._tmp_seq}"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        # crash-test hook: the writer process can be armed to die after
        # N payload bytes (fault_injection kill-after-bytes), modelling
        # a crash mid-save with some chunks on disk and no manifest
        from ..distributed.fleet.runtime.fault_injection import injector
        inj = injector()
        if inj.active:
            inj.maybe_kill_bytes(len(data))
        os.replace(tmp, path)
        with self._lock:
            self.chunks_written += 1
            self.bytes_written += len(data)
        _CHUNKS_WRITTEN.inc()
        _BYTES_WRITTEN.labels(tier="chunk").inc(len(data))
        return digest

    def get(self, digest: str, verify: bool = True) -> bytes:
        path = self._path(digest)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            raise ChunkError(f"chunk {digest} missing from {self.dir}")
        if verify and digest_of(data) != digest:
            raise ChunkError(f"chunk {digest} corrupt on disk "
                             f"(content hash mismatch)")
        return data

    def all_digests(self) -> set[str]:
        out: set[str] = set()
        if not os.path.isdir(self.dir):
            return out
        for sub in os.listdir(self.dir):
            subdir = os.path.join(self.dir, sub)
            if not os.path.isdir(subdir):
                continue
            for fn in os.listdir(subdir):
                if ".tmp." not in fn:
                    out.add(fn)
        return out

    def gc(self, live: set[str]) -> int:
        """Delete chunks not referenced by any retained manifest (and
        any stale temp files from crashed writers). Returns the number
        of chunks deleted."""
        n = 0
        if not os.path.isdir(self.dir):
            return 0
        for sub in os.listdir(self.dir):
            subdir = os.path.join(self.dir, sub)
            if not os.path.isdir(subdir):
                continue
            for fn in os.listdir(subdir):
                if ".tmp." in fn or fn not in live:
                    try:
                        os.unlink(os.path.join(subdir, fn))
                    except OSError:
                        continue
                    if ".tmp." not in fn:
                        n += 1
        if n:
            _GC_CHUNKS.inc(n)
        return n
