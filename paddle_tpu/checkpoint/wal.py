"""Row-level write-ahead journal.

One append-only file of self-delimiting, CRC'd records. This is the
layer that closes the ROADMAP item "a delta still rewrites the whole
dirty table": a PS push journals only the ROWS it touched
(``append_rows(table, idx, values)`` — O(touched rows) bytes), and a
restore replays ``base snapshot + journal`` back to the exact live
state. Compaction (owner-triggered past a byte threshold) folds the
journal into a fresh base and starts a new file.

Record layout (little-endian, data-only — no pickle, scanned by
scripts/check_no_wire_pickle.py):

    magic u32 | crc32(payload) u32 | payload_len u64 | payload
    payload := jlen u32 | header JSON | idx bytes | values bytes
               | extra bytes

The header JSON carries kind ("rows" | "mark"), table metadata
(dim/init_std/seed — enough to recreate the table from nothing), array
dtypes/counts, the RPC request id (exactly-once dedup survives a
crash-restore), and the extra-blob length (an opaque reply blob the PS
tier round-trips; the journal never interprets it).

Torn-tail semantics: a crash mid-append leaves a partial last record;
``replay`` verifies magic + length + CRC per record and STOPS at the
first bad one — everything before it is committed, everything after is
the crash. Appends go through one ``os.write`` per record and are
flushed to the OS before returning (surviving process death); set
``PADDLE_TPU_WAL_FSYNC=1`` to also fsync per append (surviving power
loss, at write-through cost).
"""
from __future__ import annotations

import json
import os
import struct
import threading
import zlib

import numpy as np

from ..observability import flight as _flight, registry as _obs

__all__ = ["RowJournal", "replay_file", "committed_length",
           "WAL_MAGIC"]

WAL_MAGIC = 0x5054574C  # "PTWL"
_REC = struct.Struct("<IIQ")  # magic, crc32(payload), payload_len
_JLEN = struct.Struct("<I")

_ROWS_APPENDED = _obs.counter(
    "paddle_tpu_ckpt_wal_rows_appended_total",
    "table rows appended to row-level WAL journals")
_WAL_RECORDS = _obs.counter(
    "paddle_tpu_ckpt_wal_records_total",
    "records appended to row-level WAL journals", ["kind"])
_WAL_COMPACTIONS = _obs.counter(
    "paddle_tpu_ckpt_wal_compactions_total",
    "WAL journals folded into a fresh base snapshot")


def _encode(header: dict, idx: np.ndarray | None,
            values: np.ndarray | None, extra: bytes) -> bytes:
    jb = json.dumps(header, sort_keys=True,
                    separators=(",", ":")).encode("utf-8")
    parts = [_JLEN.pack(len(jb)), jb]
    if idx is not None:
        parts.append(idx.tobytes())
    if values is not None:
        parts.append(values.tobytes())
    if extra:
        parts.append(extra)
    payload = b"".join(parts)
    return _REC.pack(WAL_MAGIC, zlib.crc32(payload) & 0xFFFFFFFF,
                     len(payload)) + payload


class RowJournal:
    """Appender for one WAL file (thread-safe; one writer process).

    ``recover=True`` (re-opening a journal a previous incarnation may
    have died writing) truncates any torn tail BEFORE appending:
    records appended after garbage would sit beyond the point every
    future replay stops at — silently un-replayable durability."""

    def __init__(self, path: str, fsync: bool | None = None,
                 recover: bool = False):
        self.path = path
        self.fsync = fsync if fsync is not None else \
            os.environ.get("PADDLE_TPU_WAL_FSYNC", "") not in ("", "0")
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        if recover and os.path.exists(path):
            good = committed_length(path)
            if good < os.path.getsize(path):
                with open(path, "r+b") as f:
                    f.truncate(good)
        self._lock = threading.Lock()
        self._fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                           0o644)
        self.bytes_written = 0
        self.rows_appended = 0
        self.records = 0

    def _append(self, record: bytes, rows: int, kind: str) -> int:
        with self._lock:
            if self._fd is None:
                raise ValueError(f"journal {self.path} is closed")
            os.write(self._fd, record)
            if self.fsync:
                os.fsync(self._fd)
            self.bytes_written += len(record)
            self.rows_appended += rows
            self.records += 1
        if rows:
            _ROWS_APPENDED.inc(rows)
        _WAL_RECORDS.labels(kind=kind).inc()
        from .chunks import _BYTES_WRITTEN
        _BYTES_WRITTEN.labels(tier="wal").inc(len(record))
        return len(record)

    def append_rows(self, table: str, idx, values, *, dim: int | None
                    = None, init_std: float = 0.01, seed: int = 0,
                    req_id: int = 0, extra: bytes = b"") -> int:
        """Journal the post-apply VALUES of the touched rows of one
        table. Replay = ensure-rows-exist + assign, which is idempotent
        and (replayed in append order from the same base) reproduces
        the live table's data, key→slot index, and RNG stream exactly.
        Returns bytes appended — O(len(idx) · dim), never O(table)."""
        idx = np.ascontiguousarray(np.asarray(idx, np.int64).ravel())
        values = np.ascontiguousarray(np.asarray(values, np.float32))
        values = values.reshape(len(idx), -1)
        header = {"kind": "rows", "table": table,
                  "dim": int(dim if dim is not None
                             else values.shape[1]),
                  "init_std": float(init_std), "seed": int(seed),
                  "n": int(len(idx)), "kdt": idx.dtype.str,
                  "vdt": values.dtype.str, "vshape": list(values.shape),
                  "req_id": int(req_id), "xlen": len(extra)}
        return self._append(_encode(header, idx, values, extra),
                            len(idx), "rows")

    def append_mark(self, req_id: int, extra: bytes = b"") -> int:
        """Journal a dedup-only record: the request id (and its opaque
        reply blob) of a mutating op whose state effects were journaled
        elsewhere — a crash-restore re-arms exactly-once for it."""
        header = {"kind": "mark", "req_id": int(req_id),
                  "xlen": len(extra)}
        return self._append(_encode(header, None, None, extra), 0,
                            "mark")

    def close(self):
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None

    @staticmethod
    def note_compaction():
        _WAL_COMPACTIONS.inc()
        _flight.record("ckpt", "wal_compaction")


def _walk(blob: bytes):
    """Yield (record, end_offset) for every committed record, stopping
    at the first torn/corrupt one (the crash point)."""
    off = 0
    while off + _REC.size <= len(blob):
        magic, crc, plen = _REC.unpack_from(blob, off)
        if magic != WAL_MAGIC:
            return  # torn tail / foreign bytes: stop
        start = off + _REC.size
        if start + plen > len(blob):
            return  # partial last record (crash mid-append)
        payload = blob[start:start + plen]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            return  # corrupt record: everything after is suspect
        (jlen,) = _JLEN.unpack_from(payload, 0)
        header = json.loads(payload[_JLEN.size:_JLEN.size + jlen]
                            .decode("utf-8"))
        rec = dict(header)
        p = _JLEN.size + jlen
        if header["kind"] == "rows":
            n = int(header["n"])
            kdt = np.dtype(header["kdt"])
            idx = np.frombuffer(payload, kdt, n, p)
            p += n * kdt.itemsize
            vdt = np.dtype(header["vdt"])
            vshape = tuple(header["vshape"])
            nv = int(np.prod(vshape)) if vshape else 1
            rec["idx"] = idx
            rec["values"] = np.frombuffer(payload, vdt, nv,
                                          p).reshape(vshape)
            p += nv * vdt.itemsize
        rec["extra"] = payload[p:p + int(header.get("xlen", 0))]
        off = start + plen
        yield rec, off


def replay_file(path: str):
    """Yield committed records from a WAL file, stopping cleanly at the
    first torn/corrupt record (the crash point). Each yielded dict has
    the header fields plus ``idx``/``values`` ndarrays (rows records)
    and ``extra`` bytes."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except FileNotFoundError:
        return
    for rec, _end in _walk(blob):
        yield rec


def committed_length(path: str) -> int:
    """Byte offset just past the last committed record (0 for a
    missing/empty/corrupt-from-the-start file) — the truncation point
    for reopening a journal after a crash."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except FileNotFoundError:
        return 0
    end = 0
    for _rec, end in _walk(blob):
        pass
    return end
