"""CRC'd JSON manifests with atomic rename commit.

A manifest is the unit of checkpoint visibility: it names every array
(dtype, shape) and the content-addressed chunks that reassemble it.
The COMMIT POINT of a save is the ``os.replace`` that puts
``manifest-<step>.json`` at its final name — chunks land first, the
manifest rename is last, so a crash at ANY byte of the save leaves the
previous committed manifest fully intact (the kill-mid-save test pins
this bit-for-bit).

File layout (pure JSON, no pickle — the restore path is scanned by
scripts/check_no_wire_pickle.py):

    {"format": "paddle-tpu-ckpt-v1", "crc32": <crc of canonical
     payload JSON>, "payload": {"step": N, "meta": {...},
     "arrays": {name: {"dtype", "shape", "nbytes",
                       "chunks": [{"h", "o", "n"}, ...]}}}}

``load_latest`` scans newest-first and skips unreadable / CRC-bad
files, so a torn manifest (crash mid-fsync on a weird filesystem, or
plain disk corruption) degrades to the previous committed step instead
of a failed restore.
"""
from __future__ import annotations

import json
import os
import zlib

from ..observability import registry as _obs

__all__ = ["ManifestError", "commit_manifest", "load_manifest",
           "list_manifests", "load_latest", "manifest_path"]

FORMAT = "paddle-tpu-ckpt-v1"
_PREFIX, _SUFFIX = "manifest-", ".json"

_COMMITS = _obs.counter(
    "paddle_tpu_ckpt_manifests_committed_total",
    "checkpoint manifests atomically committed")


class ManifestError(RuntimeError):
    """No committed manifest, or the named one is unreadable."""


def _canonical(payload: dict) -> bytes:
    return json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def manifest_path(root: str, step: int) -> str:
    return os.path.join(root, f"{_PREFIX}{step:010d}{_SUFFIX}")


def commit_manifest(root: str, payload: dict) -> str:
    """Atomically commit ``payload`` as step ``payload['step']``.
    Write tmp → fsync → rename; the rename IS the commit."""
    step = int(payload["step"])
    path = manifest_path(root, step)
    body = _canonical(payload)
    doc = json.dumps({"format": FORMAT,
                      "crc32": zlib.crc32(body) & 0xFFFFFFFF,
                      "payload": payload}).encode("utf-8")
    os.makedirs(root, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(doc)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    from .chunks import _BYTES_WRITTEN
    _BYTES_WRITTEN.labels(tier="manifest").inc(len(doc))
    _COMMITS.inc()
    return path


def load_manifest(path: str) -> dict:
    """Parse + CRC-validate one manifest file; returns the payload."""
    with open(path, "rb") as f:
        doc = json.loads(f.read().decode("utf-8"))
    if doc.get("format") != FORMAT:
        raise ManifestError(f"{path}: not a {FORMAT} manifest")
    payload = doc["payload"]
    crc = zlib.crc32(_canonical(payload)) & 0xFFFFFFFF
    if crc != int(doc.get("crc32", -1)):
        raise ManifestError(f"{path}: CRC mismatch "
                            f"(stored {doc.get('crc32')}, computed {crc})")
    return payload


def list_manifests(root: str) -> list[tuple[int, str]]:
    """(step, path) of every committed manifest, ascending by step."""
    out = []
    try:
        names = os.listdir(root)
    except FileNotFoundError:
        return []
    for fn in names:
        if fn.startswith(_PREFIX) and fn.endswith(_SUFFIX):
            try:
                out.append((int(fn[len(_PREFIX):-len(_SUFFIX)]),
                            os.path.join(root, fn)))
            except ValueError:
                continue
    return sorted(out)


def load_latest(root: str, step: int | None = None) -> dict:
    """Newest valid manifest (or the exact ``step``). Unreadable or
    CRC-bad files are skipped — restore degrades to the last committed
    step rather than failing on a corrupt newest file."""
    if step is not None:
        return load_manifest(manifest_path(root, step))
    errors = []
    for s, path in reversed(list_manifests(root)):
        try:
            return load_manifest(path)
        except (ManifestError, OSError, ValueError) as e:
            errors.append(f"{path}: {e}")
    raise ManifestError(
        f"no committed checkpoint manifest under {root}"
        + (" (skipped corrupt: " + "; ".join(errors) + ")"
           if errors else ""))
