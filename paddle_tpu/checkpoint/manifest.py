"""CRC'd JSON manifests with atomic rename commit.

A manifest is the unit of checkpoint visibility: it names every array
(dtype, shape) and the content-addressed chunks that reassemble it.
The COMMIT POINT of a save is the ``os.replace`` that puts
``manifest-<step>.json`` at its final name — chunks land first, the
manifest rename is last, so a crash at ANY byte of the save leaves the
previous committed manifest fully intact (the kill-mid-save test pins
this bit-for-bit).

File layout (pure JSON, no pickle — the restore path is scanned by
scripts/check_no_wire_pickle.py):

    {"format": "paddle-tpu-ckpt-v1", "crc32": <crc of canonical
     payload JSON>, "payload": {"step": N, "meta": {...},
     "arrays": {name: {"dtype", "shape", "nbytes",
                       "chunks": [{"h", "o", "n"}, ...]}}}}

``load_latest`` scans newest-first and skips unreadable / CRC-bad
files, so a torn manifest (crash mid-fsync on a weird filesystem, or
plain disk corruption) degrades to the previous committed step instead
of a failed restore.
"""
from __future__ import annotations

import json
import os
import zlib

from ..observability import registry as _obs

__all__ = ["ManifestError", "commit_manifest", "load_manifest",
           "list_manifests", "load_latest", "manifest_path",
           "commit_part", "part_path", "list_parts", "merge_parts"]

FORMAT = "paddle-tpu-ckpt-v1"
_PREFIX, _SUFFIX = "manifest-", ".json"
_PART_PREFIX = "part-"

_COMMITS = _obs.counter(
    "paddle_tpu_ckpt_manifests_committed_total",
    "checkpoint manifests atomically committed")


class ManifestError(RuntimeError):
    """No committed manifest, or the named one is unreadable."""


def _canonical(payload: dict) -> bytes:
    return json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def manifest_path(root: str, step: int) -> str:
    return os.path.join(root, f"{_PREFIX}{step:010d}{_SUFFIX}")


def commit_manifest(root: str, payload: dict) -> str:
    """Atomically commit ``payload`` as step ``payload['step']``.
    Write tmp → fsync → rename; the rename IS the commit."""
    step = int(payload["step"])
    path = manifest_path(root, step)
    body = _canonical(payload)
    doc = json.dumps({"format": FORMAT,
                      "crc32": zlib.crc32(body) & 0xFFFFFFFF,
                      "payload": payload}).encode("utf-8")
    os.makedirs(root, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(doc)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    from .chunks import _BYTES_WRITTEN
    _BYTES_WRITTEN.labels(tier="manifest").inc(len(doc))
    _COMMITS.inc()
    return path


def load_manifest(path: str) -> dict:
    """Parse + CRC-validate one manifest file; returns the payload."""
    with open(path, "rb") as f:
        doc = json.loads(f.read().decode("utf-8"))
    if doc.get("format") != FORMAT:
        raise ManifestError(f"{path}: not a {FORMAT} manifest")
    payload = doc["payload"]
    crc = zlib.crc32(_canonical(payload)) & 0xFFFFFFFF
    if crc != int(doc.get("crc32", -1)):
        raise ManifestError(f"{path}: CRC mismatch "
                            f"(stored {doc.get('crc32')}, computed {crc})")
    return payload


def list_manifests(root: str) -> list[tuple[int, str]]:
    """(step, path) of every committed manifest, ascending by step."""
    out = []
    try:
        names = os.listdir(root)
    except FileNotFoundError:
        return []
    for fn in names:
        if fn.startswith(_PREFIX) and fn.endswith(_SUFFIX):
            try:
                out.append((int(fn[len(_PREFIX):-len(_SUFFIX)]),
                            os.path.join(root, fn)))
            except ValueError:
                continue
    return sorted(out)


def part_path(root: str, step: int, rank: int) -> str:
    return os.path.join(
        root, f"{_PART_PREFIX}{step:010d}.{rank:04d}{_SUFFIX}")


def commit_part(root: str, payload: dict, rank: int,
                world: int) -> str:
    """One rank's PARTIAL manifest of a multi-process save (multi-host
    pjit: each process writes the chunks of the arrays it owns, then
    publishes this part; rank 0 merges the parts into the ONE
    committed version with ``merge_parts``). Same CRC'd doc + atomic
    rename as a full manifest, but under a ``part-`` name that
    ``list_manifests``/``load_latest`` never see — an unmerged or torn
    multi-host save is invisible, and the previous committed step
    stays the restore target."""
    step = int(payload["step"])
    path = part_path(root, step, int(rank))
    doc = json.dumps({"format": FORMAT,
                      "crc32": zlib.crc32(_canonical(payload))
                      & 0xFFFFFFFF,
                      "rank": int(rank), "world": int(world),
                      "payload": payload}).encode("utf-8")
    os.makedirs(root, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(doc)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def list_parts(root: str, step: int) -> list[tuple[int, str]]:
    """(rank, path) of every published part of ``step``, by rank."""
    prefix = f"{_PART_PREFIX}{step:010d}."
    out = []
    try:
        names = os.listdir(root)
    except FileNotFoundError:
        return []
    for fn in names:
        if fn.startswith(prefix) and fn.endswith(_SUFFIX):
            try:
                out.append((int(fn[len(prefix):-len(_SUFFIX)]),
                            os.path.join(root, fn)))
            except ValueError:
                continue
    return sorted(out)


def _load_part(path: str, expect_world: int | None = None) -> dict:
    with open(path, "rb") as f:
        doc = json.loads(f.read().decode("utf-8"))
    if doc.get("format") != FORMAT:
        raise ManifestError(f"{path}: not a {FORMAT} part")
    if expect_world is not None \
            and int(doc.get("world", -1)) != int(expect_world):
        # a leftover part from a previous life at a DIFFERENT world
        # size (elastic resize) must never merge into this version —
        # its shard pieces were cut for the old partition
        raise ManifestError(
            f"{path}: part written for world {doc.get('world')}, "
            f"merging world {expect_world}")
    payload = doc["payload"]
    crc = zlib.crc32(_canonical(payload)) & 0xFFFFFFFF
    if crc != int(doc.get("crc32", -1)):
        raise ManifestError(f"{path}: part CRC mismatch")
    return payload


def merge_parts(root: str, step: int, world: int,
                meta=None, cleanup: bool = True) -> dict:
    """Rank 0's half of a multi-process commit: merge all ``world``
    parts of ``step`` into one manifest and commit it atomically.
    Every rank must have published its part and no two parts may claim
    the same array — a missing, torn, or CRC-bad part raises
    ManifestError BEFORE anything commits, so a torn multi-host save
    degrades to the previous committed version exactly like a torn
    single-host one. Returns the merged payload."""
    parts = dict(list_parts(root, step))
    missing = [r for r in range(int(world)) if r not in parts]
    if missing:
        raise ManifestError(
            f"step {step}: missing part(s) from rank(s) {missing} "
            f"(found {sorted(parts)})")
    arrays: dict = {}
    merged_meta = {} if meta is None else dict(meta)
    for rank in range(int(world)):
        # raises on torn/corrupt/wrong-world
        payload = _load_part(parts[rank], expect_world=world)
        if int(payload.get("step", -1)) != int(step):
            raise ManifestError(
                f"{parts[rank]}: part claims step {payload.get('step')}"
                f", merging step {step}")
        for name, rec in payload.get("arrays", {}).items():
            if name in arrays:
                raise ManifestError(
                    f"step {step}: array {name!r} published by two "
                    f"ranks — parts must partition the state")
            arrays[name] = rec
        if meta is None and payload.get("meta"):
            merged_meta.update(payload["meta"])
    merged = {"step": int(step), "meta": merged_meta or None,
              "arrays": arrays}
    commit_manifest(root, merged)
    if cleanup:
        for _rank, path in list_parts(root, step):
            try:
                os.unlink(path)
            except OSError:
                pass
    return merged


def load_latest(root: str, step: int | None = None) -> dict:
    """Newest valid manifest (or the exact ``step``). Unreadable or
    CRC-bad files are skipped — restore degrades to the last committed
    step rather than failing on a corrupt newest file."""
    if step is not None:
        return load_manifest(manifest_path(root, step))
    errors = []
    for s, path in reversed(list_manifests(root)):
        try:
            return load_manifest(path)
        except (ManifestError, OSError, ValueError) as e:
            errors.append(f"{path}: {e}")
    raise ManifestError(
        f"no committed checkpoint manifest under {root}"
        + (" (skipped corrupt: " + "; ".join(errors) + ")"
           if errors else ""))
