"""CheckpointStore — crash-consistent, incremental, resharding-aware
array checkpoints.

Save path: every array is laid out as its C-contiguous global byte
stream and cut on a FIXED chunk grid (``chunk_bytes`` boundaries from
byte 0). The grid is a function of the global array only — not of the
mesh/pjit layout it was saved from — so (a) a step that mutates 1% of
the state re-references ~99% of its chunks from the previous manifest
(dedup, never rewritten), and (b) a checkpoint saved under one shard
layout restores under any other (the chunk grid is reassembled for
whatever byte ranges the new layout needs). The manifest rename is the
single commit point (manifest.py); a crash anywhere before it leaves
the previous checkpoint untouched.

Async save: ``save_async`` snapshots HOST COPIES of every array
synchronously (a memcpy, not a disk write) and enqueues them for ONE
persistent background writer thread — the train/decode step never
blocks on chunk IO. The queue holds at most two pending saves: a
cadence the writer keeps up with never blocks at all, and sustained
overload degrades to backpressure (blocking in save_async) instead of
unbounded host-copy memory. Errors surface on ``wait()`` or the next
save.

Restore: ``restore()`` reassembles full arrays; ``restore_shard``
reads ONLY the chunks overlapping one shard's byte range (axis-0
sharding maps to a contiguous byte span of the C order), which is how
a resharded restart avoids reading state it doesn't own.

Retention: the newest ``keep`` manifests survive (env
``PADDLE_TPU_CKPT_KEEP``, default 2 — crash recovery always has the
previous step); retention GC deletes older manifests, then chunks no
retained manifest references.
"""
from __future__ import annotations

import os
import threading
import time
import weakref

import numpy as np

from ..observability import flight as _flight, registry as _obs
from . import manifest as _manifest
from .chunks import ChunkStore

__all__ = ["CheckpointStore", "ShardedArray", "DEFAULT_CHUNK_BYTES"]

DEFAULT_CHUNK_BYTES = 1 << 20

_SAVE_SECONDS = _obs.histogram(
    "paddle_tpu_ckpt_save_seconds",
    "wall time of one checkpoint save (async = writer-thread time)",
    ["mode"])
_RESTORE_SECONDS = _obs.histogram(
    "paddle_tpu_ckpt_restore_seconds",
    "wall time of one checkpoint restore")
_SAVES = _obs.counter(
    "paddle_tpu_ckpt_saves_total",
    "checkpoint saves committed, by mode", ["mode"])

# async-writer queue gauges, evaluated at exposition time over every
# live store (zero hot-path writes): a rising queue depth / pending
# bytes means the train cadence is outrunning the writer (backpressure
# imminent), and a large in-flight save age is a wedged disk — the
# stall signals the watchdog/postmortem tier reads
_STORES: "weakref.WeakSet[CheckpointStore]" = weakref.WeakSet()


def _sum_stores(fn) -> float:
    total = 0.0
    for s in list(_STORES):
        try:
            total += fn(s)
        except Exception:
            pass
    return total


_WRITER_QUEUE_DEPTH = _obs.gauge(
    "paddle_tpu_ckpt_writer_queue_depth",
    "async saves queued for the background writer (live, all stores)")
_WRITER_QUEUE_DEPTH.set_function(lambda: _sum_stores(
    lambda s: s._queue.qsize() if s._queue is not None else 0))
_WRITER_PENDING_BYTES = _obs.gauge(
    "paddle_tpu_ckpt_writer_pending_bytes",
    "host-copy bytes held by queued + in-flight async saves (live)")
_WRITER_PENDING_BYTES.set_function(
    lambda: _sum_stores(lambda s: s._pending_bytes))
_INFLIGHT_SAVE_SECONDS = _obs.gauge(
    "paddle_tpu_ckpt_inflight_save_seconds",
    "age of the oldest in-flight async save write (live; 0 when idle)")
# snapshot _save_started ONCE per store — the writer thread clears it
# concurrently, and a second read racing that clear would be float-None
_INFLIGHT_SAVE_SECONDS.set_function(lambda: max(
    (time.monotonic() - t
     for t in (s._save_started for s in list(_STORES))
     if t is not None), default=0.0))


class ShardedArray:
    """A logically-global array provided as per-shard host pieces
    (axis-0 concatenation order) — the save-side view of a mesh/pjit
    sharded parameter. The store chunks the GLOBAL byte stream, so the
    manifest is identical whatever sharding produced it."""

    def __init__(self, pieces, axis: int = 0):
        if axis != 0:
            raise ValueError("ShardedArray: only axis-0 sharding maps "
                             "to contiguous byte spans; transpose "
                             "before saving for other layouts")
        self.pieces = [np.ascontiguousarray(np.asarray(p))
                       for p in pieces]
        if not self.pieces:
            raise ValueError("ShardedArray needs at least one piece")
        first = self.pieces[0]
        for p in self.pieces[1:]:
            if p.shape[1:] != first.shape[1:] or p.dtype != first.dtype:
                raise ValueError("ShardedArray pieces disagree on "
                                 "trailing shape/dtype")
        self.dtype = first.dtype
        self.shape = (sum(p.shape[0] for p in self.pieces),) \
            + first.shape[1:]
        self.nbytes = sum(p.nbytes for p in self.pieces)

    def iter_bytes(self, chunk_bytes: int):
        """Yield the global byte stream cut on the fixed chunk grid —
        chunks may span piece boundaries (the grid must not depend on
        the sharding). Aligned spans slice straight out of the piece
        (no staging copy — the save path is memory-bandwidth-bound)."""
        buf = bytearray()
        for p in self.pieces:
            if p.nbytes == 0:
                continue
            mv = memoryview(p).cast("B")
            off = 0
            if buf:  # finish the chunk straddling the piece boundary
                take = min(chunk_bytes - len(buf), len(mv))
                buf += mv[:take]
                off = take
                if len(buf) < chunk_bytes:
                    continue
                yield bytes(buf)
                buf.clear()
            while off + chunk_bytes <= len(mv):
                yield mv[off:off + chunk_bytes].tobytes()
                off += chunk_bytes
            if off < len(mv):
                buf += mv[off:]
        if buf:
            yield bytes(buf)


def _stop_writer(q):
    try:
        q.put_nowait(None)
    except Exception:
        pass


def _host_array(x) -> np.ndarray:
    """Materialise any array-like (incl. jax Arrays — device_get) as a
    C-contiguous host ndarray. NOT ascontiguousarray: that promotes
    0-d to 1-d and would lose scalar shapes in the manifest."""
    arr = np.asarray(x)
    if arr.ndim and not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    return arr


class CheckpointStore:
    def __init__(self, root: str, chunk_bytes: int | None = None,
                 keep: int | None = None):
        self.root = root
        env = os.environ.get
        self.chunk_bytes = int(chunk_bytes if chunk_bytes is not None
                               else env("PADDLE_TPU_CKPT_CHUNK_BYTES",
                                        str(DEFAULT_CHUNK_BYTES)))
        if self.chunk_bytes <= 0:
            raise ValueError("chunk_bytes must be positive")
        self.keep = int(keep if keep is not None
                        else env("PADDLE_TPU_CKPT_KEEP", "2"))
        self.chunks = ChunkStore(root)
        self._async_lock = threading.Lock()
        self._async_error: BaseException | None = None
        self._queue: "queue.Queue | None" = None  # lazy writer thread
        self._last_step = 0
        self._pending_bytes = 0          # queued + in-flight host copies
        self._save_started: float | None = None  # writer busy since
        _STORES.add(self)

    # -- save -----------------------------------------------------------
    def _resolve_step(self, step: int | None) -> int:
        """Assign (or fold in an explicit) step number under the lock:
        queued async saves hold steps not yet on disk, and an explicit
        high step must not be shadowed by a later auto-assigned lower
        one (restore() returns the highest committed step)."""
        with self._async_lock:
            if step is None:
                ms = _manifest.list_manifests(self.root)
                on_disk = ms[-1][0] if ms else 0
                self._last_step = max(self._last_step, on_disk) + 1
                return self._last_step
            self._last_step = max(self._last_step, int(step))
            return int(step)

    def _write_state(self, state: dict, step: int, meta, mode: str):
        t0 = time.perf_counter()
        arrays = {}
        for name, val in state.items():
            if isinstance(val, ShardedArray):
                src = val
                dtype, shape, nbytes = val.dtype, val.shape, val.nbytes
            else:
                arr = _host_array(val)
                src = ShardedArray([arr.reshape((-1,) if arr.ndim == 0
                                                else arr.shape)])
                dtype, shape, nbytes = arr.dtype, arr.shape, arr.nbytes
            chunks, off = [], 0
            for piece in src.iter_bytes(self.chunk_bytes):
                chunks.append({"h": self.chunks.put(piece), "o": off,
                               "n": len(piece)})
                off += len(piece)
            arrays[name] = {"dtype": np.dtype(dtype).str,
                            "shape": [int(s) for s in shape],
                            "nbytes": int(nbytes), "chunks": chunks}
        payload = {"step": int(step), "meta": meta, "arrays": arrays}
        _manifest.commit_manifest(self.root, payload)
        self._retention_gc()
        _SAVE_SECONDS.labels(mode=mode).observe(time.perf_counter() - t0)
        _SAVES.labels(mode=mode).inc()
        _flight.record("ckpt", "manifest_commit", step=int(step),
                       mode=mode, arrays=len(arrays),
                       seconds=round(time.perf_counter() - t0, 6))
        return payload

    def save(self, state: dict, step: int | None = None,
             meta=None) -> int:
        """Synchronous save; returns the committed step. ``state`` maps
        name → array-like (numpy / jax, any dtype/shape) or
        ShardedArray. ``meta`` is any JSON-serialisable extra (rides
        the manifest, CRC-covered)."""
        self.wait()  # manifests must commit in step order
        step = self._resolve_step(step)
        self._write_state(dict(state), step, meta, "sync")
        return step

    def save_part(self, state: dict, step: int, rank: int,
                  world: int, meta=None) -> str:
        """One rank's share of a multi-process save: write this rank's
        chunks, then publish a PARTIAL manifest (invisible to
        restore). ``step`` must be agreed across ranks; ``state``
        holds only the arrays this rank owns — ranks must partition
        the state by array name. Rank 0 calls ``merge_parts`` once
        every rank returned to commit the version."""
        arrays = {}
        for name, val in state.items():
            if isinstance(val, ShardedArray):
                src = val
                dtype, shape, nbytes = val.dtype, val.shape, val.nbytes
            else:
                arr = _host_array(val)
                src = ShardedArray([arr.reshape((-1,) if arr.ndim == 0
                                                else arr.shape)])
                dtype, shape, nbytes = arr.dtype, arr.shape, arr.nbytes
            chunks, off = [], 0
            for piece in src.iter_bytes(self.chunk_bytes):
                chunks.append({"h": self.chunks.put(piece), "o": off,
                               "n": len(piece)})
                off += len(piece)
            arrays[name] = {"dtype": np.dtype(dtype).str,
                            "shape": [int(s) for s in shape],
                            "nbytes": int(nbytes), "chunks": chunks}
        payload = {"step": int(step), "meta": meta, "arrays": arrays}
        path = _manifest.commit_part(self.root, payload, rank, world)
        _flight.record("ckpt", "part_commit", step=int(step),
                       rank=int(rank), world=int(world),
                       arrays=len(arrays))
        return path

    def merge_parts(self, step: int, world: int, meta=None) -> int:
        """Rank 0's commit of a multi-process save: merge the
        ``world`` parts of ``step`` into ONE manifest (the commit
        point), then run retention GC. Raises ManifestError (nothing
        commits, previous step stays restorable) if any part is
        missing, torn, or overlaps another rank's arrays."""
        self.wait()  # manifests must commit in step order
        with self._async_lock:
            self._last_step = max(self._last_step, int(step))
        payload = _manifest.merge_parts(self.root, step, world,
                                        meta=meta)
        self._retention_gc()
        _SAVES.labels(mode="merged").inc()
        _flight.record("ckpt", "manifest_commit", step=int(step),
                       mode="merged", arrays=len(payload["arrays"]))
        return int(step)

    def _merge_when_ready(self, step: int, world: int, meta,
                          timeout: float) -> int:
        """Poll for all ``world`` parts of ``step``, then merge-commit.
        Unlike ``merge_parts`` this never calls ``wait()`` — it is the
        writer-thread body of ``merge_parts_async`` (queue FIFO already
        orders it after this store's own part write). A timeout leaves
        the parts uncommitted: restore degrades to the previous
        manifest bit-for-bit."""
        deadline = time.monotonic() + max(float(timeout), 0.0)
        while True:
            present = len(_manifest.list_parts(self.root, step))
            if present >= world:
                break
            if time.monotonic() >= deadline:
                raise _manifest.ManifestError(
                    f"merge step {step}: only {present}/{world} parts "
                    f"after {timeout}s — previous manifest stays the "
                    "restore target")
            time.sleep(0.02)
        with self._async_lock:
            self._last_step = max(self._last_step, int(step))
        payload = _manifest.merge_parts(self.root, step, world,
                                        meta=meta)
        self._retention_gc()
        _SAVES.labels(mode="merged").inc()
        _flight.record("ckpt", "manifest_commit", step=int(step),
                       mode="merged", arrays=len(payload["arrays"]))
        return int(step)

    def _writer_loop(self, q):
        while True:
            item = q.get()
            if item is None:
                q.task_done()
                return
            kind, step, nbytes = item["kind"], item["step"], \
                item["nbytes"]
            self._save_started = time.monotonic()
            _flight.record("ckpt", "write_start", step=step,
                           bytes=nbytes, queued=q.qsize(), kind=kind)
            try:
                if kind == "full":
                    self._write_state(item["host"], step, item["meta"],
                                      "async")
                elif kind == "part":
                    self.save_part(item["host"], step, item["rank"],
                                   item["world"], meta=item["meta"])
                elif kind == "merge":
                    self._merge_when_ready(step, item["world"],
                                           item["meta"],
                                           item["timeout"])
                else:  # pragma: no cover - enqueue sites are in-file
                    raise ValueError(f"unknown writer item {kind!r}")
            except BaseException as e:  # surfaced on wait()/next save
                with self._async_lock:
                    self._async_error = e
                _flight.record("ckpt", "write_error", step=step,
                               kind=kind,
                               error=f"{type(e).__name__}: {e}")
            else:
                _flight.record(
                    "ckpt", "write_done", step=step, bytes=nbytes,
                    kind=kind,
                    seconds=round(
                        time.monotonic() - self._save_started, 6))
            finally:
                self._save_started = None
                with self._async_lock:
                    self._pending_bytes -= nbytes
                q.task_done()

    def _ensure_writer(self):
        """Start (once) the persistent background writer; re-raise any
        error the previous async item left behind."""
        with self._async_lock:
            err, self._async_error = self._async_error, None
            if self._queue is None:
                import queue as _queue
                self._queue = _queue.Queue(maxsize=2)
                t = threading.Thread(target=self._writer_loop,
                                     args=(self._queue,), daemon=True,
                                     name="ckpt-writer")
                t.start()
                # the writer loop must not outlive the store (daemon
                # thread regardless, so a full queue at GC just leaves
                # it to die with the process)
                import weakref
                weakref.finalize(self, _stop_writer, self._queue)
        if err is not None:
            raise err

    def _host_copy(self, state: dict) -> tuple[dict, int]:
        host = {}
        for name, val in state.items():
            if isinstance(val, ShardedArray):
                # pieces are host copies already (ctor asarray), but
                # guard aliasing with the training loop's buffers
                host[name] = ShardedArray(
                    [np.array(p, copy=True) for p in val.pieces])
            else:
                host[name] = np.array(_host_array(val), copy=True)
        return host, int(sum(v.nbytes for v in host.values()))

    def _enqueue(self, item: dict):
        with self._async_lock:
            self._pending_bytes += item["nbytes"]
        _flight.record("ckpt", "enqueue", step=item["step"],
                       bytes=item["nbytes"], kind=item["kind"],
                       queued=self._queue.qsize())
        self._queue.put(item)

    def save_async(self, state: dict, step: int | None = None,
                   meta=None) -> int:
        """Non-blocking save: host copies are taken NOW (so the caller
        may keep mutating/donating its arrays); chunk+manifest IO runs
        on a persistent background writer. Blocks only when TWO saves
        are already pending (backpressure — bounded host-copy memory).
        Returns the step that WILL commit; ``wait()`` (or the next
        save) surfaces writer errors."""
        self._ensure_writer()
        step = self._resolve_step(step)
        host, nbytes = self._host_copy(state)
        self._enqueue({"kind": "full", "host": host, "step": step,
                       "meta": meta, "nbytes": nbytes})
        return step

    def save_part_async(self, state: dict, step: int, rank: int,
                        world: int, meta=None) -> int:
        """``save_part`` off the step path: host copies now, partial
        manifest published by the background writer. Same backpressure
        and error-surfacing contract as ``save_async``. Nothing
        becomes restorable until rank 0 merges."""
        self._ensure_writer()
        with self._async_lock:
            self._last_step = max(self._last_step, int(step))
        host, nbytes = self._host_copy(state)
        self._enqueue({"kind": "part", "host": host, "step": int(step),
                       "rank": int(rank), "world": int(world),
                       "meta": meta, "nbytes": nbytes})
        return int(step)

    def merge_parts_async(self, step: int, world: int, meta=None,
                          timeout: float = 60.0) -> int:
        """Rank 0's asynchronous commit of a multi-process save: the
        background writer waits (up to ``timeout`` seconds) for all
        ``world`` parts of ``step`` then merge-commits. Queue FIFO
        guarantees this rank's own part lands first. On timeout the
        ManifestError surfaces on ``wait()``/next save and the
        PREVIOUS manifest remains the restore target bit-for-bit."""
        self._ensure_writer()
        self._enqueue({"kind": "merge", "step": int(step),
                       "world": int(world), "meta": meta,
                       "timeout": float(timeout), "nbytes": 0})
        return int(step)

    def wait(self):
        """Drain pending async saves and re-raise any writer error."""
        with self._async_lock:
            q = self._queue
        if q is not None:
            q.join()
        with self._async_lock:
            err, self._async_error = self._async_error, None
        if err is not None:
            raise err

    # -- retention ------------------------------------------------------
    def _retention_gc(self):
        if self.keep <= 0:
            return
        ms = _manifest.list_manifests(self.root)
        drop, hold = ms[:-self.keep], ms[-self.keep:]
        if not drop:
            return
        live: set[str] = set()
        for _s, path in hold:
            try:
                payload = _manifest.load_manifest(path)
            except _manifest.ManifestError:
                continue
            for ent in payload["arrays"].values():
                live.update(c["h"] for c in ent["chunks"])
        for _s, path in drop:
            try:
                os.unlink(path)
            except OSError:
                pass
        self.chunks.gc(live)

    # -- restore --------------------------------------------------------
    def latest_manifest(self, step: int | None = None) -> dict:
        return _manifest.load_latest(self.root, step)

    def restore(self, step: int | None = None,
                names=None) -> tuple[dict, object]:
        """(arrays, meta) of the newest committed step (or ``step``).
        ``names`` restricts to a subset without reading the rest."""
        t0 = time.perf_counter()
        payload = self.latest_manifest(step)
        out = {}
        for name, ent in payload["arrays"].items():
            if names is not None and name not in names:
                continue
            out[name] = self._assemble(ent)
        _RESTORE_SECONDS.observe(time.perf_counter() - t0)
        return out, payload.get("meta")

    def _read_range(self, ent: dict, lo: int, hi: int) -> bytes:
        """Bytes [lo, hi) of an array's global stream, reading only the
        chunks that overlap."""
        parts = []
        for c in ent["chunks"]:
            co, cn = int(c["o"]), int(c["n"])
            if co + cn <= lo or co >= hi:
                continue
            data = self.chunks.get(c["h"])
            if len(data) != cn:
                from .chunks import ChunkError
                raise ChunkError(
                    f"chunk {c['h']} length {len(data)} != manifest "
                    f"{cn}")
            parts.append(data[max(lo - co, 0):min(hi - co, cn)])
        blob = b"".join(parts)
        if len(blob) != hi - lo:
            from .chunks import ChunkError
            raise ChunkError(
                f"array bytes [{lo},{hi}) incomplete: got {len(blob)}")
        return blob

    def _assemble(self, ent: dict) -> np.ndarray:
        blob = self._read_range(ent, 0, int(ent["nbytes"]))
        return np.frombuffer(blob, dtype=np.dtype(ent["dtype"])) \
            .reshape(tuple(ent["shape"])).copy()

    def restore_array(self, name: str, step: int | None = None) \
            -> np.ndarray:
        payload = self.latest_manifest(step)
        return self._assemble(payload["arrays"][name])

    def materialize(self, ent: dict) -> np.ndarray:
        """Assemble one manifest ``arrays`` entry (as returned by
        ``latest_manifest``) into an ndarray — the entry-level restore
        primitive for layers that walk a manifest once and read many
        arrays (cluster_ckpt's resize path)."""
        return self._assemble(ent)

    def read_rows(self, ent: dict, row_lo: int, row_hi: int) \
            -> np.ndarray:
        """Axis-0 rows [row_lo, row_hi) of one manifest entry, reading
        only the chunks overlapping that byte span. Scalars cannot be
        row-addressed."""
        shape = tuple(ent["shape"])
        if not shape:
            raise ValueError("read_rows: scalar entries have no rows")
        dtype = np.dtype(ent["dtype"])
        row_bytes = dtype.itemsize * int(np.prod(shape[1:],
                                                 dtype=np.int64))
        if not 0 <= row_lo <= row_hi <= shape[0]:
            raise ValueError(
                f"read_rows: [{row_lo},{row_hi}) outside [0,{shape[0]}]")
        if row_lo == row_hi:
            return np.empty((0,) + shape[1:], dtype=dtype)
        blob = self._read_range(ent, row_lo * row_bytes,
                                row_hi * row_bytes)
        return np.frombuffer(blob, dtype=dtype) \
            .reshape((row_hi - row_lo,) + shape[1:]).copy()

    def restore_shard(self, name: str, shard: int, num_shards: int,
                      step: int | None = None) -> np.ndarray:
        """Shard ``shard`` of ``num_shards`` of axis 0 (np.array_split
        partition — uneven leading dims round-robin the remainder),
        reading only the overlapping chunks. This is the resharding
        path: the saved layout is irrelevant, only the chunk grid
        matters."""
        if not 0 <= shard < num_shards:
            raise ValueError(f"shard {shard} outside [0, {num_shards})")
        payload = self.latest_manifest(step)
        ent = payload["arrays"][name]
        shape = tuple(ent["shape"])
        if not shape:
            raise ValueError(f"{name} is a scalar — nothing to shard")
        dtype = np.dtype(ent["dtype"])
        row_bytes = dtype.itemsize * int(np.prod(shape[1:], dtype=np.int64))
        n = shape[0]
        base, rem = divmod(n, num_shards)
        r0 = shard * base + min(shard, rem)
        rows = base + (1 if shard < rem else 0)
        blob = self._read_range(ent, r0 * row_bytes,
                                (r0 + rows) * row_bytes)
        return np.frombuffer(blob, dtype=dtype) \
            .reshape((rows,) + shape[1:]).copy()

    def steps(self) -> list[int]:
        return [s for s, _p in _manifest.list_manifests(self.root)]

    @staticmethod
    def exists(root: str) -> bool:
        """Is there a committed checkpoint under ``root``?"""
        return bool(_manifest.list_manifests(root))
