"""Top-level framework helpers (reference python/paddle/framework/)."""
from __future__ import annotations

import os
import pickle

import numpy as np

__all__ = ["get_default_dtype", "set_default_dtype", "seed", "save", "load",
           "set_device", "get_device", "DataParallel", "set_grad_enabled",
           "is_grad_enabled", "summary", "flops"]

_default_dtype = "float32"


def get_default_dtype():
    return _default_dtype


def set_default_dtype(d):
    global _default_dtype
    from .fluid import core
    _default_dtype = core.convert_dtype(d)


def seed(s: int):
    np.random.seed(s)
    from .fluid import framework
    tr = framework._dygraph_tracer()
    if tr is not None:
        tr.seed(int(s))
    from .fluid.framework import default_main_program, default_startup_program
    default_main_program().random_seed = int(s)
    default_startup_program().random_seed = int(s)
    return s


def save(obj, path, protocol=4):
    """paddle.save — state dicts / tensors / pytrees of arrays."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def conv(o):
        if hasattr(o, "numpy"):
            return o.numpy()
        if isinstance(o, dict):
            return {k: conv(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return type(o)(conv(v) for v in o)
        return o
    with open(path, "wb") as f:
        pickle.dump(conv(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        return pickle.load(f)


def set_device(device: str):
    os.environ["PADDLE_DEVICE"] = device
    return device


def get_device() -> str:
    import jax
    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


from .distributed.parallel import DataParallel  # noqa: E402


import contextlib


@contextlib.contextmanager
def set_grad_enabled(mode: bool):
    from .fluid.dygraph.tracer import no_grad_guard
    if mode:
        yield
    else:
        with no_grad_guard():
            yield


def is_grad_enabled():
    from .fluid import framework
    tr = framework._dygraph_tracer()
    return tr is None or tr._has_grad


def summary(net, input_size=None, dtypes=None, input=None):
    """Model summary (reference hapi/model_summary.py)."""
    from .hapi.summary import summary as _hapi_summary
    return _hapi_summary(net, input_size, dtypes)


def flops(net, input_size, custom_ops=None, print_detail=False):
    return 0
