"""Pallas TPU epilogue-fused transformer decoder sub-blocks (CODA style).

The remaining fusion headroom after the flash/FFN/LN kernels is the
sub-block SEAMS: the attention out-projection's result and the FFN's
result each take an HBM round trip before their residual-add and
layernorm. This module rewrites both sub-blocks as GEMM-epilogue
programs (CODA, arxiv 2605.19269 — the epilogue rides the MXU pipeline
for free; the XLA fusion study 2301.13062 documents XLA declining
exactly these cross-op fusions):

  fused_out_ln    z = res + dropout_p(a @ W + b);  h = LN(z)*s + ln_b
                  — the attention-out projection GEMM whose epilogue
                  carries bias + dropout + residual-add + layernorm,
                  emitting BOTH the new residual stream z and the
                  normalised h (pre-LN blocks feed h to the FFN; post-LN
                  blocks use h as the sub-block output).

  fused_ffn_ln    out = [LN]( res + dropout_p( act(x' @ W1 + b1) @ W2
                  + b2 ) ) with x' = LN(x) when norm="pre" —
                  the whole FFN sub-block as one GEMM-pair program: the
                  4H intermediate stays in VMEM (pallas_ffn lineage) and
                  the epilogue carries bias + activation + dropout +
                  residual + (pre|post)norm.

Both carry custom VJPs (rematerialising backward: save only primal
inputs, grads via one composed-XLA recompute with the dropout mask
REPLAYED from the counter hash — no mask tensor ever exists in HBM), so
the fused paths hold on the training hot path. Both are gated through
ops/autobench.prefer: on TPU the Pallas program must measurably beat
the composed XLA chain per shape (and the decision persists in the
tuning cache); off-TPU only the interpret-mode opt-in runs them.

Ragged rows: the row dimension is padded to the block size inside the
wrappers (padded rows are dead lanes sliced off on exit), so
non-multiple-of-block token counts (ragged serving batches, odd
sequence lengths) stay on the fused path instead of falling back.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import autobench
from .pallas_attention import on_tpu
from .pallas_ffn import _ACTS, _CompilerParams, _vmem_budget
from .pallas_fused_residual import _ids, _keep

__all__ = ["fused_out_ln", "can_use_fused_out_ln", "out_ln_wins",
           "out_ln_reference", "fused_ffn_ln", "can_use_fused_ffn_ln",
           "ffn_ln_wins", "ffn_ln_reference"]


def _interpret() -> bool:
    return not on_tpu()


def _seed_spec():
    """(1,) int32 seed: SMEM on TPU; a plain block in interpret mode
    (2-D grid variant of pallas_fused_residual._smem_seed_spec)."""
    if _interpret():
        return pl.BlockSpec((1,), lambda mi, j: (0,))
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _pad_rows(x2, m_pad: int):
    m = x2.shape[0]
    return x2 if m == m_pad else jnp.pad(x2, ((0, m_pad - m), (0, 0)))


def _padded_m(m: int) -> int:
    return -(-m // 128) * 128


def _row_block(m_pad: int) -> int:
    for bm in (512, 256, 128):
        if m_pad % bm == 0:
            return bm
    return 128


def _keep_full(seed_arr, m: int, c: int, p: float):
    """Full-grid dropout mask replay for the composed backward — same
    counter hash over the same global element ids as the kernel."""
    rows = jnp.broadcast_to(jnp.arange(m, dtype=jnp.int32)[:, None],
                            (m, c))
    cols = jnp.broadcast_to(jnp.arange(c, dtype=jnp.int32)[None, :],
                            (m, c))
    return _keep(seed_arr, rows, cols, c, p)


# f32 activations for the composed reference/backward (the in-kernel
# erf-poly gelu differs from lax.erf by <1.5e-7 — inside every caller's
# tolerance; gelu_tanh and relu are bit-identical formulas)
_REF_ACTS = {
    "gelu": lambda v: jax.nn.gelu(v, approximate=False),
    "gelu_tanh": lambda v: jax.nn.gelu(v, approximate=True),
    "relu": jax.nn.relu,
}


def _ln_f32(z, scale, bias, eps):
    mean = jnp.mean(z, -1, keepdims=True)
    var = jnp.mean(jnp.square(z - mean), -1, keepdims=True)
    return (z - mean) * jax.lax.rsqrt(var + eps) * scale + bias


# ---------------------------------------------------------------------------
# fused_out_ln: GEMM + bias + dropout + residual + LN, one program
# ---------------------------------------------------------------------------

def _pick_out_blocks(m_pad: int, din: int, dout: int,
                     itemsize: int) -> tuple[int, int] | None:
    """(bm, bk) whose VMEM working set fits: f32 (bm, dout) accumulator
    + double-buffered a/w/b/res/ln/z/h blocks."""
    budget = _vmem_budget()
    bm0 = _row_block(m_pad)
    for bm in (512, 256, 128):
        if bm > bm0 or m_pad % bm:
            continue
        for bk in (512, 256, 128):
            if din % bk:
                continue
            scratch = bm * dout * 4
            blocks = 2 * itemsize * (bm * bk        # a block
                                     + bk * dout    # w block
                                     + 3 * dout     # bias, ln scale/bias
                                     + bm * dout    # residual block
                                     + 2 * bm * dout)  # z + h out blocks
            if scratch + blocks <= budget:
                return bm, bk
    return None


def can_use_fused_out_ln(m: int, din: int, dout: int,
                         itemsize: int = 4) -> bool:
    if os.environ.get("PADDLE_TPU_DISABLE_PALLAS"):
        return False
    if os.environ.get("PADDLE_TPU_DISABLE_BLOCK_FUSION"):
        return False
    if not (on_tpu() or os.environ.get("PADDLE_TPU_PALLAS_INTERPRET")):
        return False
    if din % 128 or dout % 128 or dout > 4096 or m < 1:
        return False
    return _pick_out_blocks(_padded_m(m), din, dout, itemsize) is not None


def _out_ln_kernel(seed_ref, a_ref, w_ref, b_ref, res_ref, s_ref, lb_ref,
                   z_ref, h_ref, acc_ref, *, n_k, eps, p):
    mi = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jnp.dot(a_ref[...], w_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(j == n_k - 1)
    def _epilogue():
        y = acc_ref[...] + b_ref[0].astype(jnp.float32)[None, :]
        bm, c = y.shape
        if p > 0.0:
            rows, cols = _ids(mi, bm, c)
            keep = _keep(seed_ref, rows, cols, c, p)
            y = jnp.where(keep, y / (1.0 - p), 0.0)
        z = y + res_ref[...].astype(jnp.float32)
        h = _ln_f32(z, s_ref[0].astype(jnp.float32)[None, :],
                    lb_ref[0].astype(jnp.float32)[None, :], eps)
        z_ref[...] = z.astype(z_ref.dtype)
        h_ref[...] = h.astype(h_ref.dtype)


def _out_ln_pallas(a2, w, b, res2, ln_s, ln_b, seed_arr, p, eps,
                   bm, bk, m_pad):
    din, dout = w.shape
    a2p = _pad_rows(a2, m_pad)
    resp = _pad_rows(res2, m_pad)
    n_k = din // bk
    z, h = pl.pallas_call(
        functools.partial(_out_ln_kernel, n_k=n_k, eps=eps, p=p),
        grid=(m_pad // bm, n_k),
        in_specs=[
            _seed_spec(),
            pl.BlockSpec((bm, bk), lambda mi, j: (mi, j)),
            pl.BlockSpec((bk, dout), lambda mi, j: (j, 0)),
            pl.BlockSpec((1, dout), lambda mi, j: (0, 0)),
            pl.BlockSpec((bm, dout), lambda mi, j: (mi, 0)),
            pl.BlockSpec((1, dout), lambda mi, j: (0, 0)),
            pl.BlockSpec((1, dout), lambda mi, j: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, dout), lambda mi, j: (mi, 0)),
            pl.BlockSpec((bm, dout), lambda mi, j: (mi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m_pad, dout), res2.dtype),
            jax.ShapeDtypeStruct((m_pad, dout), a2.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bm, dout), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(seed_arr, a2p, w, b.reshape(1, dout), resp,
      ln_s.reshape(1, dout), ln_b.reshape(1, dout))
    m = a2.shape[0]
    return z[:m], h[:m]


def out_ln_reference(a2, w, b, res2, ln_s, ln_b, seed_arr, p, eps):
    """Composed-XLA chain with identical semantics (fallback, autobench
    candidate, and the parity-test reference)."""
    y = (a2.astype(jnp.float32) @ w.astype(jnp.float32)
         + b.astype(jnp.float32))
    if p > 0.0:
        keep = _keep_full(seed_arr, y.shape[0], y.shape[1], p)
        y = jnp.where(keep, y / (1.0 - p), 0.0)
    z = y + res2.astype(jnp.float32)
    h = _ln_f32(z, ln_s.astype(jnp.float32), ln_b.astype(jnp.float32),
                eps)
    return z.astype(res2.dtype), h.astype(a2.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def fused_out_ln(a2, w, b, res2, ln_s, ln_b, seed_arr, p=0.0, eps=1e-5):
    """a2 (M, Din) @ w (Din, Dout) + b, dropout_p, + res2, layernorm.

    Returns (z, h): z (M, Dout) in res2.dtype is the new residual
    stream; h in a2.dtype is LN(z)*ln_s + ln_b. seed_arr: (1,) int32
    (no gradient); p/eps static."""
    return _out_ln_fwd(a2, w, b, res2, ln_s, ln_b, seed_arr, p, eps)[0]


def _out_ln_impl(a2, w, b, res2, ln_s, ln_b, seed_arr, p, eps):
    m = a2.shape[0]
    din, dout = w.shape
    m_pad = _padded_m(m)
    blocks = _pick_out_blocks(m_pad, din, dout, a2.dtype.itemsize)
    if blocks is None:
        return out_ln_reference(a2, w, b, res2, ln_s, ln_b, seed_arr, p,
                                eps)
    return _out_ln_pallas(a2, w, b, res2, ln_s, ln_b, seed_arr, p, eps,
                          *blocks, m_pad)


def _out_ln_fwd(a2, w, b, res2, ln_s, ln_b, seed_arr, p, eps):
    zh = _out_ln_impl(a2, w, b, res2, ln_s, ln_b, seed_arr, p, eps)
    return zh, (a2, w, b, res2, ln_s, ln_b, seed_arr)


def _out_ln_bwd(p, eps, saved, cots):
    a2, w, b, res2, ln_s, ln_b, seed_arr = saved
    dz, dh = cots

    def chain(a2f, wf, bf, resf, sf, lbf):
        z, h = out_ln_reference(
            a2f, wf, bf, resf, sf, lbf, seed_arr, p, eps)
        return z.astype(jnp.float32), h.astype(jnp.float32)

    _, vjp = jax.vjp(chain, a2.astype(jnp.float32),
                     w.astype(jnp.float32), b.astype(jnp.float32),
                     res2.astype(jnp.float32), ln_s.astype(jnp.float32),
                     ln_b.astype(jnp.float32))
    da, dw, db, dres, ds, dlb = vjp((dz.astype(jnp.float32),
                                     dh.astype(jnp.float32)))
    return (da.astype(a2.dtype), dw.astype(w.dtype), db.astype(b.dtype),
            dres.astype(res2.dtype), ds.astype(ln_s.dtype),
            dlb.astype(ln_b.dtype), None)


fused_out_ln.defvjp(_out_ln_fwd, _out_ln_bwd)


# ---------------------------------------------------------------------------
# fused_ffn_ln: (pre)norm + GEMM + act + GEMM + bias + dropout +
# residual (+ postnorm), one program
# ---------------------------------------------------------------------------

def _pick_ffn_blocks(m_pad: int, h: int, i: int, itemsize: int,
                     prenorm: bool) -> tuple[int, int] | None:
    budget = _vmem_budget()
    bm0 = _row_block(m_pad)
    for bm in (512, 256, 128):
        if bm > bm0 or m_pad % bm:
            continue
        for bi in (512, 256, 128):
            if i % bi:
                continue
            scratch = bm * h * 4 \
                + (bm * h * itemsize if prenorm else 0)
            blocks = 2 * itemsize * (bm * h          # x block
                                     + h * bi + bi   # W1, b1
                                     + bi * h + h    # W2, b2
                                     + bm * h        # residual block
                                     + 2 * h         # ln scale/bias
                                     + bm * h)       # out block
            if scratch + blocks <= budget:
                return bm, bi
    return None


def can_use_fused_ffn_ln(m: int, h: int, i: int, itemsize: int = 4,
                         prenorm: bool = False) -> bool:
    if os.environ.get("PADDLE_TPU_DISABLE_PALLAS"):
        return False
    if os.environ.get("PADDLE_TPU_DISABLE_BLOCK_FUSION"):
        return False
    if not (on_tpu() or os.environ.get("PADDLE_TPU_PALLAS_INTERPRET")):
        return False
    if h % 128 or i % 128 or h > 4096 or m < 1:
        return False
    return _pick_ffn_blocks(_padded_m(m), h, i, itemsize,
                            prenorm) is not None


def _ffn_ln_kernel(seed_ref, x_ref, w1_ref, b1_ref, w2_ref, b2_ref,
                   res_ref, s_ref, lb_ref, o_ref, acc_ref, xn_ref, *,
                   act, n_i, norm, eps, p):
    mi = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        if norm == "pre":
            xn = _ln_f32(x_ref[...].astype(jnp.float32),
                         s_ref[0].astype(jnp.float32)[None, :],
                         lb_ref[0].astype(jnp.float32)[None, :], eps)
            xn_ref[...] = xn.astype(xn_ref.dtype)

    src = xn_ref[...] if norm == "pre" else x_ref[...]
    a = jnp.dot(src, w1_ref[...],
                preferred_element_type=jnp.float32) + b1_ref[...]
    hid = act(a).astype(x_ref.dtype)
    acc_ref[...] += jnp.dot(hid, w2_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(j == n_i - 1)
    def _epilogue():
        y = acc_ref[...] + b2_ref[0].astype(jnp.float32)[None, :]
        bm, c = y.shape
        if p > 0.0:
            rows, cols = _ids(mi, bm, c)
            keep = _keep(seed_ref, rows, cols, c, p)
            y = jnp.where(keep, y / (1.0 - p), 0.0)
        z = y + res_ref[...].astype(jnp.float32)
        if norm == "post":
            z = _ln_f32(z, s_ref[0].astype(jnp.float32)[None, :],
                        lb_ref[0].astype(jnp.float32)[None, :], eps)
        o_ref[...] = z.astype(o_ref.dtype)


def _ffn_ln_pallas(x2, w1, b1, w2, b2, res2, ln_s, ln_b, seed_arr, act,
                   norm, p, eps, bm, bi, m_pad):
    h = x2.shape[1]
    i = w1.shape[1]
    n_i = i // bi
    x2p = _pad_rows(x2, m_pad)
    resp = _pad_rows(res2, m_pad)
    scratch = [pltpu.VMEM((bm, h), jnp.float32)]
    scratch.append(pltpu.VMEM((bm, h), x2.dtype) if norm == "pre"
                   else pltpu.VMEM((1, 128), x2.dtype))
    out = pl.pallas_call(
        functools.partial(_ffn_ln_kernel, act=_ACTS[act], n_i=n_i,
                          norm=norm, eps=eps, p=p),
        grid=(m_pad // bm, n_i),
        in_specs=[
            _seed_spec(),
            pl.BlockSpec((bm, h), lambda mi, j: (mi, 0)),
            pl.BlockSpec((h, bi), lambda mi, j: (0, j)),
            pl.BlockSpec((1, bi), lambda mi, j: (0, j)),
            pl.BlockSpec((bi, h), lambda mi, j: (j, 0)),
            pl.BlockSpec((1, h), lambda mi, j: (0, 0)),
            pl.BlockSpec((bm, h), lambda mi, j: (mi, 0)),
            pl.BlockSpec((1, h), lambda mi, j: (0, 0)),
            pl.BlockSpec((1, h), lambda mi, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, h), lambda mi, j: (mi, 0)),
        out_shape=jax.ShapeDtypeStruct((m_pad, h), res2.dtype),
        scratch_shapes=scratch,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(seed_arr, x2p, w1, b1.reshape(1, i), w2, b2.reshape(1, h), resp,
      ln_s.reshape(1, h), ln_b.reshape(1, h))
    return out[:x2.shape[0]]


def ffn_ln_reference(x2, w1, b1, w2, b2, res2, ln_s, ln_b, seed_arr,
                     act, norm, p, eps):
    """Composed-XLA chain with identical semantics."""
    sf = ln_s.astype(jnp.float32)
    lbf = ln_b.astype(jnp.float32)
    src = x2.astype(jnp.float32)
    if norm == "pre":
        src = _ln_f32(src, sf, lbf, eps)
    hid = _REF_ACTS[act](src @ w1.astype(jnp.float32)
                         + b1.astype(jnp.float32))
    y = hid @ w2.astype(jnp.float32) + b2.astype(jnp.float32)
    if p > 0.0:
        keep = _keep_full(seed_arr, y.shape[0], y.shape[1], p)
        y = jnp.where(keep, y / (1.0 - p), 0.0)
    z = y + res2.astype(jnp.float32)
    if norm == "post":
        z = _ln_f32(z, sf, lbf, eps)
    return z.astype(res2.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 10, 11, 12))
def fused_ffn_ln(x2, w1, b1, w2, b2, res2, ln_s, ln_b, seed_arr,
                 act="gelu", norm="none", p=0.0, eps=1e-5):
    """The FFN sub-block as one GEMM-epilogue program.

    out = [LN]( res2 + dropout_p( act(x' @ w1 + b1) @ w2 + b2 ) ) with
    x' = LN(x2) for norm="pre" (pre-LN blocks pass res2 == x2), plain
    x2 for norm="none"/"post"; norm="post" applies the LN to the summed
    output (post-LN encoders). ln_s/ln_b are ignored for norm="none"
    (pass ones/zeros). seed_arr: (1,) int32; act/norm/p/eps static."""
    return _ffn_ln_fwd(x2, w1, b1, w2, b2, res2, ln_s, ln_b, seed_arr,
                       act, norm, p, eps)[0]


def _ffn_ln_impl(x2, w1, b1, w2, b2, res2, ln_s, ln_b, seed_arr, act,
                 norm, p, eps):
    m, h = x2.shape
    i = w1.shape[1]
    m_pad = _padded_m(m)
    blocks = _pick_ffn_blocks(m_pad, h, i, x2.dtype.itemsize,
                              norm == "pre")
    if blocks is None:
        return ffn_ln_reference(x2, w1, b1, w2, b2, res2, ln_s, ln_b,
                                seed_arr, act, norm, p, eps)
    return _ffn_ln_pallas(x2, w1, b1, w2, b2, res2, ln_s, ln_b,
                          seed_arr, act, norm, p, eps, *blocks, m_pad)


def _ffn_ln_fwd(x2, w1, b1, w2, b2, res2, ln_s, ln_b, seed_arr, act,
                norm, p, eps):
    out = _ffn_ln_impl(x2, w1, b1, w2, b2, res2, ln_s, ln_b, seed_arr,
                       act, norm, p, eps)
    return out, (x2, w1, b1, w2, b2, res2, ln_s, ln_b, seed_arr)


def _ffn_ln_bwd(act, norm, p, eps, saved, dy):
    x2, w1, b1, w2, b2, res2, ln_s, ln_b, seed_arr = saved

    def chain(x2f, w1f, b1f, w2f, b2f, resf, sf, lbf):
        return ffn_ln_reference(x2f, w1f, b1f, w2f, b2f, resf, sf, lbf,
                                seed_arr, act, norm, p,
                                eps).astype(jnp.float32)

    _, vjp = jax.vjp(chain, x2.astype(jnp.float32),
                     w1.astype(jnp.float32), b1.astype(jnp.float32),
                     w2.astype(jnp.float32), b2.astype(jnp.float32),
                     res2.astype(jnp.float32), ln_s.astype(jnp.float32),
                     ln_b.astype(jnp.float32))
    dx, dw1, db1, dw2, db2, dres, ds, dlb = vjp(dy.astype(jnp.float32))
    return (dx.astype(x2.dtype), dw1.astype(w1.dtype),
            db1.astype(b1.dtype), dw2.astype(w2.dtype),
            db2.astype(b2.dtype), dres.astype(res2.dtype),
            ds.astype(ln_s.dtype), dlb.astype(ln_b.dtype), None)


fused_ffn_ln.defvjp(_ffn_ln_fwd, _ffn_ln_bwd)


# ---------------------------------------------------------------------------
# autobench gates + warmers (gate-then-cache flow, docs/KERNELS.md)
# ---------------------------------------------------------------------------

def _rand2(rng, m, n, dtype):
    return jnp.asarray(rng.randn(m, n) * 0.05, dtype)


def _gate_out_ln(m, din, dout, dtype, p=0.0, eps=1e-5):
    import numpy as np
    dtype = jnp.dtype(dtype)
    key = ("fused_out_ln", m, din, dout, str(dtype), round(p, 4))

    def make_args():
        rng = np.random.RandomState(0)
        return (_rand2(rng, m, din, dtype), _rand2(rng, din, dout, dtype),
                _rand2(rng, 1, dout, dtype)[0], _rand2(rng, m, dout, dtype),
                jnp.ones((dout,), jnp.float32),
                jnp.zeros((dout,), jnp.float32),
                jnp.zeros((1,), jnp.int32))

    cands = {
        "pallas": lambda *a: fused_out_ln(*a, p, eps),
        "xla": lambda *a: out_ln_reference(*a, p, eps),
    }
    return key, cands, make_args


def out_ln_wins(m, din, dout, dtype, p=0.0, eps=1e-5) -> bool:
    """Autobench gate: on TPU the fused program must beat the composed
    chain at this shape (decision persisted via the tuning cache);
    off-TPU the interpret-mode opt-in that passed can_use runs it."""
    if not on_tpu():
        return True
    key, cands, make_args = _gate_out_ln(m, din, dout, dtype, p, eps)
    return autobench.prefer(key, cands, make_args,
                            default="pallas") == "pallas"


def _gate_ffn_ln(m, h, i, dtype, act, norm, p=0.0, eps=1e-5):
    import numpy as np
    dtype = jnp.dtype(dtype)
    key = ("fused_ffn_ln", m, h, i, str(dtype), act, norm, round(p, 4))

    def make_args():
        rng = np.random.RandomState(0)
        return (_rand2(rng, m, h, dtype), _rand2(rng, h, i, dtype),
                _rand2(rng, 1, i, dtype)[0], _rand2(rng, i, h, dtype),
                _rand2(rng, 1, h, dtype)[0], _rand2(rng, m, h, dtype),
                jnp.ones((h,), jnp.float32), jnp.zeros((h,), jnp.float32),
                jnp.zeros((1,), jnp.int32))

    cands = {
        "pallas": lambda *a: fused_ffn_ln(*a, act, norm, p, eps),
        "xla": lambda *a: ffn_ln_reference(*a, act, norm, p, eps),
    }
    return key, cands, make_args


def ffn_ln_wins(m, h, i, dtype, act, norm, p=0.0, eps=1e-5) -> bool:
    if not on_tpu():
        return True
    key, cands, make_args = _gate_ffn_ln(m, h, i, dtype, act, norm, p,
                                         eps)
    return autobench.prefer(key, cands, make_args,
                            default="pallas") == "pallas"


def _warm_out_ln(spec: dict) -> str:
    key, cands, make_args = _gate_out_ln(
        int(spec["m"]), int(spec["din"]), int(spec["dout"]),
        spec.get("dtype", "bfloat16"), float(spec.get("p", 0.0)))
    return autobench.prefer(key, cands, make_args, default="pallas")


def _warm_ffn_ln(spec: dict) -> str:
    key, cands, make_args = _gate_ffn_ln(
        int(spec["m"]), int(spec["h"]), int(spec["i"]),
        spec.get("dtype", "bfloat16"), spec.get("act", "gelu"),
        spec.get("norm", "none"), float(spec.get("p", 0.0)))
    return autobench.prefer(key, cands, make_args, default="pallas")


autobench.register_warmer("fused_out_ln", _warm_out_ln)
autobench.register_warmer("fused_ffn_block", _warm_ffn_ln)
