"""Fused scaled-dot-product attention.

Reference equivalent: operators/fused/multihead_matmul_op +
math/bert_encoder_functor.cu. Round-1 provides the XLA-fused reference path
(jnp, fully fused by XLA into MXU-friendly form); the Pallas blockwise
(flash) kernel slots in behind the same `fused_attention` op type in the
transformer round.
"""
from __future__ import annotations

import math
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..fluid.registry import register, same_shape_as
from ..fluid.ops.common import x

__all__ = ["scaled_dot_product_attention"]


def sdpa_reference(q, k, v, mask=None, scale=None, causal=False,
                   dropout_p=0.0, rng_key=None):
    """q,k,v: (B, H, S, D). mask: broadcastable to (B, H, S, S)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhsd,bhtd->bhst", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((s, t), dtype=bool))
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0 and rng_key is not None:
        keep = jax.random.bernoulli(rng_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhst,bhtd->bhsd", probs.astype(v.dtype), v)


def _gate_flash(b, h, s, t, d, dtype, causal, has_mask, scale=None):
    """(key, candidates, make_args) for the flash-vs-XLA gate — shared
    by the trace-time gate and the autobench warm CLI so a pre-warmed
    cache record matches the runtime lookup exactly."""
    from .pallas_attention import flash_attention
    dtype = jnp.dtype(dtype)
    key = ("flash_attention", b, h, s, t, d, str(dtype), bool(causal),
           bool(has_mask))

    def make_args():
        rng = np.random.RandomState(0)
        args = [jnp.asarray(rng.randn(b, h, s, d), dtype),
                jnp.asarray(rng.randn(b, h, t, d), dtype),
                jnp.asarray(rng.randn(b, h, t, d), dtype)]
        if has_mask:
            args.append(jnp.zeros((b, 1, 1, t), jnp.float32))
        return tuple(args)

    if has_mask:
        cands = {
            "pallas": lambda q, k, v, m: flash_attention(
                q, k, v, m, scale, causal),
            "xla": lambda q, k, v, m: sdpa_reference(
                q, k, v, m, scale, causal),
        }
    else:
        cands = {
            "pallas": lambda q, k, v: flash_attention(
                q, k, v, None, scale, causal),
            "xla": lambda q, k, v: sdpa_reference(
                q, k, v, None, scale, causal),
        }
    return key, cands, make_args


def _flash_wins(q, k, v, mask, scale, causal) -> bool:
    """One-shot auto-benchmark gate (VERDICT r5 weak #1: the Pallas
    kernel measured 0.756x vs XLA at BERT seq-512 yet still held the
    hot path). On a real TPU the first trace at each shape times the
    Pallas kernel against the jnp/XLA sdpa (ops/autobench: measured
    once per shape per process, then persisted in the tuning cache) and
    the op routes to the winner; off-TPU (interpret-mode tests) the
    explicit env opt-in is honored unbenchmarked — timing the
    interpreter would be meaningless. PADDLE_TPU_FLASH_AUTOBENCH=0
    restores the old always-pallas behavior."""
    from .pallas_attention import on_tpu
    if not on_tpu():
        return True   # PADDLE_TPU_PALLAS_INTERPRET tests opt in explicitly
    if os.environ.get("PADDLE_TPU_FLASH_AUTOBENCH", "1") == "0":
        return True
    from . import autobench
    b, h, s, d = q.shape
    t = k.shape[2]
    key, cands, make_args = _gate_flash(
        b, h, s, t, d, q.dtype, causal, mask is not None, scale)
    return autobench.prefer(key, cands, make_args,
                            default="pallas") == "pallas"


def _warm_flash(spec: dict) -> str:
    from . import autobench
    s = int(spec["s"])
    key, cands, make_args = _gate_flash(
        int(spec["b"]), int(spec["h"]), s, int(spec.get("t", s)),
        int(spec["d"]), spec.get("dtype", "bfloat16"),
        bool(spec.get("causal", False)), bool(spec.get("mask", False)))
    return autobench.prefer(key, cands, make_args, default="pallas")


def _register_warmer():
    from . import autobench
    autobench.register_warmer("flash_attention", _warm_flash)


_register_warmer()


@register("fused_attention", stochastic=True,
          infer_shape=same_shape_as("Q"),
          attrs={"causal": False, "dropout_p": 0.0, "scale": 0.0},
          no_grad_slots=("Mask",))
def _fused_attention(ctx, ins, attrs):
    q, k, v = x(ins, "Q"), x(ins, "K"), x(ins, "V")
    mask = x(ins, "Mask")
    scale = attrs.get("scale") or None
    causal = attrs.get("causal", False)
    dropout_p = attrs.get("dropout_p", 0.0) if not ctx.is_test else 0.0

    from .pallas_attention import can_use_flash, flash_attention
    if can_use_flash(q, k, v, mask, dropout_p) \
            and _flash_wins(q, k, v, mask, scale, causal):
        seed = 0
        if dropout_p > 0.0:
            # fold the step key into a 32-bit seed for the in-kernel hash rng
            key = ctx.rng(attrs)
            kd = key if jnp.issubdtype(key.dtype, jnp.integer) \
                else jax.random.key_data(key)
            seed = kd.ravel()[-1].astype(jnp.int32)
        o = flash_attention(q, k, v, mask, scale, causal, dropout_p, seed)
        return {"Out": [o]}

    key = ctx.rng(attrs) if dropout_p > 0 else None
    o = sdpa_reference(q, k, v, mask, scale, causal,
                       dropout_p if key is not None else 0.0, key)
    return {"Out": [o]}


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    from ..common_ops import run_op
    ins = {"Q": query, "K": key, "V": value}
    if attn_mask is not None:
        ins["Mask"] = attn_mask
    return run_op("fused_attention", ins,
                  {"causal": is_causal,
                   "dropout_p": float(dropout_p) if training else 0.0})
