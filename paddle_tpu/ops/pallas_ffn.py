"""Pallas TPU fused transformer FFN: y = act(x @ W1 + b1) @ W2 + b2.

The round-5 BERT traffic audit (bench.py bench_bert docstring) measured
the FFN activation tier — erf-gelu + its saved branch predicates over
bf16[B,T,4H] — at ~19% of the train step, VPU-compute-bound and
materialised to HBM between the two matmuls. This kernel keeps the 4H
intermediate in VMEM: per (M-block, I-block) grid cell it computes
act(x_blk @ W1_blk + b1_blk) on-chip and accumulates the second matmul
into an f32 scratch, so the intermediate never exists in HBM and the
gelu runs tile-at-a-time interleaved with MXU work.

Reference equivalent: the fused FFN passes of
operators/fused/fused_feedforward_op.cc (the mechanism — one kernel for
linear+act+linear — re-expressed as a TPU Mosaic pipeline).

Backward (custom_vjp) rematerialises: only x is saved; dx/dW come from
one recompute matmul + the standard four, all left to XLA — the fwd
traffic/VPU win is where the audit says the money is.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_attention import on_tpu

# jax renamed TPUCompilerParams -> CompilerParams across releases;
# accept either so the kernel runs on the toolchain actually installed
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")

__all__ = ["fused_ffn", "can_use_fused_ffn"]


def _interpret() -> bool:
    return not on_tpu()


def _vmem_budget() -> int:
    return int(os.environ.get("PADDLE_TPU_FFN_VMEM_BUDGET",
                              14 * (1 << 20)))


def _pick_blocks(m: int, h: int, i: int,
                 itemsize: int) -> tuple[int, int] | None:
    """Largest (bm, bi) whose VMEM working set fits the budget: the f32
    (bm, h) accumulator scratch plus the double-buffered x/W1/b1/W2/b2/
    out blocks. Scaling bm (and bi) down with h is what keeps large-h
    models on the fused path instead of failing Mosaic compilation at
    runtime (ADVICE: ~16 MiB usable VMEM on v5e; 8 MiB scratch alone at
    bm=512/h=4096)."""
    budget = _vmem_budget()
    for bm in (512, 256, 128):
        if m % bm:
            continue
        for bi in (512, 256, 128):
            if i % bi:
                continue
            scratch = bm * h * 4
            blocks = 2 * itemsize * (bm * h      # x block
                                     + h * bi + bi   # W1, b1
                                     + bi * h + h    # W2, b2
                                     + bm * h)       # out block
            if scratch + blocks <= budget:
                return bm, bi
    return None


def can_use_fused_ffn(m: int, h: int, i: int, itemsize: int = 4) -> bool:
    if os.environ.get("PADDLE_TPU_DISABLE_PALLAS"):
        return False
    if os.environ.get("PADDLE_TPU_DISABLE_FFN_FUSION"):
        return False
    if not (on_tpu() or os.environ.get("PADDLE_TPU_PALLAS_INTERPRET")):
        return False
    # MXU-aligned shapes that fit the VMEM budget; fall back to the XLA
    # chain otherwise (callers pass the activation itemsize — bf16
    # fits shapes f32 cannot)
    return (m % 256 == 0 and h % 128 == 0 and i % 512 == 0
            and h <= 4096
            and _pick_blocks(m, h, i, itemsize) is not None)


def _erf_poly(z):
    """Abramowitz & Stegun 7.1.26 rational erf (|err| < 1.5e-7 in f32):
    Pallas TPU has no erf/erfc primitive, and 1.5e-7 is far inside bf16
    activation tolerance."""
    s = jnp.sign(z)
    a = jnp.abs(z)
    t = 1.0 / (1.0 + 0.3275911 * a)
    poly = t * (0.254829592 + t * (-0.284496736 + t * (
        1.421413741 + t * (-1.453152027 + t * 1.061405429))))
    return s * (1.0 - poly * jnp.exp(-a * a))


def _gelu_exact(v):
    f = v.astype(jnp.float32)
    return (0.5 * f * (1.0 + _erf_poly(f * 0.7071067811865476))
            ).astype(v.dtype)


def _gelu_tanh(v):
    """tanh-approximated gelu (the GPT-2 convention jax.nn.gelu
    approximate=True uses) — bit-matching formula, so the fused blocks
    can hold paths that train with the approximate activation."""
    f = v.astype(jnp.float32)
    c = 0.7978845608028654  # sqrt(2/pi)
    return (0.5 * f * (1.0 + jnp.tanh(c * (f + 0.044715 * f * f * f)))
            ).astype(v.dtype)


_ACTS = {
    "gelu": _gelu_exact,
    "gelu_tanh": _gelu_tanh,
    "relu": jax.nn.relu,
}


def _ffn_kernel(x_ref, w1_ref, b1_ref, w2_ref, b2_ref, o_ref, acc_ref,
                *, act, n_i):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    a = jnp.dot(x_ref[...], w1_ref[...],
                preferred_element_type=jnp.float32) + b1_ref[...]
    hid = act(a).astype(x_ref.dtype)
    acc_ref[...] += jnp.dot(hid, w2_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(j == n_i - 1)
    def _emit():
        o_ref[...] = (acc_ref[...] + b2_ref[...]).astype(o_ref.dtype)


def _ffn_fwd_impl(x2, w1, b1, w2, b2, act_name, bm, bi):
    m, h = x2.shape
    i = w1.shape[1]
    n_i = i // bi
    act = _ACTS[act_name]
    return pl.pallas_call(
        functools.partial(_ffn_kernel, act=act, n_i=n_i),
        grid=(m // bm, n_i),
        in_specs=[
            pl.BlockSpec((bm, h), lambda mi, ji: (mi, 0)),
            pl.BlockSpec((h, bi), lambda mi, ji: (0, ji)),
            pl.BlockSpec((1, bi), lambda mi, ji: (0, ji)),
            pl.BlockSpec((bi, h), lambda mi, ji: (ji, 0)),
            pl.BlockSpec((1, h), lambda mi, ji: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, h), lambda mi, ji: (mi, 0)),
        out_shape=jax.ShapeDtypeStruct((m, h), x2.dtype),
        scratch_shapes=[pltpu.VMEM((bm, h), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(x2, w1, b1.reshape(1, i), w2, b2.reshape(1, h))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def fused_ffn(x, w1, b1, w2, b2, act_name="gelu"):
    """x [..., H] -> [..., H]; the 4H intermediate stays in VMEM."""
    return _fused_ffn_fwd(x, w1, b1, w2, b2, act_name)[0]


def _fused_ffn_fwd(x, w1, b1, w2, b2, act_name):
    shape = x.shape
    h = shape[-1]
    x2 = x.reshape(-1, h)
    m = x2.shape[0]
    i = w1.shape[1]
    blocks = _pick_blocks(m, h, i, x.dtype.itemsize)
    if blocks is None:
        # no block shape fits VMEM (or m isn't block-aligned): run the
        # composed XLA chain rather than fail Mosaic compilation
        hid = _ACTS[act_name](x2 @ w1 + b1)
        y = (hid.astype(x.dtype) @ w2 + b2).astype(x.dtype)
    else:
        y = _ffn_fwd_impl(x2, w1, b1, w2, b2, act_name, *blocks)
    return y.reshape(shape), (x, w1, b1, w2, b2)


def _fused_ffn_bwd(act_name, res, dy):
    x, w1, b1, w2, b2 = res
    act = _ACTS[act_name]
    h = x.shape[-1]
    x2 = x.reshape(-1, h).astype(jnp.float32)
    dy2 = dy.reshape(-1, h).astype(jnp.float32)

    def chain(x2f, w1f, b1f, w2f, b2f):
        hid = act(x2f @ w1f + b1f)
        return hid @ w2f + b2f

    # one recompute matmul + the standard four, via XLA's autodiff —
    # nothing was saved between the matmuls
    _, vjp = jax.vjp(chain, x2, w1.astype(jnp.float32),
                     b1.astype(jnp.float32), w2.astype(jnp.float32),
                     b2.astype(jnp.float32))
    dx2, dw1, db1, dw2, db2 = vjp(dy2)
    return (dx2.reshape(x.shape).astype(x.dtype),
            dw1.astype(w1.dtype), db1.astype(b1.dtype),
            dw2.astype(w2.dtype), db2.astype(b2.dtype))


fused_ffn.defvjp(_fused_ffn_fwd, _fused_ffn_bwd)


# ---------------------------------------------------------------------------
# autobench gate + warmer: the fused FFN must beat the composed chain
# per shape on TPU (PR-7 satellite: no hand kernel holds a hot path by
# construction — every Pallas-vs-XLA choice routes through prefer())
# ---------------------------------------------------------------------------

def _gate_ffn(m, h, i, dtype, act="gelu"):
    import numpy as np
    dtype = jnp.dtype(dtype)
    key = ("fused_ffn", m, h, i, str(dtype), act)

    def mk(rng, r, c):
        return jnp.asarray(rng.randn(r, c) * 0.05, dtype)

    def make_args():
        rng = np.random.RandomState(0)
        return (mk(rng, m, h), mk(rng, h, i), mk(rng, 1, i)[0],
                mk(rng, i, h), mk(rng, 1, h)[0])

    def xla_chain(x, w1, b1, w2, b2):
        hid = _ACTS[act](x @ w1 + b1)
        return (hid.astype(x.dtype) @ w2 + b2).astype(x.dtype)

    cands = {
        "pallas": lambda *a: fused_ffn(*a, act),
        "xla": xla_chain,
    }
    return key, cands, make_args


def ffn_wins(m, h, i, dtype, act="gelu") -> bool:
    """On TPU: measured per-shape arbitration (persisted via the tuning
    cache); off-TPU the interpret opt-in that passed can_use runs it."""
    if not on_tpu():
        return True
    from . import autobench
    key, cands, make_args = _gate_ffn(m, h, i, dtype, act)
    return autobench.prefer(key, cands, make_args,
                            default="pallas") == "pallas"


def _warm_ffn(spec: dict) -> str:
    from . import autobench
    key, cands, make_args = _gate_ffn(
        int(spec["m"]), int(spec["h"]), int(spec["i"]),
        spec.get("dtype", "bfloat16"), spec.get("act", "gelu"))
    return autobench.prefer(key, cands, make_args, default="pallas")


def _register_warmer():
    from . import autobench
    autobench.register_warmer("fused_ffn", _warm_ffn)


_register_warmer()
