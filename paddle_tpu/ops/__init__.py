"""paddle_tpu.ops — Pallas TPU kernels for the fusion-critical set.

Replaces the reference's hand-written CUDA fused kernels
(operators/fused/*, math/bert_encoder_functor.cu) with Mosaic/Pallas kernels:
flash attention, layer_norm, softmax-xent. Kernels register as alternative
compute impls for existing op types; the registry falls back to the jnp
reference implementation when Pallas is unavailable (CPU tests).
"""
from . import flash_attention  # noqa: F401
from . import pallas_attention  # noqa: F401
from . import pallas_layer_norm  # noqa: F401
from . import paged_attention  # noqa: F401

__all__ = ["flash_attention", "pallas_attention", "pallas_layer_norm",
           "paged_attention"]
