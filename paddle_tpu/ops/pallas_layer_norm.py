"""Pallas TPU fused layer_norm — single-pass fwd + fused-dx bwd kernels.

TPU-native replacement for the reference's layer_norm CUDA kernels
(/root/reference/paddle/fluid/operators/layer_norm_op.cu:1 and the fused
skip-layernorm tier in framework/ir/skip_layernorm_fuse_pass.cc). One VMEM
pass computes mean/rstd and the normalised+affine output per row block; the
backward fuses the three dx reduction terms into one kernel. dscale/dbias
are thin cross-row reductions left to XLA (they fuse into surrounding ops).

Layouts: x/y (R, C); scale/bias (1, C); mean/rstd residuals (R, 128)
lane-broadcast f32 (TPU min-tile trick, same as the flash kernel's lse).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .pallas_attention import on_tpu

__all__ = ["fused_layer_norm", "can_use_fused_ln"]


def _interpret() -> bool:
    return not on_tpu()


def can_use_fused_ln(rows: int, cols: int, has_scale: bool,
                     has_bias: bool) -> bool:
    if os.environ.get("PADDLE_TPU_DISABLE_PALLAS"):
        return False
    if not (on_tpu() or os.environ.get("PADDLE_TPU_PALLAS_INTERPRET")):
        return False
    if not (has_scale and has_bias):
        return False
    if cols % 128 or cols > 16384:
        return False
    return _pick_block(rows) is not None


def _pick_block(rows: int):
    for br in (256, 128, 64, 32, 16, 8):
        if rows % br == 0:
            return br
    return None


def _fwd_kernel(x_ref, scale_ref, bias_ref, y_ref, mean_ref, rstd_ref, *,
                eps):
    xv = x_ref[:].astype(jnp.float32)                    # (Br, C)
    mean = jnp.mean(xv, axis=1, keepdims=True)
    var = jnp.mean(jnp.square(xv - mean), axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (xv - mean) * rstd
    y = xhat * scale_ref[0].astype(jnp.float32)[None, :] + \
        bias_ref[0].astype(jnp.float32)[None, :]
    y_ref[:] = y.astype(y_ref.dtype)
    br = xv.shape[0]
    mean_ref[:] = jax.lax.broadcast_in_dim(mean[:, 0], (br, 128), (0,))
    rstd_ref[:] = jax.lax.broadcast_in_dim(rstd[:, 0], (br, 128), (0,))


def _bwd_dx_kernel(x_ref, scale_ref, mean_ref, rstd_ref, dy_ref, dx_ref):
    xv = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    mean = mean_ref[:][:, 0:1]
    rstd = rstd_ref[:][:, 0:1]
    xhat = (xv - mean) * rstd
    a = dy * scale_ref[0].astype(jnp.float32)[None, :]
    c1 = jnp.mean(a, axis=1, keepdims=True)
    c2 = jnp.mean(a * xhat, axis=1, keepdims=True)
    dx_ref[:] = (rstd * (a - c1 - xhat * c2)).astype(dx_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x2d, scale, bias, eps):
    """x2d: (R, C); scale/bias: (C,). Returns (y, mean, rstd) with mean/rstd
    shaped (R,) f32. Statistics outputs are non-differentiable (reference
    layer_norm Mean/Variance outputs carry no gradient)."""
    y, mean, rstd = _ln_fwd_impl(x2d, scale, bias, eps)
    return y, mean, rstd


def _ln_fwd_impl(x2d, scale, bias, eps):
    r, c = x2d.shape
    br = _pick_block(r)
    y, mean_b, rstd_b = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(r // br,),
        in_specs=[
            pl.BlockSpec((br, c), lambda i: (i, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, c), lambda i: (i, 0)),
            pl.BlockSpec((br, 128), lambda i: (i, 0)),
            pl.BlockSpec((br, 128), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((r, c), x2d.dtype),
            jax.ShapeDtypeStruct((r, 128), jnp.float32),
            jax.ShapeDtypeStruct((r, 128), jnp.float32),
        ],
        interpret=_interpret())(x2d, scale.reshape(1, c), bias.reshape(1, c))
    return y, mean_b[:, 0], rstd_b[:, 0]


def _ln_fwd(x2d, scale, bias, eps):
    y, mean, rstd = _ln_fwd_impl(x2d, scale, bias, eps)
    return (y, mean, rstd), (x2d, scale, mean, rstd)


def _ln_bwd(eps, res, cots):
    dy, _dmean, _drstd = cots  # stats are non-differentiable outputs
    x2d, scale, mean, rstd = res
    r, c = x2d.shape
    br = _pick_block(r)
    mean_b = jnp.broadcast_to(mean[:, None], (r, 128))
    rstd_b = jnp.broadcast_to(rstd[:, None], (r, 128))
    dx = pl.pallas_call(
        _bwd_dx_kernel,
        grid=(r // br,),
        in_specs=[
            pl.BlockSpec((br, c), lambda i: (i, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((br, 128), lambda i: (i, 0)),
            pl.BlockSpec((br, 128), lambda i: (i, 0)),
            pl.BlockSpec((br, c), lambda i: (i, 0)),
        ],
        out_specs=[pl.BlockSpec((br, c), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((r, c), x2d.dtype)],
        interpret=_interpret())(x2d, scale.reshape(1, c), mean_b, rstd_b,
                                dy)[0]
    # dscale/dbias: thin cross-row reductions — XLA fuses these fine
    xf = x2d.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    xhat = (xf - mean[:, None]) * rstd[:, None]
    dscale = jnp.sum(dyf * xhat, axis=0).astype(scale.dtype)
    dbias = jnp.sum(dyf, axis=0).astype(scale.dtype)
    return dx, dscale, dbias


fused_layer_norm.defvjp(_ln_fwd, _ln_bwd)


# ---------------------------------------------------------------------------
# autobench gate + warmer (PR-7 satellite: this kernel used to hold the
# layer_norm op unconditionally wherever can_use_fused_ln passed — now
# it must beat the composed XLA chain per shape on TPU, with the
# decision persisted via the tuning cache)
# ---------------------------------------------------------------------------

def _ln_xla_ref(x2d, scale, bias, eps=1e-5):
    fp = x2d.astype(jnp.float32)
    mean = jnp.mean(fp, -1, keepdims=True)
    var = jnp.var(fp, -1, keepdims=True)
    y = (fp - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x2d.dtype)


def _gate_ln(rows, cols, dtype, eps=1e-5):
    import numpy as np
    dtype = jnp.dtype(dtype)
    key = ("fused_layer_norm", rows, cols, str(dtype))

    def make_args():
        rng = np.random.RandomState(0)
        return (jnp.asarray(rng.randn(rows, cols), dtype),
                jnp.ones((cols,), jnp.float32),
                jnp.zeros((cols,), jnp.float32))

    cands = {
        "pallas": lambda x, s, b: fused_layer_norm(x, s, b, eps)[0],
        "xla": lambda x, s, b: _ln_xla_ref(x, s, b, eps),
    }
    return key, cands, make_args


def ln_wins(rows, cols, dtype, eps=1e-5) -> bool:
    if not on_tpu():
        return True
    from . import autobench
    key, cands, make_args = _gate_ln(rows, cols, dtype, eps)
    return autobench.prefer(key, cands, make_args,
                            default="pallas") == "pallas"


def _warm_ln(spec: dict) -> str:
    from . import autobench
    key, cands, make_args = _gate_ln(
        int(spec["rows"]), int(spec["cols"]),
        spec.get("dtype", "bfloat16"))
    return autobench.prefer(key, cands, make_args, default="pallas")


def _register_warmer():
    from . import autobench
    autobench.register_warmer("fused_layer_norm", _warm_ln)


_register_warmer()
