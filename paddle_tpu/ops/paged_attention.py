"""Ragged paged-attention decode (PAPERS.md: Ragged Paged Attention).

The serving tier stores each request's KV history in fixed-size pages of
a preallocated HBM pool; decode computes one new token per in-flight
request ("slot") against its own ragged-length history. Two
implementations behind one function:

  * gather-based XLA: k_pages[page_table] gathers each slot's pages into
    a [S, M*ps] context, masked past ctx_len — one fused XLA computation,
    the portable default;
  * a Pallas TPU kernel: grid (slot, page), page indices scalar-prefetched
    so each program DMAs exactly one page from HBM, online-softmax
    accumulation in VMEM scratch — the TPU-native shape of the kernel
    (same design as the stock ragged-paged-attention kernels).

Selection runs through ops/autobench.prefer — the same measure-once gate
that arbitrates Pallas-vs-XLA flash attention — so the hand kernel only
holds the hot path on shapes where it measures faster.

Layouts:
  q          [S, H, d]        one query token per slot
  k/v_pages  [P, ps, H, d]    the page pools
  page_table [S, M] int32     pool index of each slot's m-th page
  ctx_lens   [S] int32        valid history length per slot (>= 1)
Returns     [S, H, d]
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from ..fluid.registry import register, same_shape_as
from ..fluid.ops.common import x
from .pallas_attention import on_tpu

__all__ = ["paged_attention_decode", "paged_attention_xla",
           "paged_attention_pallas"]

_NEG = -1e30


def paged_attention_xla(q, k_pages, v_pages, page_table, ctx_lens,
                        scale=None):
    """Gather-based reference path; fully fused by XLA."""
    S, H, d = q.shape
    ps = k_pages.shape[1]
    M = page_table.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    k = k_pages[page_table].reshape(S, M * ps, H, d)
    v = v_pages[page_table].reshape(S, M * ps, H, d)
    logits = jnp.einsum("shd,sthd->sht", q, k,
                        preferred_element_type=jnp.float32) * scale
    pos = jnp.arange(M * ps, dtype=jnp.int32)[None, :]
    logits = jnp.where(pos[:, None, :] < ctx_lens[:, None, None],
                       logits, _NEG)
    probs = jax.nn.softmax(logits, axis=-1)
    o = jnp.einsum("sht,sthd->shd", probs.astype(v.dtype), v)
    return o.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel: grid (slot, page); page_table + ctx_lens scalar-prefetched
# so the k/v BlockSpec index_map can steer each program's DMA at one page.
# ---------------------------------------------------------------------------

def _paged_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, page_size, scale):
    s, m = pl.program_id(0), pl.program_id(1)
    n_pages = pl.num_programs(1)

    @pl.when(m == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32)            # [H, d]
    k = k_ref[0].astype(jnp.float32)            # [ps, H, d]
    scores = jnp.einsum("hd,phd->hp", q, k,
                        preferred_element_type=jnp.float32) * scale
    idx = m * page_size + jax.lax.broadcasted_iota(
        jnp.int32, scores.shape, 1)
    scores = jnp.where(idx < len_ref[s], scores, _NEG)

    m_prev = m_ref[...]                          # [H, 1]
    m_new = jnp.maximum(m_prev, jnp.max(scores, -1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(scores - m_new)                  # [H, ps]
    p = jnp.where(idx < len_ref[s], p, 0.0)      # kill exp(-NEG - -NEG)=1
    l_ref[...] = alpha * l_ref[...] + jnp.sum(p, -1, keepdims=True)
    v = v_ref[0].astype(jnp.float32)             # [ps, H, d]
    pv = jnp.einsum("hp,phd->hd", p, v,
                    preferred_element_type=jnp.float32)
    acc_ref[...] = acc_ref[...] * alpha + pv
    m_ref[...] = m_new

    @pl.when(m == n_pages - 1)
    def _fin():
        l = l_ref[...]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def paged_attention_pallas(q, k_pages, v_pages, page_table, ctx_lens,
                           scale=None, interpret=None):
    S, H, d = q.shape
    ps = k_pages.shape[1]
    M = page_table.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = not on_tpu()
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, M),
        in_specs=[
            pl.BlockSpec((1, H, d), lambda s, m, pt, ln: (s, 0, 0)),
            pl.BlockSpec((1, ps, H, d),
                         lambda s, m, pt, ln: (pt[s, m], 0, 0, 0)),
            pl.BlockSpec((1, ps, H, d),
                         lambda s, m, pt, ln: (pt[s, m], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, d), lambda s, m, pt, ln: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, d), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_kernel, page_size=ps,
                               scale=float(scale))
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, d), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), ctx_lens.astype(jnp.int32),
      q, k_pages, v_pages)


def _gate_paged(S, H, d, P, ps, M, dtype):
    """(key, candidates, make_args) — shared by the decode-path gate and
    the autobench warm CLI (a fleet replica shipping a pre-warmed cache
    skips first-request measurement on its decode hot path)."""
    dtype = jnp.dtype(dtype)
    key = ("paged_attention", S, H, d, P, ps, M, str(dtype))

    def make_args():
        import numpy as np
        rng = np.random.RandomState(0)
        qq = jnp.asarray(rng.randn(S, H, d), dtype)
        kk = jnp.asarray(rng.randn(P, ps, H, d), dtype)
        vv = jnp.asarray(rng.randn(P, ps, H, d), dtype)
        pt = jnp.asarray(rng.randint(0, P, (S, M)), jnp.int32)
        ln = jnp.asarray(rng.randint(1, M * ps + 1, (S,)), jnp.int32)
        return qq, kk, vv, pt, ln

    cands = {
        "xla": paged_attention_xla,
        "pallas": lambda *a: paged_attention_pallas(*a, interpret=False),
    }
    return key, cands, make_args


def _auto_impl(q, k_pages, page_table) -> str:
    """Measure-once arbitration (TPU only; everywhere else the gathered
    XLA path is the portable winner and interpret-mode timing would be
    meaningless)."""
    if os.environ.get("PADDLE_TPU_DISABLE_PALLAS") or pltpu is None \
            or not on_tpu():
        return "xla"
    from . import autobench
    S, H, d = q.shape
    P, ps = k_pages.shape[0], k_pages.shape[1]
    M = page_table.shape[1]
    key, cands, make_args = _gate_paged(S, H, d, P, ps, M, q.dtype)
    return autobench.prefer(key, cands, make_args, default="xla")


def _warm_paged(spec: dict) -> str:
    from . import autobench
    key, cands, make_args = _gate_paged(
        int(spec["s"]), int(spec["h"]), int(spec["d"]), int(spec["p"]),
        int(spec["ps"]), int(spec["m"]), spec.get("dtype", "bfloat16"))
    return autobench.prefer(key, cands, make_args, default="xla")


def _register_warmer():
    from . import autobench
    autobench.register_warmer("paged_attention", _warm_paged)


_register_warmer()


def paged_attention_decode(q, k_pages, v_pages, page_table, ctx_lens,
                           scale=None, impl=None):
    """Ragged paged-attention decode; see module docstring for layouts.

    impl: None = auto (XLA everywhere; on TPU the Pallas kernel is
    auto-benchmarked per shape and used where it wins), or force
    "xla" / "pallas"."""
    if impl is None:
        impl = _auto_impl(q, k_pages, page_table)
    if impl == "pallas":
        return paged_attention_pallas(q, k_pages, v_pages, page_table,
                                      ctx_lens, scale)
    return paged_attention_xla(q, k_pages, v_pages, page_table, ctx_lens,
                               scale)


@register("paged_attention", grad=None,
          infer_shape=same_shape_as("Q"),
          attrs={"scale": 0.0, "impl": ""},
          no_grad_slots=("PageTable", "CtxLens"))
def _paged_attention_op(ctx, ins, attrs):
    """Op form so deserialized/static serving programs can spell the
    decode step as a graph op (inference-only: grad=None)."""
    q = x(ins, "Q")
    o = paged_attention_decode(
        q, x(ins, "KCache"), x(ins, "VCache"), x(ins, "PageTable"),
        x(ins, "CtxLens"), scale=attrs.get("scale") or None,
        impl=attrs.get("impl") or None)
    return {"Out": [o]}
