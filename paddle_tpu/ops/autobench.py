"""Per-shape kernel auto-benchmark gate with a persistent tuning cache.

Motivation (VERDICT r5 weak #1): the hand-written Pallas flash-attention
kernel measured 0.756x vs stock XLA at BERT seq-512 shapes while the
model hot path still ran it — a hand kernel must EARN its slot per
shape, not hold it by construction. This module provides the gate:

  winner = prefer(key, {"pallas": fn_a, "xla": fn_b}, make_args)

On first call for `key` (a hashable shape/dtype signature) each
candidate is jitted and timed on freshly made concrete inputs; the
fastest name is cached for the life of the process and every later
call for the same key returns instantly. The gate is invoked at
trace/first-call time from op kernels — Python side effects during a
jax trace run exactly once per compilation, so the measurement cost is
paid once per shape bucket, never per step.

Persistent tuning cache (PR 7, TPP-style portable primitives): set
``PADDLE_TPU_AUTOBENCH_CACHE=/path/to/autobench.json`` and every
decision is also published to disk keyed by (shape key, device kind,
jax version, kernel schema version), so a *new process* — a restarted
trainer, or a fleet of serving replicas shipped a pre-warmed file —
skips in-process measuring entirely. Properties, mirroring the PR-4
checkpoint store:

  * atomic publish: records are merged into the current file content
    and committed by tmp + ``os.replace`` — a reader never sees a torn
    file, concurrent writers race benignly (last writer wins; the
    read-merge-write keeps disjoint keys from clobbering each other);
  * per-record CRC32 over the canonical JSON — a corrupt record is
    skipped (and re-measured), a corrupt FILE degrades to in-process
    measuring and is overwritten by the next publish;
  * version stamps: records carry the jax version and this module's
    ``KERNEL_VERSION``; a mismatch marks the record stale and it is
    re-measured (then re-published) rather than trusted.

CLI (fleet warm/inspect):  ``python -m paddle_tpu.ops.autobench
list|warm|invalidate`` — see ``_main`` below and docs/KERNELS.md.

Every decision is also recorded as structured telemetry
(paddle_tpu_autobench_* gauges + cache hit/miss/stale counters on the
process registry) and logged through the `paddle_tpu.autobench` logger.

Env knobs:
  PADDLE_TPU_AUTOBENCH=0          disable measuring; `default` wins
  PADDLE_TPU_AUTOBENCH_FORCE=name force a candidate (debug/A-B runs);
                                  a name no gate offers logs a warning
                                  (typo guard, like PADDLE_PS_FAULT_*)
  PADDLE_TPU_AUTOBENCH_CACHE=path persistent tuning-cache file
                                  (unset/empty/0 = in-process only)
  PADDLE_TPU_AUTOBENCH_VERBOSE=1  log-level switch: raises the
                                  `paddle_tpu.autobench` logger to INFO
                                  (with a stderr handler if the app
                                  configured none)
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from typing import Callable

from ..observability import perf as _perf, registry as _obs

__all__ = ["prefer", "decisions", "clear", "stats", "register_warmer",
           "warm", "list_entries", "invalidate", "KERNEL_VERSION",
           "PRESETS"]

# Bump when any gated Pallas kernel's implementation changes materially:
# cached winners were measured against the OLD kernel and must not
# survive it. (The jax version is stamped independently.)
KERNEL_VERSION = 2

_FORMAT = "paddle-tpu-autobench-v1"

_CACHE: dict = {}
_LOCK = threading.Lock()
_DISK: dict | None = None      # (key_str, device) -> record, lazy-loaded
_DISK_PATH: str | None = None  # path _DISK was loaded from
_STATS = {"measures": 0, "cache_hits": 0, "cache_misses": 0,
          "cache_stale": 0, "cache_corrupt": 0, "publishes": 0}
_WARNED_FORCE: set = set()

logger = logging.getLogger("paddle_tpu.autobench")

_CANDIDATE_MS = _obs.gauge(
    "paddle_tpu_autobench_candidate_ms",
    "measured median wall time per candidate per shape key",
    ["key", "candidate"])
_WINNER = _obs.gauge(
    "paddle_tpu_autobench_winner",
    "1 for the candidate holding the hot path of a shape key, else 0",
    ["key", "candidate"])
_CACHE_HITS = _obs.counter(
    "paddle_tpu_autobench_cache_hits_total",
    "decisions adopted from the persistent tuning cache (no measuring)")
_CACHE_MISSES = _obs.counter(
    "paddle_tpu_autobench_cache_misses_total",
    "lookups the persistent tuning cache had no record for")
_CACHE_STALE = _obs.counter(
    "paddle_tpu_autobench_cache_stale_total",
    "cache records ignored for a jax/kernel version mismatch")
_CACHE_CORRUPT = _obs.counter(
    "paddle_tpu_autobench_cache_corrupt_total",
    "cache files or records dropped for CRC/parse failures")
_MEASURES = _obs.counter(
    "paddle_tpu_autobench_measure_total",
    "in-process candidate measuring rounds (cold-path cost)")


def _verbose_logging():
    """PADDLE_TPU_AUTOBENCH_VERBOSE kept as a LOG-LEVEL switch: it used
    to print to stderr; now it raises the module logger to INFO (adding
    a stderr handler only when logging is unconfigured)."""
    if not os.environ.get("PADDLE_TPU_AUTOBENCH_VERBOSE"):
        return
    if logger.getEffectiveLevel() > logging.INFO:
        logger.setLevel(logging.INFO)
    if not logger.handlers and not logging.getLogger().handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter("[autobench] %(message)s"))
        logger.addHandler(h)


def _record_decision(key, winner: str, timings: dict[str, float],
                     source: str = "measured"):
    skey = str(key)
    for name, t in timings.items():
        _CANDIDATE_MS.labels(key=skey, candidate=name).set(
            round(t * 1e3, 4) if t < float("inf") else float("inf"))
        _WINNER.labels(key=skey, candidate=name).set(
            1.0 if name == winner else 0.0)
    # the perf plane keeps the full per-candidate table so `top` can
    # show Pallas-vs-XLA margins, not just the winner name
    _perf.note_kernel(skey, winner,
                      {n: t * 1e3 for n, t in timings.items()})
    _verbose_logging()
    ms = {k: round(v * 1e3, 3) for k, v in timings.items()}
    logger.info("%s -> %s %s (%s)", skey, winner, ms, source)


def _measure(fn: Callable, make_args: Callable, reps: int) -> float:
    """Median wall time of `fn(*make_args())` jitted, after one warmup
    call that also pays compilation. Separated out so tests can inject
    deterministic timings."""
    import jax

    args = make_args()
    jfn = jax.jit(fn)
    out = jax.block_until_ready(jfn(*args))
    del out
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(jfn(*args))
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


# ---------------------------------------------------------------------------
# persistent tuning cache
# ---------------------------------------------------------------------------

def cache_path() -> str | None:
    p = os.environ.get("PADDLE_TPU_AUTOBENCH_CACHE", "").strip()
    return p if p and p != "0" else None


def _device_kind() -> str:
    try:
        import jax
        return str(jax.devices()[0].device_kind)
    except Exception:  # pragma: no cover - no backend at all
        return "unknown"


def _jax_version() -> str:
    try:
        import jax
        return str(jax.__version__)
    except Exception:  # pragma: no cover
        return "unknown"


def _rec_crc(rec: dict) -> int:
    body = {k: v for k, v in rec.items() if k != "crc"}
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return zlib.crc32(blob.encode("utf-8")) & 0xFFFFFFFF


def _read_file(path: str) -> dict:
    """(key_str, device) -> record from `path`. A corrupt file degrades
    to {} (in-process measuring still works); corrupt records are
    skipped individually. Both count toward the corrupt telemetry."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        records = doc["records"]
        assert isinstance(records, list)
    except FileNotFoundError:
        return {}
    except Exception as e:
        with _LOCK:
            _STATS["cache_corrupt"] += 1
        _CACHE_CORRUPT.inc()
        logger.warning("autobench cache %s unreadable (%s: %s) — "
                       "degrading to in-process measuring", path,
                       type(e).__name__, e)
        return {}
    out: dict = {}
    for rec in records:
        if not (isinstance(rec, dict) and "key" in rec and "device" in rec
                and "winner" in rec and rec.get("crc") == _rec_crc(rec)):
            with _LOCK:
                _STATS["cache_corrupt"] += 1
            _CACHE_CORRUPT.inc()
            continue
        out[(rec["key"], rec["device"])] = rec
    return out


def _disk_records() -> dict:
    """Lazy-load the cache file once per process (clear() resets)."""
    global _DISK, _DISK_PATH
    path = cache_path()
    if path is None:
        return {}
    with _LOCK:
        if _DISK is not None and _DISK_PATH == path:
            return _DISK
    recs = _read_file(path)
    with _LOCK:
        _DISK, _DISK_PATH = recs, path
    return recs


def _write_doc(path: str, records: dict):
    """Atomic, durable commit of the full record map: unique tmp file
    (pid+thread keyed — two in-process threads must not share one), an
    fsync so the rename never publishes a torn file, then os.replace."""
    doc = {"format": _FORMAT,
           "records": [records[k] for k in sorted(records)]}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(
        d, f".{os.path.basename(path)}.tmp.{os.getpid()}."
           f"{threading.get_ident()}")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=0, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# serializes read-merge-write publishers within the process (two traced
# ops on different threads can decide at the same time); cross-process
# racers remain benign last-writer-wins via the fresh re-read
_PUBLISH_LOCK = threading.Lock()


def _publish(path: str, rec: dict):
    """Merge `rec` into the file atomically (read-merge-write, tmp +
    rename commit like the PR-4 chunk store). Concurrent publishers are
    last-writer-wins per key; the fresh re-read keeps disjoint keys."""
    rec = dict(rec)
    rec["crc"] = _rec_crc(rec)
    with _PUBLISH_LOCK:
        current = _read_file(path)
        current[(rec["key"], rec["device"])] = rec
        _write_doc(path, current)
    with _LOCK:
        _STATS["publishes"] += 1
        global _DISK, _DISK_PATH
        if _DISK_PATH == path and _DISK is not None:
            _DISK[(rec["key"], rec["device"])] = rec


def _disk_lookup(key, candidates) -> str | None:
    """Adoptable winner from the persistent cache, or None (counting a
    miss or a stale record as appropriate)."""
    if cache_path() is None:
        return None
    rec = _disk_records().get((str(key), _device_kind()))
    if rec is None:
        with _LOCK:
            _STATS["cache_misses"] += 1
        _CACHE_MISSES.inc()
        return None
    if (rec.get("jax") != _jax_version()
            or rec.get("kernels") != KERNEL_VERSION
            or rec["winner"] not in candidates):
        with _LOCK:
            _STATS["cache_stale"] += 1
        _CACHE_STALE.inc()
        logger.info("stale cache record for %s (jax %s/%s kernels %s/%s)"
                    " — remeasuring", key, rec.get("jax"), _jax_version(),
                    rec.get("kernels"), KERNEL_VERSION)
        return None
    with _LOCK:
        _STATS["cache_hits"] += 1
    _CACHE_HITS.inc()
    # null timing = the candidate errored when measured (inf serialized
    # as JSON null) — adopt it as inf, never crash the gate on it
    timings = {n: (float(t) / 1e3 if t is not None else float("inf"))
               for n, t in (rec.get("timings_ms") or {}).items()}
    _record_decision(key, rec["winner"], timings, source="cache")
    return rec["winner"]


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------

def prefer(key, candidates: dict[str, Callable], make_args: Callable,
           default: str | None = None, reps: int = 3) -> str:
    """Return the name of the fastest candidate for `key`, measuring at
    most once per key per process — and, with
    PADDLE_TPU_AUTOBENCH_CACHE set, at most once per key per cache
    lifetime across processes.

    candidates: name -> nullary-composable fn taking make_args() outputs.
    make_args:  () -> tuple of concrete device arrays (built lazily, only
                on the measuring call).
    default:    winner when benchmarking is disabled (first name if None).
    """
    forced = os.environ.get("PADDLE_TPU_AUTOBENCH_FORCE")
    if forced:
        if forced in candidates:
            return forced
        # typo guard (PR-6 fault-knob idiom): a forced name no gate
        # offers would otherwise be silently ignored
        mark = (forced, tuple(sorted(candidates)))
        with _LOCK:
            fresh = mark not in _WARNED_FORCE
            _WARNED_FORCE.add(mark)
        if fresh:
            logger.warning(
                "PADDLE_TPU_AUTOBENCH_FORCE=%r names no candidate of "
                "this gate (candidates: %s) — ignoring the force and "
                "benchmarking normally", forced,
                ", ".join(sorted(candidates)))
    if default is None:
        default = next(iter(candidates))
    if os.environ.get("PADDLE_TPU_AUTOBENCH", "1") == "0":
        return default
    with _LOCK:
        hit = _CACHE.get(key)
    if hit is not None:
        return hit
    disk_winner = _disk_lookup(key, candidates)
    if disk_winner is not None:
        with _LOCK:
            return _CACHE.setdefault(key, disk_winner)
    timings = {}
    with _LOCK:
        _STATS["measures"] += 1
    _MEASURES.inc()
    cost_args = None
    for name, fn in candidates.items():
        try:
            timings[name] = _measure(fn, make_args, reps)
        except Exception:  # a candidate that errors never wins
            timings[name] = float("inf")
            continue
        # fused-block ops join the perf-plane cost registry on the same
        # once-per-key measuring path (roofline rows per candidate); a
        # failed cost observation must not void a successful timing
        if _perf.costs_enabled():
            try:
                import jax
                if cost_args is None:
                    cost_args = make_args()
                _perf.register_jit_cost(f"ops:{name}", str(key),
                                        jax.jit(fn), *cost_args)
            except Exception:
                pass
    winner = min(timings, key=timings.get)
    if not (timings[winner] < float("inf")):
        winner = default
    with _LOCK:
        # a racing thread may have decided already; first one wins so the
        # process is consistent
        winner = _CACHE.setdefault(key, winner)
    _record_decision(key, winner, timings)
    path = cache_path()
    if path is not None:
        try:
            _publish(path, {
                "key": str(key), "device": _device_kind(),
                "winner": winner, "jax": _jax_version(),
                "kernels": KERNEL_VERSION,
                "timings_ms": {n: (round(t * 1e3, 4)
                                   if t < float("inf") else None)
                               for n, t in timings.items()},
                "ts": round(time.time(), 3)})
        except OSError as e:  # unwritable cache never blocks the gate
            logger.warning("autobench cache publish to %s failed: %s",
                           path, e)
    return winner


def decisions() -> dict:
    """Snapshot of the cached key -> winner map (for /stats, tests)."""
    with _LOCK:
        return dict(_CACHE)


def stats() -> dict:
    """Process-local counters: measures, cache_hits/misses/stale/
    corrupt, publishes (tests + bench assert against these)."""
    with _LOCK:
        return dict(_STATS)


def clear():
    """Drop in-process decisions AND the loaded disk snapshot (the file
    itself is untouched; next prefer() re-reads it)."""
    global _DISK, _DISK_PATH
    with _LOCK:
        _CACHE.clear()
        _DISK, _DISK_PATH = None, None
        for k in _STATS:
            _STATS[k] = 0


# ---------------------------------------------------------------------------
# CLI surface: list / warm / invalidate (fleet pre-warm workflow)
# ---------------------------------------------------------------------------

_WARMERS: dict[str, Callable] = {}


def register_warmer(kernel: str, fn: Callable):
    """Register `fn(spec: dict) -> winner_name` for the warm CLI. Kernel
    modules register a spec-driven wrapper around their own gate so
    `warm` re-uses the exact keys/candidates the runtime will look up."""
    _WARMERS[kernel] = fn


def warm(specs: list[dict]) -> list[tuple[dict, str]]:
    """Run each spec's registered warmer (measuring + publishing through
    prefer())."""
    out = []
    for spec in specs:
        kind = spec.get("kernel")
        fn = _WARMERS.get(kind)
        if fn is None:
            raise KeyError(
                f"no warmer registered for kernel {kind!r} "
                f"(known: {', '.join(sorted(_WARMERS)) or 'none'})")
        out.append((spec, fn(dict(spec))))
    return out


# Model-shaped warm presets: the shapes the serving fleet / trainers
# actually hit (docs/KERNELS.md). dtype defaults to bfloat16 on TPU.
PRESETS: dict[str, list[dict]] = {
    "gpt_350m": [
        {"kernel": "flash_attention", "b": 8, "h": 16, "s": 1024,
         "d": 64, "causal": True},
        {"kernel": "fused_out_ln", "m": 8192, "din": 1024, "dout": 1024},
        {"kernel": "fused_ffn_block", "m": 8192, "h": 1024, "i": 4096,
         "act": "gelu_tanh", "norm": "none"},
        {"kernel": "fused_layer_norm", "rows": 8192, "cols": 1024},
    ],
    "bert_base_512": [
        {"kernel": "flash_attention", "b": 16, "h": 12, "s": 512,
         "d": 64, "causal": False, "mask": True},
        {"kernel": "fused_out_ln", "m": 8192, "din": 768, "dout": 768},
        {"kernel": "fused_ffn_block", "m": 8192, "h": 768, "i": 3072,
         "act": "gelu", "norm": "post"},
        {"kernel": "fused_ffn", "m": 8192, "h": 768, "i": 3072},
        {"kernel": "fused_dropout_add_ln", "rows": 8192, "cols": 768},
        {"kernel": "fused_layer_norm", "rows": 8192, "cols": 768},
    ],
}


def list_entries(path: str | None = None) -> list[dict]:
    path = path or cache_path()
    if not path:
        return []
    return [dict(rec) for _k, rec in sorted(_read_file(path).items())]


def invalidate(path: str | None = None, match: str | None = None,
               stale_only: bool = False) -> int:
    """Remove cache records (all, by substring, or only version-stale
    ones). Returns the number removed; commit is atomic like publish."""
    path = path or cache_path()
    if not path:
        return 0
    removed = 0
    with _PUBLISH_LOCK:  # read under the lock: a concurrent in-process
        # publish between read and write must not be erased
        current = _read_file(path)
        keep = {}
        for k, rec in current.items():
            is_stale = (rec.get("jax") != _jax_version()
                        or rec.get("kernels") != KERNEL_VERSION)
            hit = (match in rec["key"]) if match is not None \
                else (is_stale if stale_only else True)
            if hit:
                removed += 1
            else:
                keep[k] = rec
        if removed:
            _write_doc(path, keep)
    if removed:
        global _DISK, _DISK_PATH
        with _LOCK:
            _DISK, _DISK_PATH = None, None
    return removed


def _import_warmer_modules():
    """Importing the kernel modules registers their warmers."""
    from . import flash_attention  # noqa: F401
    from . import paged_attention  # noqa: F401
    from . import pallas_block  # noqa: F401
    from . import pallas_ffn  # noqa: F401
    from . import pallas_fused_residual  # noqa: F401
    from . import pallas_layer_norm  # noqa: F401


def _main(argv: list[str]) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.ops.autobench",
        description="inspect/warm/invalidate the persistent kernel "
                    "tuning cache (docs/KERNELS.md)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_list = sub.add_parser("list", help="print cache records")
    p_list.add_argument("--path", default=None)
    p_list.add_argument("--json", action="store_true")
    p_warm = sub.add_parser(
        "warm", help="measure + publish decisions for model-shaped "
                     "presets or a JSON spec file")
    p_warm.add_argument("--path", default=None,
                        help="cache file (defaults to "
                             "PADDLE_TPU_AUTOBENCH_CACHE)")
    p_warm.add_argument("--preset", action="append", default=[],
                        choices=sorted(PRESETS))
    p_warm.add_argument("--specs", default=None,
                        help="JSON file: list of warm spec objects")
    p_inv = sub.add_parser("invalidate", help="remove cache records")
    p_inv.add_argument("--path", default=None)
    g = p_inv.add_mutually_exclusive_group(required=True)
    g.add_argument("--match", default=None,
                   help="remove records whose key contains this string")
    g.add_argument("--stale", action="store_true",
                   help="remove only version-stale records")
    g.add_argument("--all", action="store_true")
    ns = ap.parse_args(argv)

    if ns.cmd == "list":
        entries = list_entries(ns.path)
        if ns.json:
            print(json.dumps(entries, indent=2, sort_keys=True))
        else:
            if not entries:
                print("(no cache records)")
            for rec in entries:
                stale = (rec.get("jax") != _jax_version()
                         or rec.get("kernels") != KERNEL_VERSION)
                print(f"{rec['winner']:>8}  {rec['device']:<12} "
                      f"{'STALE ' if stale else ''}{rec['key']}")
        return 0
    if ns.cmd == "warm":
        if ns.path:
            os.environ["PADDLE_TPU_AUTOBENCH_CACHE"] = ns.path
        if not cache_path():
            print("no cache path: pass --path or set "
                  "PADDLE_TPU_AUTOBENCH_CACHE", file=__import__("sys").stderr)
            return 2
        _import_warmer_modules()
        specs: list[dict] = []
        for name in ns.preset:
            specs.extend(PRESETS[name])
        if ns.specs:
            with open(ns.specs, encoding="utf-8") as f:
                specs.extend(json.load(f))
        if not specs:
            print("nothing to warm: pass --preset and/or --specs",
                  file=__import__("sys").stderr)
            return 2
        for spec, winner in warm(specs):
            print(f"{winner:>8}  {spec}")
        s = stats()
        print(f"warmed {len(specs)} specs -> {cache_path()} "
              f"(measures={s['measures']} hits={s['cache_hits']})")
        return 0
    if ns.cmd == "invalidate":
        n = invalidate(ns.path, match=ns.match, stale_only=ns.stale)
        print(f"removed {n} records")
        return 0
    return 2  # pragma: no cover


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    import sys

    # delegate to the CANONICAL module instance: under `python -m` this
    # file runs as `__main__`, but the kernel modules register their
    # warmers into `paddle_tpu.ops.autobench` — two module objects, two
    # _WARMERS dicts, so the CLI must drive the one the kernels see
    from paddle_tpu.ops import autobench as _canonical
    sys.exit(_canonical._main(sys.argv[1:]))
