"""One-shot per-shape kernel auto-benchmark gate.

Motivation (VERDICT r5 weak #1): the hand-written Pallas flash-attention
kernel measured 0.756x vs stock XLA at BERT seq-512 shapes while the
model hot path still ran it — a hand kernel must EARN its slot per
shape, not hold it by construction. This module provides the gate:

  winner = prefer(key, {"pallas": fn_a, "xla": fn_b}, make_args)

On first call for `key` (a hashable shape/dtype signature) each
candidate is jitted and timed on freshly made concrete inputs; the
fastest name is cached for the life of the process and every later
call for the same key returns instantly. The gate is invoked at
trace/first-call time from op kernels — Python side effects during a
jax trace run exactly once per compilation, so the measurement cost is
paid once per shape bucket, never per step.

Every decision is also recorded as structured telemetry
(paddle_tpu_autobench_* gauges on the process registry: candidate
timings + a winner flag per shape key) and logged through the
`paddle_tpu.autobench` logger — /metrics shows which kernel holds each
hot path without scraping stderr.

Env knobs:
  PADDLE_TPU_AUTOBENCH=0          disable measuring; `default` wins
  PADDLE_TPU_AUTOBENCH_FORCE=name force a candidate (debug/A-B runs)
  PADDLE_TPU_AUTOBENCH_VERBOSE=1  log-level switch: raises the
                                  `paddle_tpu.autobench` logger to INFO
                                  (with a stderr handler if the app
                                  configured none)
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable

from ..observability import registry as _obs

__all__ = ["prefer", "decisions", "clear"]

_CACHE: dict = {}
_LOCK = threading.Lock()

logger = logging.getLogger("paddle_tpu.autobench")

_CANDIDATE_MS = _obs.gauge(
    "paddle_tpu_autobench_candidate_ms",
    "measured median wall time per candidate per shape key",
    ["key", "candidate"])
_WINNER = _obs.gauge(
    "paddle_tpu_autobench_winner",
    "1 for the candidate holding the hot path of a shape key, else 0",
    ["key", "candidate"])


def _verbose_logging():
    """PADDLE_TPU_AUTOBENCH_VERBOSE kept as a LOG-LEVEL switch: it used
    to print to stderr; now it raises the module logger to INFO (adding
    a stderr handler only when logging is unconfigured)."""
    if not os.environ.get("PADDLE_TPU_AUTOBENCH_VERBOSE"):
        return
    if logger.getEffectiveLevel() > logging.INFO:
        logger.setLevel(logging.INFO)
    if not logger.handlers and not logging.getLogger().handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter("[autobench] %(message)s"))
        logger.addHandler(h)


def _record_decision(key, winner: str, timings: dict[str, float]):
    skey = str(key)
    for name, t in timings.items():
        _CANDIDATE_MS.labels(key=skey, candidate=name).set(
            round(t * 1e3, 4) if t < float("inf") else float("inf"))
        _WINNER.labels(key=skey, candidate=name).set(
            1.0 if name == winner else 0.0)
    _verbose_logging()
    ms = {k: round(v * 1e3, 3) for k, v in timings.items()}
    logger.info("%s -> %s %s", skey, winner, ms)


def _measure(fn: Callable, make_args: Callable, reps: int) -> float:
    """Median wall time of `fn(*make_args())` jitted, after one warmup
    call that also pays compilation. Separated out so tests can inject
    deterministic timings."""
    import jax

    args = make_args()
    jfn = jax.jit(fn)
    out = jax.block_until_ready(jfn(*args))
    del out
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(jfn(*args))
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def prefer(key, candidates: dict[str, Callable], make_args: Callable,
           default: str | None = None, reps: int = 3) -> str:
    """Return the name of the fastest candidate for `key`, measuring at
    most once per key per process.

    candidates: name -> nullary-composable fn taking make_args() outputs.
    make_args:  () -> tuple of concrete device arrays (built lazily, only
                on the measuring call).
    default:    winner when benchmarking is disabled (first name if None).
    """
    forced = os.environ.get("PADDLE_TPU_AUTOBENCH_FORCE")
    if forced and forced in candidates:
        return forced
    if default is None:
        default = next(iter(candidates))
    if os.environ.get("PADDLE_TPU_AUTOBENCH", "1") == "0":
        return default
    with _LOCK:
        hit = _CACHE.get(key)
    if hit is not None:
        return hit
    timings = {}
    for name, fn in candidates.items():
        try:
            timings[name] = _measure(fn, make_args, reps)
        except Exception:  # a candidate that errors never wins
            timings[name] = float("inf")
    winner = min(timings, key=timings.get)
    if not (timings[winner] < float("inf")):
        winner = default
    with _LOCK:
        # a racing thread may have decided already; first one wins so the
        # process is consistent
        winner = _CACHE.setdefault(key, winner)
    _record_decision(key, winner, timings)
    return winner


def decisions() -> dict:
    """Snapshot of the cached key -> winner map (for /stats, tests)."""
    with _LOCK:
        return dict(_CACHE)


def clear():
    with _LOCK:
        _CACHE.clear()
