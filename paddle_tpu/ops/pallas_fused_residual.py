"""Pallas TPU fused dropout + residual-add + layer_norm.

The transformer sublayer epilogue `LN(residual + dropout(x))` as ONE
VMEM pass each way. Reference equivalent: the fused skip-layernorm tier
(framework/ir/skip_layernorm_fuse_pass.cc,
operators/fused/fused_bn_activation and
fused_embedding_eltwise_layernorm). The forward reads x and residual and
writes y + the pre-LN sum (the backward residual); the backward fuses
the LN-dx reduction with a dropout-mask REPLAY (counter-based hash rng,
same scheme as the flash kernel) — no mask tensor ever exists in HBM.

Measured effect at BERT-base shapes (v5e): ~neutral at seq 128, ~+1% at
seq 512 — XLA's own fusion already handles this chain well; the kernel's
remaining value is the guaranteed fusion contract (independent of XLA
heuristics) and the in-kernel deterministic dropout. It stays behind
can_use_fused_dropout_add_ln with a composed fallback.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .pallas_attention import on_tpu
from .pallas_layer_norm import _pick_block

__all__ = ["fused_dropout_add_ln", "can_use_fused_dropout_add_ln"]


def _interpret() -> bool:
    return not on_tpu()


def can_use_fused_dropout_add_ln(rows: int, cols: int) -> bool:
    if os.environ.get("PADDLE_TPU_DISABLE_PALLAS"):
        return False
    if not (on_tpu() or os.environ.get("PADDLE_TPU_PALLAS_INTERPRET")):
        return False
    if cols % 128 or cols > 16384:
        return False
    return _pick_block(rows) is not None


def _keep(seed_ref, rows, cols, c_total, p):
    """murmur3-finalised counter mask over global element ids —
    identical forward/backward for any block partitioning."""
    x = (jnp.uint32(seed_ref[0])
         ^ ((rows * c_total + cols).astype(jnp.uint32)
            * jnp.uint32(0x85ebca6b)))
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85ebca6b)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xc2b2ae35)
    x = x ^ (x >> 16)
    thr = jnp.uint32(min(int(p * 4294967296.0), 4294967295))
    return x >= thr


def _ids(i, br, c):
    rows = jax.lax.broadcasted_iota(jnp.int32, (br, c), 0) + i * br
    cols = jax.lax.broadcasted_iota(jnp.int32, (br, c), 1)
    return rows, cols


def _fwd_kernel(seed_ref, x_ref, res_ref, scale_ref, bias_ref,
                y_ref, z_ref, mean_ref, rstd_ref, *, eps, p):
    i = pl.program_id(0)
    xv = x_ref[:].astype(jnp.float32)
    rv = res_ref[:].astype(jnp.float32)
    br, c = xv.shape
    if p > 0.0:
        rows, cols = _ids(i, br, c)
        keep = _keep(seed_ref, rows, cols, c, p)
        xv = jnp.where(keep, xv / (1.0 - p), 0.0)
    z = xv + rv
    mean = jnp.mean(z, axis=1, keepdims=True)
    var = jnp.mean(jnp.square(z - mean), axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    zhat = (z - mean) * rstd
    y = zhat * scale_ref[0].astype(jnp.float32)[None, :] \
        + bias_ref[0].astype(jnp.float32)[None, :]
    y_ref[:] = y.astype(y_ref.dtype)
    z_ref[:] = z.astype(z_ref.dtype)
    mean_ref[:] = jax.lax.broadcast_in_dim(mean[:, 0], (br, 128), (0,))
    rstd_ref[:] = jax.lax.broadcast_in_dim(rstd[:, 0], (br, 128), (0,))


def _bwd_kernel(seed_ref, z_ref, scale_ref, mean_ref, rstd_ref, dy_ref,
                dx_ref, dres_ref, *, p):
    i = pl.program_id(0)
    zv = z_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    br, c = zv.shape
    mean = mean_ref[:][:, 0:1]
    rstd = rstd_ref[:][:, 0:1]
    zhat = (zv - mean) * rstd
    a = dy * scale_ref[0].astype(jnp.float32)[None, :]
    c1 = jnp.mean(a, axis=1, keepdims=True)
    c2 = jnp.mean(a * zhat, axis=1, keepdims=True)
    dz = rstd * (a - c1 - zhat * c2)
    dres_ref[:] = dz.astype(dres_ref.dtype)
    if p > 0.0:
        rows, cols = _ids(i, br, c)
        keep = _keep(seed_ref, rows, cols, c, p)
        dx = jnp.where(keep, dz / (1.0 - p), 0.0)
    else:
        dx = dz
    dx_ref[:] = dx.astype(dx_ref.dtype)


def _smem_seed_spec():
    if _interpret():
        return pl.BlockSpec((1,), lambda i: (0,))
    from jax.experimental.pallas import tpu as pltpu
    return pl.BlockSpec(memory_space=pltpu.SMEM)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def fused_dropout_add_ln(x2d, res2d, scale, bias, seed_arr, p, eps):
    """y = LN(res + dropout_p(x)) * scale + bias, one kernel each way.

    x2d/res2d: (R, C); scale/bias: (C,); seed_arr: (1,) int32. p and eps
    are static. Gradients flow to x (mask-replayed), residual, scale,
    bias; never to seed."""
    y, _z, _mean, _rstd = _fwd_impl(x2d, res2d, scale, bias, seed_arr,
                                    p, eps)
    return y


def _fwd_impl(x2d, res2d, scale, bias, seed_arr, p, eps):
    r, c = x2d.shape
    br = _pick_block(r)
    y, z, mean_b, rstd_b = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, p=p),
        grid=(r // br,),
        in_specs=[
            _smem_seed_spec(),
            pl.BlockSpec((br, c), lambda i: (i, 0)),
            pl.BlockSpec((br, c), lambda i: (i, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, c), lambda i: (i, 0)),
            pl.BlockSpec((br, c), lambda i: (i, 0)),
            pl.BlockSpec((br, 128), lambda i: (i, 0)),
            pl.BlockSpec((br, 128), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((r, c), x2d.dtype),
            jax.ShapeDtypeStruct((r, c), x2d.dtype),
            jax.ShapeDtypeStruct((r, 128), jnp.float32),
            jax.ShapeDtypeStruct((r, 128), jnp.float32),
        ],
        interpret=_interpret())(
            seed_arr, x2d, res2d, scale.reshape(1, c), bias.reshape(1, c))
    return y, z, mean_b[:, 0], rstd_b[:, 0]


def _vjp_fwd(x2d, res2d, scale, bias, seed_arr, p, eps):
    y, z, mean, rstd = _fwd_impl(x2d, res2d, scale, bias, seed_arr, p,
                                 eps)
    return y, (z, scale, mean, rstd, seed_arr)


def _vjp_bwd(p, eps, res, dy):
    z, scale, mean, rstd, seed_arr = res
    r, c = z.shape
    br = _pick_block(r)
    mean_b = jnp.broadcast_to(mean[:, None], (r, 128))
    rstd_b = jnp.broadcast_to(rstd[:, None], (r, 128))
    dx, dres = pl.pallas_call(
        functools.partial(_bwd_kernel, p=p),
        grid=(r // br,),
        in_specs=[
            _smem_seed_spec(),
            pl.BlockSpec((br, c), lambda i: (i, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((br, 128), lambda i: (i, 0)),
            pl.BlockSpec((br, 128), lambda i: (i, 0)),
            pl.BlockSpec((br, c), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, c), lambda i: (i, 0)),
            pl.BlockSpec((br, c), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((r, c), z.dtype),
            jax.ShapeDtypeStruct((r, c), z.dtype),
        ],
        interpret=_interpret())(
            seed_arr, z, scale.reshape(1, c), mean_b, rstd_b, dy)
    zf = z.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    zhat = (zf - mean[:, None]) * rstd[:, None]
    dscale = jnp.sum(dyf * zhat, axis=0).astype(scale.dtype)
    dbias = jnp.sum(dyf, axis=0).astype(scale.dtype)
    return dx, dres, dscale, dbias, None


fused_dropout_add_ln.defvjp(_vjp_fwd, _vjp_bwd)


# ---------------------------------------------------------------------------
# autobench gate + warmer (PR-7 satellite: this kernel bypassed the
# measured gate — it now must beat the composed XLA epilogue per shape
# on TPU, with the decision persisted via the tuning cache)
# ---------------------------------------------------------------------------

def _composed_ref(x2d, res2d, scale, bias, seed_arr, p, eps):
    v = x2d.astype(jnp.float32)
    if p > 0.0:
        r, c = v.shape
        rows = jnp.broadcast_to(
            jnp.arange(r, dtype=jnp.int32)[:, None], (r, c))
        cols = jnp.broadcast_to(
            jnp.arange(c, dtype=jnp.int32)[None, :], (r, c))
        keep = _keep(seed_arr, rows, cols, c, p)
        v = jnp.where(keep, v / (1.0 - p), 0.0)
    z = v + res2d.astype(jnp.float32)
    mean = jnp.mean(z, -1, keepdims=True)
    var = jnp.mean(jnp.square(z - mean), -1, keepdims=True)
    zhat = (z - mean) * jax.lax.rsqrt(var + eps)
    return (zhat * scale + bias).astype(res2d.dtype)


def _gate_dropout_add_ln(rows, cols, dtype, p=0.0, eps=1e-5):
    import numpy as np
    dtype = jnp.dtype(dtype)
    key = ("fused_dropout_add_ln", rows, cols, str(dtype), round(p, 4))

    def make_args():
        rng = np.random.RandomState(0)
        return (jnp.asarray(rng.randn(rows, cols), dtype),
                jnp.asarray(rng.randn(rows, cols), dtype),
                jnp.ones((cols,), jnp.float32),
                jnp.zeros((cols,), jnp.float32),
                jnp.zeros((1,), jnp.int32))

    cands = {
        "pallas": lambda x, r, s, b, sd: fused_dropout_add_ln(
            x, r, s, b, sd, p, eps),
        "xla": lambda x, r, s, b, sd: _composed_ref(
            x, r, s, b, sd, p, eps),
    }
    return key, cands, make_args


def dropout_add_ln_wins(rows, cols, dtype, p=0.0, eps=1e-5) -> bool:
    if not on_tpu():
        return True
    from . import autobench
    key, cands, make_args = _gate_dropout_add_ln(rows, cols, dtype, p,
                                                 eps)
    return autobench.prefer(key, cands, make_args,
                            default="pallas") == "pallas"


def _warm_dropout_add_ln(spec: dict) -> str:
    from . import autobench
    key, cands, make_args = _gate_dropout_add_ln(
        int(spec["rows"]), int(spec["cols"]),
        spec.get("dtype", "bfloat16"), float(spec.get("p", 0.0)))
    return autobench.prefer(key, cands, make_args, default="pallas")


def _register_warmer():
    from . import autobench
    autobench.register_warmer("fused_dropout_add_ln",
                              _warm_dropout_add_ln)


_register_warmer()
