"""Pallas TPU flash (blockwise) attention — fwd + bwd kernels.

TPU-native replacement for the reference's fused attention CUDA tier
(/root/reference/paddle/fluid/operators/fused/multihead_matmul_op.cc:1,
/root/reference/paddle/fluid/operators/math/bert_encoder_functor.cu:1).
Design: online-softmax blockwise attention (flash attention) so the S×T
score matrix never materialises in HBM — Q blocks stream over K/V blocks
held in VMEM, accumulating in f32 on the MXU. Backward recomputes P from
the saved logsumexp (no S×T residual), with split dQ and dK/dV kernels.

Dropout runs INSIDE the kernel via a counter-based hash (murmur3
finaliser) of each score's global (batch·head, row, col) id, so forward
and backward regenerate the identical keep mask without ever materialising
it — and independently of block-size choices.

Numerical contract: matches `sdpa_reference` (jnp) to bf16 tolerance;
exercised by tests/test_pallas_kernels.py in interpret mode on CPU and by
the bench on real TPU.
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports fine on CPU hosts too (needed for interpret mode)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = ["flash_attention", "can_use_flash", "on_tpu"]

_NEG_INF = -1e30


def on_tpu() -> bool:
    try:
        plat = jax.devices()[0].platform
    except Exception:  # pragma: no cover
        return False
    return plat in ("tpu", "axon")


def _interpret() -> bool:
    return not on_tpu()


def _auto_block(n, env_name):
    """Largest block in (512, 256, 128, 64) dividing n, overridable via
    the env var. Measured end-to-end on v5e (BERT-base seq-512 train
    step): (512,512) @ 26.8% MFU beats (128,512) @ 24.5% — an isolated
    attention microbench prefers 128 q-blocks, but inside the fused step
    the extra grid iterations lose."""
    env = os.environ.get(env_name)
    if env and n % int(env) == 0:
        return int(env)
    for b in (512, 256, 128, 64):
        if n % b == 0:
            return b
    return None


def _auto_block_q(n):
    return _auto_block(n, "PADDLE_TPU_FLASH_BLOCK_Q")


def _auto_block_k(n):
    return _auto_block(n, "PADDLE_TPU_FLASH_BLOCK_K")


def can_use_flash(q, k, v, mask, dropout_p=0.0, block_q=None,
                  block_k=None) -> bool:
    """Gate for the Pallas path: TPU (or interpret-mode tests), block-aligned
    sequence lengths, and a padding-style mask (B,1,1,T) or none."""
    if os.environ.get("PADDLE_TPU_DISABLE_PALLAS"):
        return False
    if not (on_tpu() or os.environ.get("PADDLE_TPU_PALLAS_INTERPRET")):
        return False
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        return False
    s, d = q.shape[2], q.shape[3]
    t = k.shape[2]
    block_q = block_q or _auto_block_q(s)
    block_k = block_k or _auto_block_k(t)
    if block_q is None or block_k is None:
        return False
    if s % block_q or t % block_k or d % 8 or d > 256:
        return False
    if mask is not None:
        # only padding-style masks: (B,1,1,T) matching q's batch and k's
        # length exactly (broadcastable variants fall back to sdpa)
        if (mask.ndim != 4 or mask.shape[1] != 1 or mask.shape[2] != 1 or
                mask.shape[0] != q.shape[0] or mask.shape[3] != t):
            return False
    return True


# ---------------------------------------------------------------------------
# kernels. Layouts: q/k/v/do (BH, S|T, D); lse/delta (BH, S, 128)
# lane-broadcast f32; mask (B, 8, T) sublane-broadcast additive; seed
# (1,) int32 in SMEM. The 128/8 broadcasts satisfy TPU min-tile rules
# (same trick as the stock jax flash kernel's l/m residuals).
# ---------------------------------------------------------------------------

def _keep_mask(seed_ref, bh, rows, cols, t, dropout_p):
    """Deterministic per-element keep mask: murmur3-finalise a counter
    built from the global element id. Works identically on TPU and in
    interpret mode (no pltpu.prng dependency), and identically between
    forward and backward whatever the block partitioning."""
    salt = (jnp.uint32(bh) * jnp.uint32(0x9e3779b9) +
            jnp.uint32(seed_ref[0]))
    x = salt ^ ((rows * t + cols).astype(jnp.uint32) *
                jnp.uint32(0x85ebca6b))
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85ebca6b)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xc2b2ae35)
    x = x ^ (x >> 16)
    thr = jnp.uint32(min(int(dropout_p * 4294967296.0), 4294967295))
    return x >= thr


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, *,
                scale, causal, block_k, dropout_p):
    bh, iq = pl.program_id(0), pl.program_id(1)
    q = q_ref[0]                                        # (Bq, D) native dtype
    bq, d = q.shape
    t = k_ref.shape[1]
    nk = t // block_k
    hi = jnp.minimum(jax.lax.div((iq + 1) * bq + block_k - 1, block_k), nk) \
        if causal else nk

    def body(j, carry):
        acc, m_i, l_i = carry
        kblk = k_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if mask_ref is not None:
            s = s + mask_ref[0, 0:1, pl.ds(j * block_k, block_k)] \
                .astype(jnp.float32)
        rows = iq * bq + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 0)
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        if causal:
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_i - m_new)
        l_new = alpha * l_i + jnp.sum(p, axis=-1)
        if dropout_p > 0.0:
            keep = _keep_mask(seed_ref, bh, rows, cols, t, dropout_p)
            p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
        vblk = v_ref[0, pl.ds(j * block_k, block_k), :]
        acc = acc * alpha[:, None] + jnp.dot(
            p.astype(vblk.dtype), vblk, preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc, m_i, l_i = jax.lax.fori_loop(
        0, hi, body, (jnp.zeros((bq, d), jnp.float32),
                      jnp.full((bq,), _NEG_INF, jnp.float32),
                      jnp.zeros((bq,), jnp.float32)))
    l_safe = jnp.where(l_i == 0.0, 1.0, l_i)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lane-broadcast to 128 (TPU min tile; same layout as the stock jax
    # flash kernel's l/m residuals)
    lse_ref[0] = jax.lax.broadcast_in_dim(
        m_i + jnp.log(l_safe), (bq, 128), (0,))


def _recompute_p(q, kblk, scale, mask_blk, lse_col, causal, rows, cols):
    s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if mask_blk is not None:
        s = s + mask_blk
    if causal:
        s = jnp.where(rows >= cols, s, _NEG_INF)
    return jnp.exp(s - lse_col)


def _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   mask_ref, dq_ref, *, scale, causal, block_k, dropout_p):
    bh, iq = pl.program_id(0), pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse_col = lse_ref[0][:, 0:1]
    delta_col = delta_ref[0][:, 0:1]
    bq, d = q.shape
    t = k_ref.shape[1]
    nk = t // block_k
    hi = jnp.minimum(jax.lax.div((iq + 1) * bq + block_k - 1, block_k), nk) \
        if causal else nk

    def body(j, dq):
        kblk = k_ref[0, pl.ds(j * block_k, block_k), :]
        vblk = v_ref[0, pl.ds(j * block_k, block_k), :]
        mask_blk = None
        if mask_ref is not None:
            mask_blk = mask_ref[0, 0:1, pl.ds(j * block_k, block_k)] \
                .astype(jnp.float32)
        rows = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        p = _recompute_p(q, kblk, scale, mask_blk, lse_col, causal, rows,
                         cols)
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            keep = _keep_mask(seed_ref, bh, rows, cols, t, dropout_p)
            dp = jnp.where(keep, dp / (1.0 - dropout_p), 0.0)
        ds = (p * (dp - delta_col) * scale).astype(kblk.dtype)
        return dq + jnp.dot(ds, kblk, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, hi, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    mask_ref, dk_ref, dv_ref, *, scale, causal, block_q,
                    dropout_p):
    bh, jk = pl.program_id(0), pl.program_id(1)
    nk = pl.num_programs(1)
    kblk = k_ref[0]                                     # (Bk, D) native
    vblk = v_ref[0]
    bk, d = kblk.shape
    s_len = q_ref.shape[1]
    s_len_t = nk * bk  # kv length (hash uses row*T+col global ids)
    nq = s_len // block_q
    mask_blk = mask_ref[0, 0:1, :].astype(jnp.float32) \
        if mask_ref is not None else None
    lo = jax.lax.div(jk * bk, block_q) if causal else 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse_col = lse_ref[0, pl.ds(i * block_q, block_q), 0:1]
        delta_col = delta_ref[0, pl.ds(i * block_q, block_q), 0:1]
        rows = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, bk), 0)
        cols = jk * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
        p = _recompute_p(q, kblk, scale, mask_blk, lse_col, causal, rows,
                         cols)
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            keep = _keep_mask(seed_ref, bh, rows, cols, s_len_t, dropout_p)
            pd = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
            dp = jnp.where(keep, dp / (1.0 - dropout_p), 0.0)
        else:
            pd = p
        dv = dv + jax.lax.dot_general(pd.astype(do.dtype), do,
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_col) * scale).astype(q.dtype)
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, nq, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# custom-vjp wrapper
# ---------------------------------------------------------------------------

def _smem_seed_spec():
    if pltpu is not None:
        return pl.BlockSpec(memory_space=pltpu.SMEM)
    return pl.BlockSpec(memory_space=pl.ANY)  # pragma: no cover


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q3, k3, v3, mask2, seed_arr, scale, causal, block_q, block_k,
           dropout_p):
    o, _ = _flash_fwd_impl(q3, k3, v3, mask2, seed_arr, scale, causal,
                           block_q, block_k, dropout_p)
    return o


def _flash_fwd_impl(q3, k3, v3, mask2, seed_arr, scale, causal, block_q,
                    block_k, dropout_p):
    """q3,k3,v3: (BH, S, D); mask2: (B, 8, T) additive or None."""
    bh, s, d = q3.shape
    t = k3.shape[1]
    heads = bh // mask2.shape[0] if mask2 is not None else 1
    in_specs = [
        _smem_seed_spec(),
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
    ]
    args = [seed_arr, q3, k3, v3]
    if mask2 is not None:
        in_specs.append(
            pl.BlockSpec((1, 8, t), lambda b, i: (b // heads, 0, 0)))
        args.append(mask2)

        def kfn(seed_ref, q_ref, k_ref, v_ref, m_ref, o_ref, lse_ref):
            _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, m_ref, o_ref, lse_ref,
                        scale=scale, causal=causal, block_k=block_k,
                        dropout_p=dropout_p)
    else:
        def kfn(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref):
            _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, None, o_ref, lse_ref,
                        scale=scale, causal=causal, block_k=block_k,
                        dropout_p=dropout_p)

    o, lse = pl.pallas_call(
        kfn, grid=(bh, s // block_q), in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, s, 128), jnp.float32),
        ],
        interpret=_interpret())(*args)
    return o, lse


def _flash_fwd(q3, k3, v3, mask2, seed_arr, scale, causal, block_q,
               block_k, dropout_p):
    o, lse = _flash_fwd_impl(q3, k3, v3, mask2, seed_arr, scale, causal,
                             block_q, block_k, dropout_p)
    return o, (q3, k3, v3, mask2, seed_arr, o, lse)


def _flash_bwd(scale, causal, block_q, block_k, dropout_p, res, g):
    q3, k3, v3, mask2, seed_arr, o, lse = res
    bh, s, d = q3.shape
    t = k3.shape[1]
    heads = bh // mask2.shape[0] if mask2 is not None else 1
    delta = jnp.broadcast_to(
        jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                axis=-1, keepdims=True), (bh, s, 128))

    dq_in = [
        _smem_seed_spec(),
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),   # q
        pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),         # k
        pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),         # v
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),   # do
        pl.BlockSpec((1, block_q, 128), lambda b, i: (b, i, 0)),  # lse
        pl.BlockSpec((1, block_q, 128), lambda b, i: (b, i, 0)),  # delta
    ]
    dq_args = [seed_arr, q3, k3, v3, g, lse, delta]
    if mask2 is not None:
        dq_in.append(
            pl.BlockSpec((1, 8, t), lambda b, i: (b // heads, 0, 0)))
        dq_args.append(mask2)

        def dq_kfn(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   m_ref, dq_ref):
            _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                           delta_ref, m_ref, dq_ref, scale=scale,
                           causal=causal, block_k=block_k,
                           dropout_p=dropout_p)
    else:
        def dq_kfn(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref):
            _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                           delta_ref, None, dq_ref, scale=scale,
                           causal=causal, block_k=block_k,
                           dropout_p=dropout_p)

    dq = pl.pallas_call(
        dq_kfn, grid=(bh, s // block_q), in_specs=dq_in,
        out_specs=[pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((bh, s, d), q3.dtype)],
        interpret=_interpret())(*dq_args)[0]

    kv_in = [
        _smem_seed_spec(),
        pl.BlockSpec((1, s, d), lambda b, j: (b, 0, 0)),         # q full
        pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),   # k block
        pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),   # v block
        pl.BlockSpec((1, s, d), lambda b, j: (b, 0, 0)),         # do full
        pl.BlockSpec((1, s, 128), lambda b, j: (b, 0, 0)),       # lse
        pl.BlockSpec((1, s, 128), lambda b, j: (b, 0, 0)),       # delta
    ]
    kv_args = [seed_arr, q3, k3, v3, g, lse, delta]
    if mask2 is not None:
        kv_in.append(
            pl.BlockSpec((1, 8, block_k), lambda b, j: (b // heads, 0, j)))
        kv_args.append(mask2)

        def dkv_kfn(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    m_ref, dk_ref, dv_ref):
            _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                            delta_ref, m_ref, dk_ref, dv_ref, scale=scale,
                            causal=causal, block_q=block_q,
                            dropout_p=dropout_p)
    else:
        def dkv_kfn(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref):
            _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                            delta_ref, None, dk_ref, dv_ref, scale=scale,
                            causal=causal, block_q=block_q,
                            dropout_p=dropout_p)

    dk, dv = pl.pallas_call(
        dkv_kfn, grid=(bh, t // block_k), in_specs=kv_in,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v3.dtype),
        ],
        interpret=_interpret())(*kv_args)
    return dq, dk, dv, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, mask=None, scale=None, causal=False,
                    dropout_p=0.0, dropout_seed=0, block_q=None,
                    block_k=None):
    """q,k,v: (B,H,S,D); mask: additive (B,1,1,T) or None. Returns (B,H,S,D).

    The Pallas path; call `can_use_flash` first. On non-TPU hosts the same
    kernels run in interpreter mode (slow — tests only).
    """
    b, h, s, d = q.shape
    t = k.shape[2]
    block_q = block_q or _auto_block_q(s)
    block_k = block_k or _auto_block_k(t)
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    q3 = q.reshape(b * h, s, d)
    k3 = k.reshape(b * h, t, d)
    v3 = v.reshape(b * h, t, d)
    mask2 = None
    if mask is not None:
        mask2 = jnp.broadcast_to(mask.reshape(b, 1, t), (b, 8, t))
    seed_arr = jnp.asarray(dropout_seed, jnp.int32).reshape(1)
    o = _flash(q3, k3, v3, mask2, seed_arr, float(scale), bool(causal),
               int(block_q), int(block_k), float(dropout_p))
    return o.reshape(b, h, s, d)
