"""paddle.optimizer — 2.0 optimizers (reference python/paddle/optimizer/).

Dual-mode: in dygraph `step()` runs the SAME registered optimizer-op kernels
eagerly over (param, grad, accumulators); in static mode they delegate to the
fluid optimizer machinery (append ops to the Program).
"""
from __future__ import annotations

import numpy as np

from . import lr as lr  # noqa: F401
from .lr import LRScheduler
from .. import fluid
from ..fluid import optimizer as fopt
from ..fluid import registry
from ..fluid.framework import in_dygraph_mode
from ..fluid.dygraph.varbase import Tensor

__all__ = ["Optimizer", "SGD", "Momentum", "Adagrad", "Adam", "AdamW",
           "Adamax", "RMSProp", "Adadelta", "Lamb", "lr"]


class Optimizer:
    _op_type = None
    _static_cls = None

    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **op_attrs):
        self._learning_rate = learning_rate
        self._parameters = list(parameters) if parameters is not None else None
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._op_attrs = op_attrs
        self._accum: dict[str, dict[str, object]] = {}
        self._static = None

    # -- lr ----------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        self._learning_rate = float(value)
        if self._static is not None:
            self._static.set_lr(value)

    # -- static-mode delegation ---------------------------------------------
    def _static_optimizer(self):
        if self._static is None:
            reg = None
            if isinstance(self._weight_decay, (int, float)):
                from ..fluid.regularizer import L2Decay
                reg = L2Decay(float(self._weight_decay))
            elif self._weight_decay is not None:
                reg = self._weight_decay
            lr_val = self.get_lr() if isinstance(
                self._learning_rate, LRScheduler) else self._learning_rate
            self._static = self._make_static(lr_val, reg)
            if isinstance(self._learning_rate, LRScheduler):
                self._wire_scheduler_to_scope(self._learning_rate,
                                              self._static)
        return self._static

    @staticmethod
    def _wire_scheduler_to_scope(sched: LRScheduler, static_opt):
        """In static mode the LR lives in a scope var; hook scheduler.step()
        so each host-side step writes the new value into that var."""
        if getattr(sched, "_scope_wired", False):
            return
        orig_step = sched.step

        def step(*a, **kw):
            orig_step(*a, **kw)
            if static_opt._lr_var is not None:
                static_opt.set_lr(sched.last_lr)
        sched.step = step
        sched._scope_wired = True

    def _make_static(self, lr_val, reg):
        return self._static_cls(learning_rate=lr_val, regularization=reg,
                                grad_clip=self._grad_clip,
                                **self._static_kwargs())

    def _static_kwargs(self):
        return {}

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        if in_dygraph_mode():
            self.step()
            return None, None
        return self._static_optimizer().minimize(
            loss, startup_program, parameters or self._parameters,
            no_grad_set)

    # -- dygraph step --------------------------------------------------------
    def _accumulators_for(self, p: Tensor) -> dict:
        raise NotImplementedError

    def _op_inputs(self, p, g, acc, lr):
        raise NotImplementedError

    def _apply_outputs(self, p, acc, outs):
        raise NotImplementedError

    def step(self):
        import jax.numpy as jnp
        if self._parameters is None:
            raise ValueError("pass parameters= to the optimizer in dygraph")
        params_grads = [(p, p.grad) for p in self._parameters
                        if p.trainable and p.grad is not None]
        if self._grad_clip is not None:
            # eager clip works on Tensors
            pgs = [(p, g) for p, g in params_grads]
            params_grads = self._grad_clip(pgs)
        lr = jnp.asarray([self.get_lr()], dtype=jnp.float32)
        opdef = registry.require(self._op_type)
        wd = self._weight_decay
        for p, g in params_grads:
            gval = g._value if isinstance(g, Tensor) else jnp.asarray(g)
            if wd is not None and not isinstance(self, AdamW) and \
                    isinstance(wd, (int, float)):
                gval = gval + float(wd) * p._value
            acc = self._accumulators_for(p)
            ins = self._op_inputs(p, gval, acc, lr)
            outs = opdef.compute(None, ins, dict(self._op_attrs))
            self._apply_outputs(p, acc, outs)

    def clear_grad(self):
        for p in (self._parameters or []):
            if isinstance(p, Tensor):
                p.clear_gradient()

    clear_gradients = clear_grad

    # -- state ---------------------------------------------------------------
    def state_dict(self):
        from ..fluid import core
        sd = core.batched_to_numpy_dict(
            [(f"{pname}_{aname}", val)
             for pname, accs in self._accum.items()
             for aname, val in accs.items()])
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        if self._static is not None:
            sd.update(self._static.state_dict())
        return sd

    def set_state_dict(self, sd):
        import jax.numpy as jnp
        for pname, accs in self._accum.items():
            for aname in list(accs):
                k = f"{pname}_{aname}"
                if k in sd:
                    accs[aname] = jnp.asarray(sd[k])
        if "LR_Scheduler" in sd and isinstance(self._learning_rate,
                                               LRScheduler):
            self._learning_rate.set_state_dict(sd["LR_Scheduler"])
        if self._static is not None:
            self._static.set_state_dict(
                {k: v for k, v in sd.items() if k != "LR_Scheduler"})

    load_state_dict = set_state_dict


class SGD(Optimizer):
    _op_type = "sgd"
    _static_cls = fopt.SGDOptimizer

    def _accumulators_for(self, p):
        return {}

    def _op_inputs(self, p, g, acc, lr):
        return {"Param": [p._value], "Grad": [g], "LearningRate": [lr]}

    def _apply_outputs(self, p, acc, outs):
        p._set_value(outs["ParamOut"][0])


class Momentum(Optimizer):
    _op_type = "momentum"
    _static_cls = fopt.MomentumOptimizer

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, mu=momentum, use_nesterov=use_nesterov)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _static_kwargs(self):
        return {"momentum": self._momentum,
                "use_nesterov": self._use_nesterov}

    def _make_static(self, lr_val, reg):
        return fopt.MomentumOptimizer(lr_val, self._momentum,
                                      self._use_nesterov,
                                      regularization=reg,
                                      grad_clip=self._grad_clip)

    def _accumulators_for(self, p):
        import jax.numpy as jnp
        a = self._accum.setdefault(p.name, {})
        if "velocity" not in a:
            a["velocity"] = jnp.zeros_like(p._value)
        return a

    def _op_inputs(self, p, g, acc, lr):
        return {"Param": [p._value], "Grad": [g],
                "Velocity": [acc["velocity"]], "LearningRate": [lr]}

    def _apply_outputs(self, p, acc, outs):
        p._set_value(outs["ParamOut"][0])
        acc["velocity"] = outs["VelocityOut"][0]


class Adam(Optimizer):
    _op_type = "adam"
    _static_cls = fopt.AdamOptimizer

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, beta1=beta1, beta2=beta2, epsilon=epsilon)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _make_static(self, lr_val, reg):
        return self._static_cls(lr_val, self._beta1, self._beta2,
                                self._epsilon, regularization=reg,
                                grad_clip=self._grad_clip)

    def _accumulators_for(self, p):
        import jax.numpy as jnp
        a = self._accum.setdefault(p.name, {})
        if "moment1" not in a:
            a["moment1"] = jnp.zeros(p.shape, jnp.float32)
            a["moment2"] = jnp.zeros(p.shape, jnp.float32)
            a["beta1_pow"] = jnp.ones((1,), jnp.float32)
            a["beta2_pow"] = jnp.ones((1,), jnp.float32)
        return a

    def _op_inputs(self, p, g, acc, lr):
        return {"Param": [p._value], "Grad": [g], "LearningRate": [lr],
                "Moment1": [acc["moment1"]], "Moment2": [acc["moment2"]],
                "Beta1Pow": [acc["beta1_pow"]],
                "Beta2Pow": [acc["beta2_pow"]]}

    def _apply_outputs(self, p, acc, outs):
        p._set_value(outs["ParamOut"][0])
        acc["moment1"] = outs["Moment1Out"][0]
        acc["moment2"] = outs["Moment2Out"][0]
        acc["beta1_pow"] = outs["Beta1PowOut"][0]
        acc["beta2_pow"] = outs["Beta2PowOut"][0]


class AdamW(Adam):
    _op_type = "adamw"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, name=name)
        self._coeff = weight_decay if isinstance(weight_decay, float) \
            else 0.01
        self._apply_decay_fn = apply_decay_param_fun
        self._op_attrs.update(coeff=self._coeff)

    def _op_inputs(self, p, g, acc, lr):
        ins = super()._op_inputs(p, g, acc, lr)
        with_decay = self._apply_decay_fn is None or \
            self._apply_decay_fn(p.name)
        self._op_attrs["with_decay"] = bool(with_decay)
        return ins

    def _make_static(self, lr_val, reg):
        # static AdamW = adam + decoupled decay via regularizer-free coeff
        class _StaticAdamW(fopt.AdamOptimizer):
            def __init__(s, *a, coeff=0.0, **kw):
                super().__init__(*a, **kw)
                s._coeff = coeff

            def _append_optimize_op(s, block, pg):
                p, g = pg
                return block.append_op(
                    type="adamw", inputs=s._adam_inputs(p, g),
                    outputs=s._adam_outputs(p),
                    attrs={"beta1": s._beta1, "beta2": s._beta2,
                           "epsilon": s._epsilon, "coeff": s._coeff})
        return _StaticAdamW(lr_val, self._beta1, self._beta2, self._epsilon,
                            grad_clip=self._grad_clip, coeff=self._coeff)


class Adagrad(Optimizer):
    _op_type = "adagrad"
    _static_cls = fopt.AdagradOptimizer

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, epsilon=epsilon)
        self._epsilon = epsilon

    def _make_static(self, lr_val, reg):
        return fopt.AdagradOptimizer(lr_val, self._epsilon,
                                     regularization=reg,
                                     grad_clip=self._grad_clip)

    def _accumulators_for(self, p):
        import jax.numpy as jnp
        a = self._accum.setdefault(p.name, {})
        if "moment" not in a:
            a["moment"] = jnp.zeros(p.shape, jnp.float32)
        return a

    def _op_inputs(self, p, g, acc, lr):
        return {"Param": [p._value], "Grad": [g], "Moment": [acc["moment"]],
                "LearningRate": [lr]}

    def _apply_outputs(self, p, acc, outs):
        p._set_value(outs["ParamOut"][0])
        acc["moment"] = outs["MomentOut"][0]


class Adamax(Optimizer):
    _op_type = "adamax"
    _static_cls = fopt.AdamaxOptimizer

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, beta1=beta1, beta2=beta2, epsilon=epsilon)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _make_static(self, lr_val, reg):
        return fopt.AdamaxOptimizer(lr_val, self._beta1, self._beta2,
                                    self._epsilon, regularization=reg,
                                    grad_clip=self._grad_clip)

    def _accumulators_for(self, p):
        import jax.numpy as jnp
        a = self._accum.setdefault(p.name, {})
        if "moment" not in a:
            a["moment"] = jnp.zeros(p.shape, jnp.float32)
            a["inf_norm"] = jnp.zeros(p.shape, jnp.float32)
            a["beta1_pow"] = jnp.full((1,), self._beta1, jnp.float32)
        return a

    def _op_inputs(self, p, g, acc, lr):
        return {"Param": [p._value], "Grad": [g], "LearningRate": [lr],
                "Moment": [acc["moment"]], "InfNorm": [acc["inf_norm"]],
                "Beta1Pow": [acc["beta1_pow"]]}

    def _apply_outputs(self, p, acc, outs):
        p._set_value(outs["ParamOut"][0])
        acc["moment"] = outs["MomentOut"][0]
        acc["inf_norm"] = outs["InfNormOut"][0]
        acc["beta1_pow"] = acc["beta1_pow"] * self._beta1


class RMSProp(Optimizer):
    _op_type = "rmsprop"
    _static_cls = fopt.RMSPropOptimizer

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, decay=rho, epsilon=epsilon, momentum=momentum,
                         centered=centered)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _make_static(self, lr_val, reg):
        return fopt.RMSPropOptimizer(lr_val, self._rho, self._epsilon,
                                     self._momentum, self._centered,
                                     regularization=reg,
                                     grad_clip=self._grad_clip)

    def _accumulators_for(self, p):
        import jax.numpy as jnp
        a = self._accum.setdefault(p.name, {})
        if "mean_square" not in a:
            a["mean_square"] = jnp.zeros(p.shape, jnp.float32)
            a["moment"] = jnp.zeros(p.shape, jnp.float32)
            a["mean_grad"] = jnp.zeros(p.shape, jnp.float32)
        return a

    def _op_inputs(self, p, g, acc, lr):
        return {"Param": [p._value], "Grad": [g], "LearningRate": [lr],
                "MeanSquare": [acc["mean_square"]], "Moment": [acc["moment"]],
                "MeanGrad": [acc["mean_grad"]]}

    def _apply_outputs(self, p, acc, outs):
        p._set_value(outs["ParamOut"][0])
        acc["mean_square"] = outs["MeanSquareOut"][0]
        acc["moment"] = outs["MomentOut"][0]
        if "MeanGradOut" in outs:
            acc["mean_grad"] = outs["MeanGradOut"][0]


class Adadelta(Optimizer):
    _op_type = "adadelta"
    _static_cls = fopt.AdadeltaOptimizer

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, rho=rho, epsilon=epsilon)
        self._rho, self._epsilon = rho, epsilon

    def _make_static(self, lr_val, reg):
        return fopt.AdadeltaOptimizer(lr_val, self._epsilon, self._rho,
                                      regularization=reg,
                                      grad_clip=self._grad_clip)

    def _accumulators_for(self, p):
        import jax.numpy as jnp
        a = self._accum.setdefault(p.name, {})
        if "avg_sq_grad" not in a:
            a["avg_sq_grad"] = jnp.zeros(p.shape, jnp.float32)
            a["avg_sq_upd"] = jnp.zeros(p.shape, jnp.float32)
        return a

    def _op_inputs(self, p, g, acc, lr):
        return {"Param": [p._value], "Grad": [g],
                "AvgSquaredGrad": [acc["avg_sq_grad"]],
                "AvgSquaredUpdate": [acc["avg_sq_upd"]]}

    def _apply_outputs(self, p, acc, outs):
        p._set_value(outs["ParamOut"][0])
        acc["avg_sq_grad"] = outs["AvgSquaredGradOut"][0]
        acc["avg_sq_upd"] = outs["AvgSquaredUpdateOut"][0]


class Lamb(Adam):
    _op_type = "lamb"
    _static_cls = fopt.LambOptimizer

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, name=name)
        self._op_attrs.update(weight_decay=lamb_weight_decay)
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _make_static(self, lr_val, reg):
        return fopt.LambOptimizer(
            lr_val, self._lamb_wd, self._beta1, self._beta2, self._epsilon,
            exclude_from_weight_decay_fn=self._exclude_fn,
            grad_clip=self._grad_clip)
