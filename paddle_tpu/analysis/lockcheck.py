"""Runtime lock-order sanitizer (test mode).

Under ``PADDLE_TPU_LOCKCHECK=1`` (installed by ``paddle_tpu/__init__``
before any framework lock exists), ``threading.Lock``/``RLock``/
``Condition`` created from instrumented modules return checking
proxies. Every acquisition records per-thread held-lock state and adds
``held-site -> acquired-site`` edges to a process-global acquisition
graph; an acquisition that would close a CYCLE in that graph — the
static ``lock-order`` rule's exact failure shape, observed live —
raises ``LockOrderError`` before blocking (or warns once per pair with
``PADDLE_TPU_LOCKCHECK=warn``).

Lock identity is the CREATION SITE (``file:line``), so every
``Engine`` instance's step lock is one node — the same aggregation the
static model uses (``ClassName._lock``), which keeps the two reports
alignable and makes cross-instance inversions of the same two classes
detectable from a single run. Same-site edges are skipped (an RLock
re-entry, or hand-over-hand between two instances of one class, is
not an inversion the site graph can judge).

Scope: only locks created from modules whose ``__name__`` starts with
an instrumented prefix (default ``paddle_tpu``; extend via
``PADDLE_TPU_LOCKCHECK_SCOPE=pfx1,pfx2``) are wrapped — stdlib/jax
internals keep raw primitives, bounding both overhead and proxy-
compatibility risk. The dynamic graph covers what the static rule
cannot see (callbacks, locks passed across objects); the static rule
covers paths no test executes. They meet in tier-1: the instrumented
test_slo_harness run must hold zero cycles.
"""
from __future__ import annotations

import _thread
import os
import sys
import threading
from contextlib import contextmanager

__all__ = ["LockOrderError", "install", "uninstall", "installed",
           "reset", "graph", "violations", "report",
           "checked_lock", "checked_rlock", "checked_condition"]

_real_Lock = threading.Lock
_real_RLock = threading.RLock
_real_Condition = threading.Condition

_DEFAULT_SCOPE = ("paddle_tpu",)

# process-global state, guarded by a RAW lock (never a proxy)
_state_lock = _thread.allocate_lock()
_edges: dict[str, set[str]] = {}          # site -> sites acquired under
_edge_witness: dict[tuple, str] = {}      # (a, b) -> description
_violations: list[dict] = []
_warned_pairs: set[tuple] = set()
_tls = threading.local()
_installed = False
_mode = "raise"
_scope: tuple = _DEFAULT_SCOPE


class LockOrderError(RuntimeError):
    """An acquisition closed a cycle in the lock-acquisition graph."""


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _site_of_caller() -> str:
    """file:line of the first frame outside this module and the
    threading machinery."""
    f = sys._getframe(2)
    while f is not None:
        g = f.f_globals.get("__name__", "")
        if g not in (__name__, "threading"):
            fn = f.f_code.co_filename
            return f"{os.path.basename(fn)}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _caller_in_scope() -> bool:
    f = sys._getframe(2)
    while f is not None:
        g = f.f_globals.get("__name__", "")
        if g not in (__name__, "threading"):
            return g.startswith(_scope)
        f = f.f_back
    return False


def _find_path(graph_: dict, src: str, dst: str) -> list[str] | None:
    """DFS path src -> dst in the edge graph (None if unreachable)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in sorted(graph_.get(node, ())):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


@contextmanager
def _bookkeeping():
    """_state_lock plus a thread-local reentrancy flag. GC can run a
    weakref/finalizer callback at any allocation — including inside
    this critical section — and if that callback acquires an
    instrumented lock, _note_acquired re-enters on the same thread and
    would self-deadlock on the raw _state_lock. The flag lets the
    nested call detect that and skip recording instead."""
    _tls.in_bookkeeping = True
    try:
        with _state_lock:
            yield
    finally:
        _tls.in_bookkeeping = False


def _note_acquired(site: str, inst_id: int):
    """Record edges held -> site; detect would-be cycles. Runs BEFORE
    the real acquire so a detected inversion raises without blocking."""
    if getattr(_tls, "in_bookkeeping", False):
        # re-entered from a GC-triggered callback while this thread is
        # already inside the sanitizer's critical section; recording
        # would deadlock on _state_lock, so skip this acquisition
        return
    held = _held()
    held_sites = [s for s, _i, _n in held]
    with _bookkeeping():
        for h in held_sites:
            if h == site:
                continue
            # adding h -> site: a path site ->* h means a cycle
            path = _find_path(_edges, site, h)
            if path is not None:
                cycle = [h] + path
                v = {"cycle": cycle,
                     "thread": threading.current_thread().name,
                     "acquiring": site, "holding": held_sites,
                     # string keys: report() promises JSON-safe
                     "witness": {f"{a} -> {b}":
                                 _edge_witness.get((a, b), "")
                                 for a, b in zip(path, path[1:])}}
                pair_key = (h, site)
                _violations.append(v)
                if _mode == "raise":
                    raise LockOrderError(
                        "lock-order cycle: acquiring "
                        f"{site} while holding {h}, but the "
                        "acquisition graph already orders "
                        + " -> ".join(path)
                        + f" (thread {v['thread']}; see "
                        "docs/STATIC_ANALYSIS.md lockcheck)")
                if pair_key not in _warned_pairs:
                    _warned_pairs.add(pair_key)
                    print(f"PADDLE_TPU_LOCKCHECK: lock-order cycle "
                          f"{' -> '.join(cycle)} "
                          f"(thread {v['thread']})",
                          file=sys.stderr)
            _edges.setdefault(h, set()).add(site)
            _edge_witness.setdefault(
                (h, site),
                f"thread {threading.current_thread().name}")


class _CheckedLock:
    """Order-checking proxy over a real Lock/RLock. Tracks per-thread
    hold counts (RLock re-entry must not re-record), and exposes the
    RLock internals Condition needs (_release_save/_acquire_restore/
    _is_owned) with held-state maintenance."""

    def __init__(self, inner, site: str):
        self._inner = inner
        self._site = site

    # -- held bookkeeping ------------------------------------------------
    def _entry(self):
        for e in _held():
            if e[1] == id(self):
                return e
        return None

    def _push(self):
        e = self._entry()
        if e is None:
            _held().append([self._site, id(self), 1])
        else:
            e[2] += 1

    def _pop(self, fully: bool = False):
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] == id(self):
                if fully:
                    held[i][2] = 0
                else:
                    held[i][2] -= 1
                if held[i][2] <= 0:
                    del held[i]
                return

    # -- lock protocol ---------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        # cycle detection (and edge recording) only for UNBOUNDED
        # blocking acquires: trylock / timed acquires are the classic
        # deadlock-AVOIDANCE patterns — they cannot deadlock, and
        # recording their intentional inversions would poison the
        # graph with false cycles for later blocking acquirers
        first = self._entry() is None
        if first and blocking and timeout == -1:
            _note_acquired(self._site, id(self))
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._push()
        return got

    def release(self):
        self._inner.release()
        self._pop()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<lockcheck {self._inner!r} @ {self._site}>"


class _CheckedRLock(_CheckedLock):
    # Condition(lock=RLock) integration: these fully release /
    # re-acquire regardless of recursion depth
    def _release_save(self):
        state = self._inner._release_save()
        self._pop(fully=True)
        return state

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        # restore the SAVED recursion depth (state is the RLock's
        # (count, owner)): pushing depth 1 regardless would desync the
        # held-entry after a Condition.wait at depth >= 2 — the first
        # release would drop the entry while the lock is still owned,
        # hiding every subsequent held->acquired edge
        count = state[0] if isinstance(state, tuple) \
            and isinstance(state[0], int) else 1
        held = _held()
        for e in held:
            if e[1] == id(self):
                e[2] += count
                return
        held.append([self._site, id(self), count])

    def _is_owned(self):
        return self._inner._is_owned()


def checked_lock(site: str | None = None) -> _CheckedLock:
    return _CheckedLock(_real_Lock(), site or _site_of_caller())


def checked_rlock(site: str | None = None) -> _CheckedRLock:
    return _CheckedRLock(_real_RLock(), site or _site_of_caller())


def checked_condition(lock=None, site: str | None = None):
    if lock is None:
        lock = checked_rlock(site or _site_of_caller())
    return _real_Condition(lock)


# -- factory patches ---------------------------------------------------

def _lock_factory():
    if _caller_in_scope():
        return _CheckedLock(_real_Lock(), _site_of_caller())
    return _real_Lock()


def _rlock_factory():
    if _caller_in_scope():
        return _CheckedRLock(_real_RLock(), _site_of_caller())
    return _real_RLock()


def _condition_factory(lock=None):
    if lock is None and _caller_in_scope():
        lock = _CheckedRLock(_real_RLock(), _site_of_caller())
    return _real_Condition(lock)


def install(mode: str | None = None, scope=None):
    """Patch threading.Lock/RLock/Condition. Idempotent. ``mode``:
    'raise' (default) or 'warn'; default from PADDLE_TPU_LOCKCHECK
    ('warn' selects warn, any other truthy value raises)."""
    global _installed, _mode, _scope
    if mode is None:
        mode = "warn" if os.environ.get(
            "PADDLE_TPU_LOCKCHECK", "") == "warn" else "raise"
    _mode = mode
    if scope is None:
        extra = os.environ.get("PADDLE_TPU_LOCKCHECK_SCOPE", "")
        scope = _DEFAULT_SCOPE + tuple(
            s.strip() for s in extra.split(",") if s.strip())
    _scope = tuple(scope)
    if _installed:
        return
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    _installed = True


def uninstall():
    global _installed
    if not _installed:
        return
    threading.Lock = _real_Lock
    threading.RLock = _real_RLock
    threading.Condition = _real_Condition
    _installed = False


def installed() -> bool:
    return _installed


def reset():
    """Clear the recorded graph/violations (between tests)."""
    with _bookkeeping():
        _edges.clear()
        _edge_witness.clear()
        _violations.clear()
        _warned_pairs.clear()


def graph() -> dict[str, list[str]]:
    with _bookkeeping():
        return {k: sorted(v) for k, v in sorted(_edges.items())}


def violations() -> list[dict]:
    with _bookkeeping():
        return [dict(v) for v in _violations]


def report() -> dict:
    """JSON-safe summary (tests and postmortem tooling)."""
    with _bookkeeping():
        return {"installed": _installed, "mode": _mode,
                "sites": sorted(set(_edges)
                                | {s for v in _edges.values()
                                   for s in v}),
                "edges": {k: sorted(v)
                          for k, v in sorted(_edges.items())},
                "violations": [dict(v) for v in _violations]}
