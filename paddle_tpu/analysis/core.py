"""Static-analysis engine: one AST parse per file, a rule registry,
findings, and a shrink-only baseline (ratchet).

Execution model
---------------
``run(root)`` walks every ``*.py`` under the root, parses each file
exactly ONCE, and hands the same tree to every selected rule
(``Rule.visit``). Rules that need the whole tree (duplicate metric
registrations, env-knob docs coverage, the cross-class lock graph)
accumulate state per file and emit their findings from
``Rule.finalize``. The engine never re-parses.

Findings and the baseline
-------------------------
A ``Finding`` carries ``rule``, ``path:line``, a human message, and a
stable ``key`` — the fingerprint used for baseline matching. Keys
deliberately exclude line numbers (lines drift on every edit); two
identical findings in one scope get ``#2``/``#3`` suffixes so the
ratchet can count occurrences.

``baseline.json`` (beside this module) maps rule name -> list of
``{"key": ..., "why": ...}`` entries. Matching findings are
suppressed; the "why" is mandatory — a baseline entry without a
justification is itself a violation. The ratchet is SHRINK-ONLY:

  * a finding not in the baseline fails the run (fix it, or hand-add a
    justified entry);
  * a baseline entry with no matching finding ("stale") also fails the
    run — ``--baseline update`` deletes stale entries and nothing
    else. The baseline can therefore only ever shrink automatically;
    growth requires a human writing a justification in the diff.
"""
from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field

__all__ = ["Finding", "FileContext", "Rule", "KeyCounter",
           "dotted_name", "register", "all_rules", "AnalysisRun",
           "run", "repo_root", "default_code_root", "baseline_path",
           "load_baseline", "render_text", "render_json"]


def dotted_name(node) -> str | None:
    """'a.b.c' for Name/Attribute chains, else None — the shared
    callee/receiver resolver for every rule family."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class KeyCounter:
    """Suffix DUPLICATE keys with #2, #3... — keys stay content-based
    (stable under unrelated edits and under fixing a sibling finding);
    only true repeats of the same content get a positional suffix.
    One instance per (rule, emission pass)."""

    def __init__(self):
        self._seen: dict[str, int] = {}

    def __call__(self, key: str) -> str:
        n = self._seen.get(key, 0) + 1
        self._seen[key] = n
        return key if n == 1 else f"{key}#{n}"


def repo_root() -> str:
    """The repository root (two levels above this package)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_code_root() -> str:
    return os.path.join(repo_root(), "paddle_tpu")


def baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


@dataclass
class Finding:
    rule: str
    path: str            # as scanned (absolute or caller-relative)
    line: int
    message: str
    key: str             # stable fingerprint (no line numbers)

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "file": self.path,
                "line": self.line, "message": self.message,
                "key": self.key}


@dataclass
class FileContext:
    """One parsed file, shared by every rule (one AST pass)."""
    path: str            # path as scanned
    relpath: str         # relative to the scan root, '/'-separated
    tree: ast.AST
    source: str
    default_tree: bool   # scanning the whole shipped paddle_tpu/ tree?
    # '/'-separated path relative to the shipped paddle_tpu/ tree when
    # this file lives inside it (regardless of the scan root), else
    # None — subtree-scoped rules (wire-pickle, metric SKIP_FILES)
    # gate on THIS, so `--root paddle_tpu/fluid` judges files the same
    # way the full-tree run does
    tree_rel: str | None = None


class Rule:
    """Base class. Subclasses set ``name``/``description``, implement
    ``visit`` (per file) and optionally ``finalize`` (after all
    files). Both may return an iterable of Finding."""

    name: str = ""
    description: str = ""

    def visit(self, ctx: FileContext):
        return ()

    def finalize(self, run: "AnalysisRun"):
        return ()

    # -- helpers --------------------------------------------------------
    def finding(self, ctx_or_path, line: int, message: str,
                key: str) -> Finding:
        path = ctx_or_path.path if isinstance(ctx_or_path, FileContext) \
            else str(ctx_or_path)
        return Finding(self.name, path, int(line), message,
                       f"{self.name}::{key}")


_REGISTRY: dict[str, type[Rule]] = {}


def register(cls: type[Rule]) -> type[Rule]:
    if not cls.name:
        raise ValueError(f"{cls.__name__} needs a non-empty .name")
    if cls.name in _REGISTRY and _REGISTRY[cls.name] is not cls:
        raise ValueError(f"duplicate rule name {cls.name!r}")
    _REGISTRY[cls.name] = cls
    return cls


def all_rules() -> dict[str, type[Rule]]:
    """The registry, loading the built-in rule modules on first use."""
    from . import rules  # noqa: F401  (registration side effect)
    return dict(_REGISTRY)


@dataclass
class AnalysisRun:
    """Everything one engine invocation produced."""
    root: str
    rules_run: list = field(default_factory=list)   # rule names
    default_scan: bool = False   # whole shipped tree was scanned?
    files: list[FileContext] = field(default_factory=list)
    findings: list[Finding] = field(default_factory=list)
    parse_errors: list[Finding] = field(default_factory=list)
    # populated by apply_baseline():
    new: list[Finding] = field(default_factory=list)
    baselined: list[Finding] = field(default_factory=list)
    stale: list[tuple[str, str]] = field(default_factory=list)
    unjustified: list[tuple[str, str]] = field(default_factory=list)

    @property
    def failures(self) -> int:
        return (len(self.new) + len(self.stale)
                + len(self.unjustified) + len(self.parse_errors))


def _iter_py_files(root: str):
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirs, files in os.walk(root):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for fn in sorted(files):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def run(root: str | None = None,
        rule_names: list[str] | None = None) -> AnalysisRun:
    """Parse every file under ``root`` once and run the selected rules
    (default: all) over the shared trees."""
    registry = all_rules()
    if rule_names:
        unknown = sorted(set(rule_names) - set(registry))
        if unknown:
            raise KeyError(
                f"unknown rule(s) {unknown}; have {sorted(registry)}")
        selected = [registry[n]() for n in rule_names]
    else:
        selected = [cls() for _n, cls in sorted(registry.items())]
    root = os.path.abspath(root if root is not None
                           else default_code_root())
    if not os.path.exists(root):
        # a typo'd --root must FAIL, not report a green 0-file scan —
        # silently disabling every rule is the exact failure mode this
        # tooling exists to prevent
        raise FileNotFoundError(f"scan root does not exist: {root}")
    code_root = os.path.abspath(default_code_root())
    default_tree = root == code_root
    out = AnalysisRun(root=root,
                      rules_run=[r.name for r in selected],
                      default_scan=default_tree)
    for path in _iter_py_files(root):
        with open(path, encoding="utf-8") as f:
            src = f.read()
        rel = os.path.relpath(path, root if os.path.isdir(root)
                              else os.path.dirname(root))
        rel = rel.replace(os.sep, "/")
        tree_rel = None
        apath = os.path.abspath(path)
        if apath.startswith(code_root + os.sep):
            tree_rel = os.path.relpath(apath, code_root) \
                .replace(os.sep, "/")
        try:
            tree = ast.parse(src, path)
        except SyntaxError as e:
            out.parse_errors.append(Finding(
                "parse", path, e.lineno or 0,
                f"unparseable: {e.msg}", f"parse::{rel}"))
            continue
        ctx = FileContext(path, rel, tree, src, default_tree,
                          tree_rel=tree_rel)
        out.files.append(ctx)
        for rule in selected:
            out.findings.extend(rule.visit(ctx) or ())
    for rule in selected:
        out.findings.extend(rule.finalize(out) or ())
    out.findings.sort(key=lambda f: (f.rule, f.path, f.line))
    return out


# -- baseline / ratchet ------------------------------------------------

def load_baseline(path: str | None = None) -> dict[str, list[dict]]:
    path = path or baseline_path()
    if not os.path.isfile(path):
        return {}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    return doc.get("rules", {})


def save_baseline(rules: dict[str, list[dict]],
                  path: str | None = None) -> str:
    path = path or baseline_path()
    doc = {"_comment": [
        "Shrink-only ratchet for python -m paddle_tpu.analysis "
        "(docs/STATIC_ANALYSIS.md).",
        "Every entry needs a one-line 'why'. `--baseline update` only "
        "DELETES stale entries;",
        "new findings must be fixed or hand-added here with a "
        "justification."],
        "rules": {r: sorted(v, key=lambda e: e["key"])
                  for r, v in sorted(rules.items()) if v}}
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return path


def apply_baseline(run_: AnalysisRun,
                   baseline: dict[str, list[dict]] | None = None,
                   update: bool = False,
                   path: str | None = None) -> AnalysisRun:
    """Split findings into new vs baselined, detect stale/unjustified
    entries; with ``update=True`` rewrite the file with stale entries
    removed (the only automatic mutation — shrink-only).

    Scoped to what this run could actually have observed: baseline
    entries for rules that did NOT run are left untouched (never
    stale, never pruned — ``--rule wire-pickle`` must not judge the
    lock entries), and staleness is only decided at all on a
    full-default-tree scan (a ``--root`` subtree cannot prove a
    finding elsewhere is gone). Matching findings are suppressed
    either way."""
    if baseline is None:
        baseline = load_baseline(path)
    # occurrence-count the finding keys so N identical sites need N
    # baseline entries (keys get #2.. suffixes at emit time already)
    finding_keys = {f.key for f in run_.findings}
    relevant = set(run_.rules_run)
    matched: set[str] = set()
    for rule_name, entries in baseline.items():
        if rule_name not in relevant:
            continue
        for e in entries:
            key = e.get("key", "")
            if not str(e.get("why", "")).strip():
                run_.unjustified.append((rule_name, key))
            if key in finding_keys:
                matched.add(key)
            elif run_.default_scan:
                run_.stale.append((rule_name, key))
    for f in run_.findings:
        (run_.baselined if f.key in matched else run_.new).append(f)
    if update and run_.stale:
        stale_keys = {k for _r, k in run_.stale}
        pruned = {r: [e for e in v if e.get("key") not in stale_keys]
                  for r, v in baseline.items()}
        save_baseline(pruned, path)
    return run_


# -- rendering ---------------------------------------------------------

def render_text(run_: AnalysisRun, verbose: bool = False) -> str:
    lines: list[str] = []
    for f in run_.parse_errors:
        lines.append(f"{f.location()}: [{f.rule}] {f.message}")
    for f in run_.new:
        lines.append(f"{f.location()}: [{f.rule}] {f.message}")
    for rule_name, key in run_.stale:
        lines.append(
            f"baseline: [{rule_name}] stale entry {key!r} — the "
            "finding is gone; run `python -m paddle_tpu.analysis "
            "--baseline update` to ratchet the baseline down")
    for rule_name, key in run_.unjustified:
        lines.append(
            f"baseline: [{rule_name}] entry {key!r} has no 'why' — "
            "every baselined finding needs a one-line justification")
    if verbose:
        for f in run_.baselined:
            lines.append(f"{f.location()}: [{f.rule}] (baselined) "
                         f"{f.message}")
    n_files = len(run_.files)
    if run_.failures:
        lines.append(
            f"FAIL: {len(run_.new)} unbaselined finding(s), "
            f"{len(run_.stale)} stale baseline entr(ies), "
            f"{len(run_.unjustified)} unjustified, "
            f"{len(run_.parse_errors)} parse error(s) over "
            f"{n_files} file(s) under {run_.root}")
    else:
        lines.append(
            f"OK: {n_files} file(s) under {run_.root} — "
            f"{len(run_.baselined)} baselined finding(s), 0 new")
    return "\n".join(lines)


def render_json(run_: AnalysisRun) -> str:
    return json.dumps({
        "root": run_.root,
        "files": len(run_.files),
        "ok": run_.failures == 0,
        "new": [f.to_dict() for f in run_.new],
        "baselined": [f.to_dict() for f in run_.baselined],
        "stale_baseline": [{"rule": r, "key": k}
                           for r, k in run_.stale],
        "unjustified_baseline": [{"rule": r, "key": k}
                                 for r, k in run_.unjustified],
        "parse_errors": [f.to_dict() for f in run_.parse_errors],
    }, indent=1)
