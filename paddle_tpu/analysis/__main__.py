"""CLI: ``python -m paddle_tpu.analysis``.

Runs every registered rule (or a ``--rule`` subset) over paddle_tpu/
(or ``--root``) in one AST pass per file, applies the shrink-only
baseline, and prints findings as human text (default) or JSON
(``--json``). Exit 0 = zero unbaselined findings and a tight baseline;
1 = findings / stale or unjustified baseline entries; 2 = usage.

``--baseline update`` deletes stale baseline entries (entries whose
finding no longer exists) — the ONLY automatic mutation; adding an
entry is always a hand edit with a one-line "why".
"""
from __future__ import annotations

import argparse
import sys

from . import core


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="unified static analysis "
                    "(docs/STATIC_ANALYSIS.md)")
    p.add_argument("--root", default=None,
                   help="directory (or file) to scan "
                        "[default: <repo>/paddle_tpu]")
    p.add_argument("--rule", action="append", default=None,
                   metavar="NAME",
                   help="run only this rule (repeatable)")
    p.add_argument("--baseline", choices=("check", "update"),
                   default="check",
                   help="'update' deletes stale baseline entries "
                        "(shrink-only ratchet)")
    p.add_argument("--baseline-file", default=None,
                   help="alternate baseline path (tests)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report raw findings, no baseline matching")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings")
    p.add_argument("--verbose", action="store_true",
                   help="also print baselined findings")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for name, cls in sorted(core.all_rules().items()):
            print(f"{name:22s} {cls.description}")
        return 0

    try:
        run_ = core.run(args.root, args.rule)
    except KeyError as e:
        print(e.args[0], file=sys.stderr)
        return 2
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.no_baseline:
        run_.new = list(run_.findings)
    else:
        core.apply_baseline(run_, update=args.baseline == "update",
                            path=args.baseline_file)
        if args.baseline == "update":
            # the update already pruned the file; report post-update
            run_.stale = []
    print(core.render_json(run_) if args.json
          else core.render_text(run_, verbose=args.verbose))
    return 1 if run_.failures else 0


if __name__ == "__main__":
    sys.exit(main())
