"""Concurrency rules: the static lock model.

Built from ``with self._lock:``-style blocks across the whole scanned
tree in one pass:

  * ``lock-order``    — inconsistent lock-ordering pairs (deadlock
                        potential): lock A is taken while holding B in
                        one place and B while holding A in another;
  * ``lock-blocking-call`` — a call that can block (sleep, socket/file
                        I/O, ``.result()``, subprocess, checkpoint
                        restore, registry exposition) executed while a
                        lock is held, directly or through a resolvable
                        call chain;
  * ``lock-callback``  — an OPAQUE stored callback (``self._fn(...)``
                        where ``_fn`` was assigned from a parameter)
                        invoked under a lock: its lock-order effects
                        are unknowable statically, so it can close a
                        cycle no reviewer can see (the registry gauge
                        ``set_function`` bug was exactly this shape).

Model
-----
Locks are identified by OWNER and attribute: ``ClassName._lock`` for
``self._lock = threading.Lock()`` and ``module.NAME`` for module-level
locks; all instances of a class share one lock identity (the same
aggregation the runtime sanitizer uses, so static and dynamic reports
line up). Attribute receivers are typed from constructor assignments
(``self.scheduler = Scheduler(...)`` types ``Engine.scheduler``), which
resolves cross-object acquisitions like ``with self.scheduler._lock:``
and cross-object calls like ``self.scheduler.admit()``.

Per function the rule records every acquisition (with the locks held
at that point) and every call made under a held lock. A fixpoint over
the resolvable call graph then computes which locks each function MAY
acquire and whether it MAY block; edges ``held -> acquired`` feed the
order graph, and may-block callees under a lock feed the blocking
rule. ``with cond:`` on a Condition is a lock acquisition;
``cond.wait()`` is NOT a blocking call (it releases the lock).

Known limits (by design, to stay useful instead of noisy): dynamic
callables (jitted functions, hooks) are opaque; attribute chains
deeper than ``self.attr.method`` are unresolved; a lock passed across
objects keeps its creation-site identity only when the attribute type
is resolvable. The ``PADDLE_TPU_LOCKCHECK=1`` runtime sanitizer
(analysis/lockcheck.py) is the dynamic complement covering what this
model cannot see.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field

from ..core import (FileContext, KeyCounter, Rule, dotted_name,
                    register)

__all__ = ["LockOrderRule", "BlockingUnderLockRule",
           "CallbackUnderLockRule", "LOCK_FACTORIES",
           "BLOCKING_PRIMITIVES"]

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

# Call shapes that BLOCK (sleep, wire/file I/O, futures, subprocess,
# checkpoint restore, metrics exposition). Matched against the dotted
# tail of the callee: "time.sleep" matches `time.sleep(...)`, "sleep"
# matches any `*.sleep(...)` or bare `sleep(...)`.
BLOCKING_PRIMITIVES = {
    # sleeping / waiting
    "time.sleep", "sleep",
    # sockets (the wire framing helpers are this tree's socket I/O)
    "connect", "create_connection", "recv", "recv_into", "sendall",
    "accept", "send_frame", "recv_frame",
    # file I/O
    "open", "os.open", "os.write", "os.replace", "os.fsync",
    "np.savez", "np.savez_compressed", "np.load", "savez",
    # futures / threads / subprocess
    "result", "subprocess.run", "subprocess.check_call",
    "subprocess.check_output", "communicate", "subprocess.Popen",
    # checkpoint restore/save entry points (disk behind one name)
    "load_checkpoint", "save_checkpoint", "load_snapshot", "restore",
    # registry exposition walks every series and evaluates gauge
    # callbacks — never under a subsystem lock
    "prometheus_text", "dump_to_file",
}

# receivers whose .join() is a thread join, not str.join
_JOINABLE_HINTS = ("thread", "proc", "worker")


_dotted = dotted_name   # shared AST chain resolver (core.py)


def _call_tail(name: str) -> list[str]:
    """Match candidates for a dotted callee: full dotted name and the
    bare final attribute."""
    out = [name]
    if "." in name:
        out.append(name.rsplit(".", 1)[1])
    return out


def _is_blocking_callee(dotted: str, call: ast.Call) -> bool:
    tails = _call_tail(dotted)
    for t in tails:
        if t in BLOCKING_PRIMITIVES:
            return True
    # thread/process join heuristic (str.join is everywhere)
    if tails[-1] == "join" and "." in dotted:
        recv = dotted.rsplit(".", 1)[0].lower()
        if any(h in recv for h in _JOINABLE_HINTS):
            return True
    return False


# -- per-file model ----------------------------------------------------

@dataclass
class FuncInfo:
    """One function/method's lock-relevant behavior."""
    key: tuple            # (module, class|None, name)
    path: str
    # (lock_id, line, tuple(held lock_ids at that point))
    acquires: list = field(default_factory=list)
    # (callee descriptor, line, tuple(held), dotted_name)
    calls: list = field(default_factory=list)
    # (dotted_name, line, tuple(held)) blocking primitives UNDER a lock
    blocking: list = field(default_factory=list)
    # dotted_name -> line: every blocking primitive in the body,
    # locked or not (seed for interprocedural may-block propagation)
    blocks_any: dict = field(default_factory=dict)
    # (attr_name, line, tuple(held)) opaque stored-callback calls
    callbacks: list = field(default_factory=list)


@dataclass
class ClassInfo:
    module: str
    name: str
    path: str
    bases: list = field(default_factory=list)        # same-file names
    lock_attrs: set = field(default_factory=set)
    attr_types: dict = field(default_factory=dict)   # attr -> ClassName
    # attrs assigned from a plain parameter/lambda somewhere (callback
    # storage like self._fn = fn)
    callback_attrs: set = field(default_factory=set)
    methods: set = field(default_factory=set)
    lock_owner: dict = field(default_factory=dict)   # attr -> def class


class _ModuleScan:
    """Single pass over one parsed file collecting the lock model."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        # keys must be stable across scan roots: a shipped-tree file
        # keeps its full-tree-relative path however the scan was
        # rooted, so subtree runs match the same baseline entries
        self.keypath = ctx.tree_rel or ctx.relpath
        self.module = self.keypath[:-3].replace("/", ".")
        # a package's locks/functions belong to the PACKAGE name —
        # keying every __init__.py under the basename "__init__"
        # would merge all packages into one resolution bucket
        if self.module.endswith(".__init__"):
            self.module = self.module[:-len(".__init__")]
        self.classes: dict[str, ClassInfo] = {}
        self.module_locks: set[str] = set()
        self.functions: dict[tuple, FuncInfo] = {}
        self.imports: dict[str, str] = {}   # alias -> module basename
        self._scan()

    # -- phase 1: discover locks / attr types / imports ---------------
    def _scan(self):
        # two passes: collect EVERY class's lock/attr model first (a
        # subclass method may use a base-class lock defined later in
        # the file), then walk function bodies
        for node in self.ctx.tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._scan_import(node)
            elif isinstance(node, ast.Assign):
                self._module_assign(node)
            elif isinstance(node, ast.ClassDef):
                self._collect_class(node)
        self._inherit()
        for node in self.ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self._scan_func(sub, cls=node.name)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self._scan_func(node, cls=None)

    def _inherit(self):
        """Fold same-file base-class lock/attr/method models into
        subclasses (the registry's _Child hierarchy keeps its lock on
        the base). Lock identity stays the DEFINING class so one base
        lock is one graph node across all subclasses."""
        def fold(name, seen):
            info = self.classes.get(name)
            if info is None or name in seen:
                return info
            seen.add(name)
            for b in info.bases:
                binfo = fold(b, seen)
                if binfo is None:
                    continue
                for attr in binfo.lock_attrs:
                    # keep the base's identity for inherited locks
                    info.lock_owner.setdefault(attr,
                                               binfo.lock_owner.get(
                                                   attr, binfo.name))
                    info.lock_attrs.add(attr)
                for k, v in binfo.attr_types.items():
                    info.attr_types.setdefault(k, v)
                info.callback_attrs |= binfo.callback_attrs
                info.methods |= binfo.methods
            return info

        for name in list(self.classes):
            fold(name, set())

    def _scan_import(self, node):
        if isinstance(node, ast.Import):
            for a in node.names:
                alias = a.asname or a.name.split(".")[0]
                self.imports[alias] = a.name.split(".")[-1]
        else:
            for a in node.names:
                # `from ..observability import flight as _flight`
                # imports the MODULE flight; `from x import func` maps
                # the name to the source module for function lookup
                self.imports[a.asname or a.name] = a.name

    def _module_assign(self, node: ast.Assign):
        if _lock_factory_call(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.module_locks.add(t.id)

    def _collect_class(self, cnode: ast.ClassDef):
        info = ClassInfo(self.module, cnode.name, self.ctx.path,
                         bases=[b.id for b in cnode.bases
                                if isinstance(b, ast.Name)])
        self.classes[cnode.name] = info
        for node in cnode.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                info.methods.add(node.name)
        for node in ast.walk(cnode):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    if _lock_factory_call(node.value):
                        info.lock_attrs.add(t.attr)
                        info.lock_owner[t.attr] = cnode.name
                    elif isinstance(node.value, ast.Call) \
                            and isinstance(node.value.func, ast.Name):
                        info.attr_types[t.attr] = node.value.func.id
                    elif isinstance(node.value,
                                    (ast.Name, ast.Lambda)):
                        info.callback_attrs.add(t.attr)

    # -- phase 2: per-function lock-aware walk -------------------------
    def _scan_func(self, fnode, cls: str | None):
        key = (self.module, cls, fnode.name)
        info = FuncInfo(key, self.ctx.path)
        self.functions[key] = info
        self._walk_body(fnode.body, cls, info, held=())

    def _lock_id(self, expr, cls: str | None):
        """Resolve a `with` context expression to a lock identity, or
        None. Identities: ('C', attr) for class locks, ('mod:<module>',
        name) for module-level locks."""
        d = _dotted(expr)
        if d is None:
            return None
        parts = d.split(".")
        if len(parts) == 1:
            if parts[0] in self.module_locks:
                return (f"mod:{self.module}", parts[0])
            return None
        if parts[0] == "self" and cls is not None:
            cinfo = self.classes.get(cls)
            if cinfo is None:
                return None
            if len(parts) == 2 and parts[1] in cinfo.lock_attrs:
                return (cinfo.lock_owner.get(parts[1], cls), parts[1])
            if len(parts) == 3:
                # with self.scheduler._lock: -> (Scheduler, _lock)
                owner = cinfo.attr_types.get(parts[1])
                if owner is not None:
                    oinfo = self.classes.get(owner)
                    if oinfo is not None:
                        owner = oinfo.lock_owner.get(parts[2], owner)
                    return (owner, parts[2])
            return None
        return None

    def _callee(self, call: ast.Call, cls: str | None):
        """(descriptor, dotted) where descriptor resolves the callee:
        ('method', class, name) / ('func', module_hint, name) / None."""
        d = _dotted(call.func)
        if d is None:
            return None, None
        parts = d.split(".")
        if parts[0] == "self" and cls is not None:
            cinfo = self.classes.get(cls)
            if len(parts) == 2:
                if cinfo and parts[1] in cinfo.methods:
                    return ("method", cls, parts[1]), d
                if cinfo and parts[1] in cinfo.callback_attrs:
                    return ("callback", cls, parts[1]), d
                return None, d
            if len(parts) == 3 and cinfo:
                owner = cinfo.attr_types.get(parts[1])
                if owner is not None:
                    return ("method", owner, parts[2]), d
                return None, d
            return None, d
        if len(parts) == 1:
            return ("func", self.module, parts[0]), d
        if len(parts) == 2 and parts[0] in self.imports:
            return ("func", self.imports[parts[0]], parts[1]), d
        if len(parts) == 2:
            # ClassName.method / unknown-receiver.method
            return ("maybe_method", parts[0], parts[1]), d
        return None, d

    def _walk_body(self, body, cls, info: FuncInfo, held: tuple):
        for stmt in body:
            self._walk_stmt(stmt, cls, info, held)

    def _walk_stmt(self, stmt, cls, info: FuncInfo, held: tuple):
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in stmt.items:
                lid = self._lock_id(item.context_expr, cls)
                if lid is not None:
                    if lid not in new_held:
                        info.acquires.append(
                            (lid, item.context_expr.lineno, new_held))
                        new_held = new_held + (lid,)
                else:
                    # later items of `with self._lock, open(p):` run
                    # with the earlier items' locks HELD — visit with
                    # the accumulating set, not the pre-With one
                    self._visit_expr(item.context_expr, cls, info,
                                     new_held)
            self._walk_body(stmt.body, cls, info, new_held)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: its body runs later, outside our held set
            self._walk_body(stmt.body, cls, info, ())
            return
        if isinstance(stmt, ast.ClassDef):
            return
        # every other statement: visit expressions, recurse into
        # compound bodies with the same held set
        for name, value in ast.iter_fields(stmt):
            if name in ("body", "orelse", "finalbody", "handlers"):
                continue
            for expr in _exprs(value):
                self._visit_expr(expr, cls, info, held)
        for name in ("body", "orelse", "finalbody"):
            self._walk_body(getattr(stmt, name, []) or [], cls, info,
                            held)
        for h in getattr(stmt, "handlers", []) or []:
            self._walk_body(h.body, cls, info, held)

    def _visit_expr(self, expr, cls, info: FuncInfo, held: tuple):
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            desc, dotted = self._callee(node, cls)
            if dotted is None:
                continue
            tail = dotted.rsplit(".", 1)[-1]
            if tail == "wait":
                continue      # Condition.wait releases its lock
            if _is_blocking_callee(dotted, node):
                info.blocks_any.setdefault(dotted, node.lineno)
                if held:
                    info.blocking.append((dotted, node.lineno, held))
                continue
            if desc is None:
                continue
            if desc[0] == "callback":
                if held:
                    info.callbacks.append(
                        (desc[2], node.lineno, held))
                continue
            info.calls.append((desc, node.lineno, held, dotted))


def _exprs(value):
    if isinstance(value, ast.AST):
        yield value
    elif isinstance(value, list):
        for v in value:
            if isinstance(v, ast.AST):
                yield v


def _lock_factory_call(node) -> bool:
    """threading.Lock() / threading.RLock() / threading.Condition()
    (or bare Lock()/RLock()/Condition() from `from threading import`)."""
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    if d is None:
        return False
    parts = d.split(".")
    return parts[-1] in LOCK_FACTORIES and \
        (len(parts) == 1 or parts[-2] == "threading")


# -- cross-file analysis ----------------------------------------------

class _Model:
    """The whole-tree lock model shared by the three concurrency rules
    (built once per engine invocation, on the shared ASTs)."""

    def __init__(self):
        self.scans: list[_ModuleScan] = []
        self._done = False
        # resolution indexes
        self.by_class: dict[str, dict[str, FuncInfo]] = {}
        self.by_module: dict[str, dict[str, FuncInfo]] = {}
        self.may_acquire: dict[tuple, set] = {}
        self.may_block: dict[tuple, dict] = {}   # key -> {prim: line}

    def add(self, ctx: FileContext):
        self.scans.append(_ModuleScan(ctx))

    def resolve(self, desc):
        kind, owner, name = desc
        if kind in ("method", "maybe_method"):
            return self.by_class.get(owner, {}).get(name)
        if kind == "func":
            # owner may be a dotted module path or basename
            base = owner.rsplit(".", 1)[-1]
            return self.by_module.get(base, {}).get(name)
        return None

    def finalize(self):
        if self._done:
            return
        self._done = True
        # cross-file indexes resolve by bare name: names defined in
        # MORE than one module are ambiguous — resolving them to
        # whichever registration came last would propagate the wrong
        # class's lock model through the fixpoint, so ambiguous names
        # are dropped from resolution entirely (conservative: fewer
        # edges, never wrong-class edges)
        class_owner: dict[str, set] = {}
        module_owner: dict[str, set] = {}
        for scan in self.scans:
            for cname in scan.classes:
                class_owner.setdefault(cname, set()).add(scan.module)
            module_owner.setdefault(
                scan.module.rsplit(".", 1)[-1], set()).add(scan.module)
        for scan in self.scans:
            base = scan.module.rsplit(".", 1)[-1]
            for key, fi in scan.functions.items():
                _module, cls, name = key
                if cls is not None:
                    if len(class_owner.get(cls, ())) == 1:
                        self.by_class.setdefault(cls, {})[name] = fi
                elif len(module_owner.get(base, ())) == 1:
                    self.by_module.setdefault(base, {})[name] = fi
        funcs = [fi for scan in self.scans
                 for fi in scan.functions.values()]
        for fi in funcs:
            self.may_acquire[fi.key] = {l for l, _ln, _h
                                        in fi.acquires}
            self.may_block[fi.key] = dict(fi.blocks_any)
        # fixpoint over the resolvable call graph
        changed = True
        while changed:
            changed = False
            for fi in funcs:
                acq = self.may_acquire[fi.key]
                blk = self.may_block[fi.key]
                for desc, _line, _held, _dotted in fi.calls:
                    callee = self.resolve(desc)
                    if callee is None:
                        continue
                    extra = self.may_acquire.get(callee.key, set()) \
                        - acq
                    if extra:
                        acq |= extra
                        changed = True
                    for prim, ln in self.may_block.get(
                            callee.key, {}).items():
                        if prim not in blk:
                            blk[prim] = ln
                            changed = True


def _lock_name(lid) -> str:
    owner, attr = lid
    return f"{owner}.{attr}" if not owner.startswith("mod:") \
        else f"{owner[4:]}.{attr}"


def _shared_model(run) -> _Model:
    """ONE _Model per engine invocation, cached on the AnalysisRun:
    the module scans and the call-graph fixpoint run once however many
    concurrency rules are selected."""
    m = getattr(run, "_concurrency_model", None)
    if m is None:
        m = _Model()
        for ctx in run.files:
            m.add(ctx)
        m.finalize()
        run._concurrency_model = m
    return m


class _ConcurrencyBase(Rule):
    """Concurrency rules are finalize-only: they read the shared
    per-run _Model (built lazily from run.files by whichever rule
    finalizes first)."""

    def visit(self, ctx: FileContext):
        return ()


@register
class LockOrderRule(_ConcurrencyBase):
    name = "lock-order"
    description = ("inconsistent lock-acquisition order between two "
                   "locks (deadlock potential)")

    def finalize(self, run):
        m = _shared_model(run)
        # edge (A -> B): witness line where B is acquired while A held
        edges: dict[tuple, tuple] = {}
        for scan in m.scans:
            for fi in scan.functions.values():
                for lid, line, held in fi.acquires:
                    for h in held:
                        if h != lid:
                            edges.setdefault((h, lid),
                                             (fi.path, line, fi.key))
                for desc, line, held, _dotted in fi.calls:
                    if not held:
                        continue
                    callee = m.resolve(desc)
                    if callee is None:
                        continue
                    for lid in m.may_acquire.get(callee.key, ()):
                        for h in held:
                            if h != lid:
                                edges.setdefault(
                                    (h, lid),
                                    (fi.path, line, fi.key))
        out = []
        seen_pairs = set()
        for (a, b), (path, line, key) in sorted(
                edges.items(), key=lambda kv: (str(kv[0]))):
            if (b, a) not in edges:
                continue
            pair = tuple(sorted((_lock_name(a), _lock_name(b))))
            if pair in seen_pairs:
                continue
            seen_pairs.add(pair)
            rpath, rline, rkey = edges[(b, a)]
            out.append(self.finding(
                path, line,
                f"inconsistent lock order: {_lock_name(a)} -> "
                f"{_lock_name(b)} here (in {_fq(key)}), but "
                f"{_lock_name(b)} -> {_lock_name(a)} at "
                f"{rpath}:{rline} (in {_fq(rkey)}) — deadlock "
                f"potential",
                key=f"{pair[0]}<->{pair[1]}"))
        return out


def _fq(key) -> str:
    module, cls, name = key
    return f"{cls}.{name}" if cls else name


_KeyCounter = KeyCounter   # shared content-based key convention


@register
class BlockingUnderLockRule(_ConcurrencyBase):
    name = "lock-blocking-call"
    description = ("blocking call (sleep / socket / file I/O / "
                   ".result() / exposition) while holding a lock")

    def finalize(self, run):
        m = _shared_model(run)
        out = []
        dedup = _KeyCounter()
        for scan in m.scans:
            for fi in sorted(scan.functions.values(),
                             key=lambda f: (f.key[0], f.key[1] or "",
                                            f.key[2])):
                for dotted, line, held in fi.blocking:
                    locks = ", ".join(_lock_name(h) for h in held)
                    out.append(self.finding(
                        fi.path, line,
                        f"blocking call {dotted}() while holding "
                        f"{locks} (in {_fq(fi.key)})",
                        key=dedup(f"{scan.keypath}::"
                                  f"{_fq(fi.key)}::{dotted}")))
                for desc, line, held, dotted in fi.calls:
                    if not held:
                        continue
                    callee = m.resolve(desc)
                    if callee is None:
                        continue
                    blk = m.may_block.get(callee.key, {})
                    if not blk:
                        continue
                    prim = sorted(blk)[0]
                    locks = ", ".join(_lock_name(h) for h in held)
                    out.append(self.finding(
                        fi.path, line,
                        f"call {dotted}() while holding {locks} "
                        f"(in {_fq(fi.key)}) reaches blocking "
                        f"{prim}() via {_fq(callee.key)}",
                        key=dedup(f"{scan.keypath}::"
                                  f"{_fq(fi.key)}::{dotted}->"
                                  f"{prim}")))
        return out


@register
class CallbackUnderLockRule(_ConcurrencyBase):
    name = "lock-callback"
    description = ("opaque stored callback invoked while holding a "
                   "lock (unknowable lock-order effects)")

    def finalize(self, run):
        out = []
        dedup = _KeyCounter()
        for scan in _shared_model(run).scans:
            for fi in sorted(scan.functions.values(),
                             key=lambda f: (f.key[0], f.key[1] or "",
                                            f.key[2])):
                for attr, line, held in fi.callbacks:
                    locks = ", ".join(_lock_name(h) for h in held)
                    out.append(self.finding(
                        fi.path, line,
                        f"opaque callback self.{attr}() invoked while "
                        f"holding {locks} (in {_fq(fi.key)}) — its "
                        f"lock-order effects are invisible to this "
                        f"analysis and can close a deadlock cycle",
                        key=dedup(f"{scan.keypath}::"
                                  f"{_fq(fi.key)}::{attr}")))
        return out
