"""metric-label-cardinality: flag ``labels()`` call sites whose label
values are not drawn from bounded sets.

Label values become series keys in the registry AND in the collector's
TSDB; an unbounded value (tenant names straight off the wire, request
ids, file paths) makes series cardinality grow with traffic until the
TSDB's retention budget is spent evicting *history* to store *keys*.
The time-series plane's survival constraint is therefore static: every
``m.labels(k=v)`` value must come from a bounded vocabulary.

What counts as bounded, judged per call site with local inference:

  * string/number literals, and conditionals / ``or``-chains whose
    arms are all bounded;
  * calls to the metering plane's sanctioned bounding helpers
    (``intern`` — cap + overflow bucket, ``normalize_outcome`` /
    ``_tier`` — fixed vocabularies);
  * a local name whose every assignment in the enclosing scope is
    itself bounded (e.g. ``verdict`` chosen from literals).

Everything else — attributes, f-strings, arbitrary calls, parameters —
is flagged. Legitimately-dynamic-but-bounded sites (an engine id, a
replica name from the static topology) are baselined in
``baseline.json`` with one-line justifications; the baseline is
shrink-only, so new unbounded labels cannot ride in quietly.
"""
from __future__ import annotations

import ast

from ..core import FileContext, KeyCounter, Rule, register

__all__ = ["MetricLabelCardinalityRule", "BOUNDING_CALLS",
           "label_cardinality_hits"]

# the metering plane's sanctioned bounding helpers: their return
# values are bounded by construction (cap + overflow bucket / fixed
# vocabulary), whatever the argument
BOUNDING_CALLS = {"intern", "normalize_outcome", "_tier"}


def _scopes(tree: ast.AST):
    """Yield (scope_node, direct_statements) for the module and every
    function — each statement list excludes nested function bodies, so
    name inference stays scope-local."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            yield node


def _walk_scope(scope: ast.AST):
    """ast.walk, but stop at nested function/class boundaries (their
    bodies are separate scopes with their own assignment maps)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _assignments(scope: ast.AST) -> dict[str, list[ast.AST]]:
    """name -> every expression assigned to it in this scope."""
    out: dict[str, list[ast.AST]] = {}
    for node in _walk_scope(scope):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.setdefault(tgt.id, []).append(node.value)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name):
                out.setdefault(node.target.id, []).append(node.value)
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name):
                # x += ... — conservatively unbounded
                out.setdefault(node.target.id, []).append(node)
    return out


def _call_tail(func: ast.AST) -> str | None:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _bounded(expr: ast.AST, assigns: dict[str, list[ast.AST]],
             seen: frozenset = frozenset()) -> bool:
    if isinstance(expr, ast.Constant):
        return True
    if isinstance(expr, ast.IfExp):
        return _bounded(expr.body, assigns, seen) \
            and _bounded(expr.orelse, assigns, seen)
    if isinstance(expr, ast.BoolOp):
        return all(_bounded(v, assigns, seen) for v in expr.values)
    if isinstance(expr, ast.Call):
        tail = _call_tail(expr.func)
        if tail in BOUNDING_CALLS:
            return True
        # str(<bounded>) stays bounded
        if isinstance(expr.func, ast.Name) and expr.func.id == "str" \
                and len(expr.args) == 1 and not expr.keywords:
            return _bounded(expr.args[0], assigns, seen)
        return False
    if isinstance(expr, ast.Name):
        if expr.id in seen:         # assignment cycle: give up safely
            return False
        vals = assigns.get(expr.id)
        if not vals:                # parameter / global / closure
            return False
        seen = seen | {expr.id}
        return all(_bounded(v, assigns, seen) for v in vals)
    return False


def _label_desc(expr: ast.AST) -> str:
    try:
        return ast.unparse(expr)
    except Exception:               # pragma: no cover — malformed AST
        return "<expr>"


def label_cardinality_hits(tree: ast.AST) \
        -> list[tuple[int, str, str, str]]:
    """(line, metric_recv, label_kw, value_src) for every ``labels()``
    keyword whose value local inference cannot prove bounded — ONE hit
    per (metric, label) pair per file: the series family is the unit
    of cardinality risk, not the call site, and the baseline should
    carry one justification per family, not one per inc()."""
    hits = []
    seen_fam: set[tuple[str, str]] = set()
    for scope in _scopes(tree):
        assigns = _assignments(scope)
        for node in _walk_scope(scope):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "labels"):
                continue
            recv = _label_desc(node.func.value)
            # strip the receiver to the metric object itself so
            # `self._m_x.labels` and `_M_X.labels` sites family-match
            fam_recv = recv.split("(")[0]
            for kw in node.keywords:
                arg = kw.arg or "**"
                if kw.arg is not None \
                        and _bounded(kw.value, assigns):
                    continue
                fam = (fam_recv, arg)
                if fam in seen_fam:
                    continue
                seen_fam.add(fam)
                hits.append((node.lineno, recv, arg,
                             _label_desc(kw.value)))
    return sorted(hits)


@register
class MetricLabelCardinalityRule(Rule):
    name = "metric-label-cardinality"
    description = ("labels() values not provably drawn from bounded "
                   "sets (unbounded series cardinality would flood "
                   "the registry and the collector TSDB)")

    def visit(self, ctx: FileContext):
        if ctx.tree_rel == "observability/registry.py":
            # the registry defines labels(); its docstrings/tests
            # exercise the API with placeholder values
            return ()
        dedup = KeyCounter()
        keypath = ctx.tree_rel or ctx.relpath
        return [self.finding(
            ctx, line,
            f"{recv}.labels({kw}={src}) — value not provably bounded; "
            f"route dynamic identifiers through meter.intern() (cap + "
            f"overflow) or a fixed vocabulary, or baseline with a "
            f"justification",
            key=dedup(
                f"{keypath}::{recv.split('(')[0]}.labels({kw})"))
            for line, recv, kw, src in
            label_cardinality_hits(ctx.tree)]
