"""Built-in rule modules. Importing this package registers every rule
with the engine registry (core.all_rules loads it lazily)."""
from . import cardinality, concurrency, invariants, \
    jit_hazards  # noqa: F401
