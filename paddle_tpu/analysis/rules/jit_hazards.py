"""Jit-hazard rules: host syncs and recompile bombs inside
jit-reachable functions.

The XLA fusion study (PAPERS.md, arxiv 2301.13062) shows frameworks
silently lose cycles to host round-trips and recompilation; neither is
visible in a diff unless something looks for it. These rules find the
shapes that cause them:

  * ``jit-host-sync``      — ``.item()`` / ``float()/int()/bool()`` on
                             a traced argument / ``np.asarray``-family
                             on a traced argument inside a jitted
                             function: each forces a device->host sync
                             per call (or worse, per trace);
  * ``jit-trace-branch``   — Python ``if``/``while`` on a traced
                             argument: either a TracerBoolConversion
                             error at runtime or, with shape-dependent
                             code, one recompile per value seen;
  * ``jit-nondeterminism`` — wall-clock / ``random`` reads inside a
                             jitted function: the value is baked in at
                             TRACE time, so it is stale for every later
                             call and differs across hosts (the
                             ``Date``-like hazard class);
  * ``jit-static-unhashable`` — ``static_argnums/argnames`` naming a
                             parameter with a mutable (unhashable)
                             default: jit's cache keying raises
                             ``TypeError: unhashable`` the first time
                             the default is actually used.

Jit-reachability: a function is jitted when decorated with
``jax.jit``/``jit``/``pjit`` (bare or under ``functools.partial``), or
when its NAME is wrapped anywhere in the same file
(``self._step = jax.jit(step)``). Reachability propagates through
bare same-file calls (``helper(x)`` inside a jitted fn marks
``helper``). Parameters named static (``static_argnums/argnames``) are
exempt from the tracer-argument checks — branching on a static arg is
exactly what static args are for. Closure variables are NOT treated as
tracers (config objects riding a closure are the dominant idiom in
this tree); only the function's own positional/keyword parameters are.
"""
from __future__ import annotations

import ast

from ..core import FileContext, Rule, dotted_name, register

__all__ = ["JitHostSyncRule", "JitTraceBranchRule",
           "JitNondeterminismRule", "JitStaticUnhashableRule"]

_JIT_NAMES = {"jit", "pjit"}
_NP_ALIASES = {"np", "numpy", "onp"}
_HOST_PULLS = {"asarray", "array", "copy", "ascontiguousarray"}
_CLOCK_CALLS = {"time.time", "time.monotonic", "time.perf_counter",
                "time.time_ns", "datetime.now", "datetime.utcnow",
                "date.today"}
_RANDOM_MODS = {"random"}   # python random; np.random handled below


_dotted = dotted_name   # shared AST chain resolver (core.py)


def _is_jit_callee(node) -> bool:
    """jax.jit / jit / pjit / functools.partial(jax.jit, ...)"""
    d = _dotted(node)
    if d is not None and d.split(".")[-1] in _JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        fd = _dotted(node.func)
        if fd is not None and fd.split(".")[-1] == "partial" \
                and node.args:
            return _is_jit_callee(node.args[0])
    return False


def _static_params(call: ast.Call | None, fnode) -> set[str]:
    """Parameter names declared static on the jit call/decorator."""
    if call is None:
        return set()
    args = [a.arg for a in fnode.args.posonlyargs + fnode.args.args]
    out: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for el in _const_elems(kw.value):
                if isinstance(el, str):
                    out.add(el)
        elif kw.arg == "static_argnums":
            for el in _const_elems(kw.value):
                if isinstance(el, int) and 0 <= el < len(args):
                    out.add(args[el])
    return out


def _const_elems(node):
    if isinstance(node, ast.Constant):
        yield node.value
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for el in node.elts:
            if isinstance(el, ast.Constant):
                yield el.value


class _JitIndex:
    """Per-file: which function defs are jit-reachable, and with which
    static params."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        # name -> (fnode, jit call node | None)
        self.jitted: dict[str, tuple] = {}
        self._defs: dict[str, ast.AST] = {}
        self._collect()

    def _collect(self):
        # every def in the file (any nesting), by name (last wins)
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                self._defs[node.name] = node
        # decorated defs
        for name, fnode in self._defs.items():
            for dec in getattr(fnode, "decorator_list", ()):
                if _is_jit_callee(dec):
                    call = dec if isinstance(dec, ast.Call) else None
                    # @partial(jax.jit, static_argnums=...) carries the
                    # kwargs on the partial call itself
                    self.jitted[name] = (fnode, call)
        # name-wrapped defs: x = jax.jit(fn, ...) anywhere in the file
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.Call) and _is_jit_callee(node.func):
                if node.args and isinstance(node.args[0], ast.Name):
                    fname = node.args[0].id
                    fnode = self._defs.get(fname)
                    if fnode is not None:
                        self.jitted[fname] = (fnode, node)
        # propagate through bare same-file calls from jitted bodies
        changed = True
        while changed:
            changed = False
            for name, (fnode, _call) in list(self.jitted.items()):
                for node in ast.walk(fnode):
                    if isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Name):
                        callee = node.func.id
                        if callee in self._defs \
                                and callee not in self.jitted:
                            self.jitted[callee] = (
                                self._defs[callee], None)
                            changed = True

    def each(self):
        """(fname, fnode, traced param-name set) per jitted fn."""
        for name, (fnode, call) in sorted(self.jitted.items()):
            static = _static_params(call, fnode)
            params = {a.arg for a in (fnode.args.posonlyargs
                                      + fnode.args.args
                                      + fnode.args.kwonlyargs)}
            params.discard("self")
            yield name, fnode, params - static, call


def _own_nodes(fnode):
    """Walk a function body but NOT into nested defs (they have their
    own parameter scopes and their own jit-index entries if reachable)."""
    stack = list(fnode.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _names_in(node) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class _JitRuleBase(Rule):
    def visit(self, ctx: FileContext):
        idx = _JitIndex(ctx)
        out = []
        for fname, fnode, traced, call in idx.each():
            out.extend(self.check(ctx, fname, fnode, traced, call))
        return out

    def check(self, ctx, fname, fnode, traced, call):
        return ()


@register
class JitHostSyncRule(_JitRuleBase):
    name = "jit-host-sync"
    description = ("device->host sync (.item() / float() / "
                   "np.asarray on a tracer) inside a jitted function")

    def check(self, ctx, fname, fnode, traced, call):
        out = []
        for node in _own_nodes(fnode):
            if not isinstance(node, ast.Call):
                continue
            # .item() on ANY receiver, incl. call results (x.sum().item())
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                recv = _dotted(node.func.value) \
                    or ast.unparse(node.func.value)[:40]
                out.append(self.finding(
                    ctx, node.lineno,
                    f"{recv}.item() in jitted `{fname}`: .item() "
                    f"forces a device->host sync on every call",
                    key=f"{(ctx.tree_rel or ctx.relpath)}::{fname}::{recv}.item"))
                continue
            d = _dotted(node.func)
            if d is None:
                continue
            parts = d.split(".")
            if len(parts) == 1 \
                    and parts[0] in ("float", "int", "bool") \
                    and len(node.args) == 1 \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in traced:
                out.append(self.finding(
                    ctx, node.lineno,
                    f"{parts[0]}({node.args[0].id}) in jitted "
                    f"`{fname}`: concretizes a traced argument "
                    f"(host sync, or TracerConversion error)",
                    key=f"{(ctx.tree_rel or ctx.relpath)}::{fname}::"
                        f"{parts[0]}({node.args[0].id})"))
            elif len(parts) == 2 and parts[0] in _NP_ALIASES \
                    and parts[1] in _HOST_PULLS and node.args \
                    and (_names_in(node.args[0]) & traced):
                out.append(self.finding(
                    ctx, node.lineno,
                    f"{d}(...) on traced argument(s) "
                    f"{sorted(_names_in(node.args[0]) & traced)} in "
                    f"jitted `{fname}`: numpy conversion pulls the "
                    f"value to host (sync) or fails on a tracer",
                    key=f"{(ctx.tree_rel or ctx.relpath)}::{fname}::{d}"))
        return out


@register
class JitTraceBranchRule(_JitRuleBase):
    name = "jit-trace-branch"
    description = ("Python if/while on a traced argument inside a "
                   "jitted function (recompile bomb / tracer error)")

    def check(self, ctx, fname, fnode, traced, call):
        out = []
        for node in _own_nodes(fnode):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            test = node.test
            hot: set[str] = set()
            if isinstance(test, ast.Name) and test.id in traced:
                hot = {test.id}
            elif isinstance(test, (ast.Compare, ast.BoolOp,
                                   ast.UnaryOp)):
                # only direct Name operands — `cfg.flag > 0` on a
                # closure config is the dominant legit idiom here
                hot = {n.id for n in ast.walk(test)
                       if isinstance(n, ast.Name)} & traced
            if hot:
                kw = "if" if isinstance(node, ast.If) else "while"
                out.append(self.finding(
                    ctx, node.lineno,
                    f"Python `{kw}` on traced argument(s) "
                    f"{sorted(hot)} in jitted `{fname}`: branch is "
                    f"resolved at trace time (recompile per value via "
                    f"static shapes, or TracerBoolConversion) — use "
                    f"lax.cond/jnp.where or mark the arg static",
                    key=f"{(ctx.tree_rel or ctx.relpath)}::{fname}::{kw}:"
                        f"{','.join(sorted(hot))}"))
        return out


@register
class JitNondeterminismRule(_JitRuleBase):
    name = "jit-nondeterminism"
    description = ("wall-clock/random read inside a jitted function "
                   "(baked in at trace time)")

    def check(self, ctx, fname, fnode, traced, call):
        out = []
        for node in _own_nodes(fnode):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None:
                continue
            parts = d.split(".")
            bad = None
            if d in _CLOCK_CALLS or (len(parts) > 1 and
                                     ".".join(parts[-2:])
                                     in _CLOCK_CALLS):
                bad = "wall-clock read"
            elif len(parts) >= 2 and parts[0] in _RANDOM_MODS:
                bad = "python random draw"
            elif len(parts) >= 3 and parts[0] in _NP_ALIASES \
                    and parts[1] == "random":
                bad = "numpy random draw"
            if bad:
                out.append(self.finding(
                    ctx, node.lineno,
                    f"{d}() in jitted `{fname}`: {bad} executes once "
                    f"at TRACE time and is a constant thereafter "
                    f"(stale clocks / identical 'randomness' every "
                    f"call) — pass values in, or use jax.random keys",
                    key=f"{(ctx.tree_rel or ctx.relpath)}::{fname}::{d}"))
        return out


@register
class JitStaticUnhashableRule(_JitRuleBase):
    name = "jit-static-unhashable"
    description = ("static_argnums/argnames parameter with a mutable "
                   "(unhashable) default")

    def check(self, ctx, fname, fnode, traced, call):
        if call is None:
            return ()
        static = _static_params(call, fnode)
        if not static:
            return ()
        out = []
        args = fnode.args.posonlyargs + fnode.args.args
        defaults = fnode.args.defaults
        offset = len(args) - len(defaults)
        pairs = [(a.arg, d) for a, d in zip(args[offset:], defaults)]
        pairs += [(a.arg, d) for a, d in
                  zip(fnode.args.kwonlyargs, fnode.args.kw_defaults)
                  if d is not None]
        for pname, dflt in pairs:
            if pname in static and isinstance(
                    dflt, (ast.List, ast.Dict, ast.Set)):
                kind = {ast.List: "list", ast.Dict: "dict",
                        ast.Set: "set"}[type(dflt)]
                out.append(self.finding(
                    ctx, dflt.lineno,
                    f"static arg `{pname}` of jitted `{fname}` "
                    f"defaults to a {kind}: jit hashes static args "
                    f"for its compile cache — unhashable default "
                    f"raises at the first defaulted call (use a "
                    f"tuple/frozenset/None)",
                    key=f"{(ctx.tree_rel or ctx.relpath)}::{fname}::{pname}"))
        return out
