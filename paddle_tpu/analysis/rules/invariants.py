"""Invariant rules migrated from the three legacy AST scripts
(scripts/check_no_wire_pickle.py, check_metric_names.py,
check_env_knobs.py).

The detection logic lives HERE, once: tree-level helper functions
(``wire_hits``, ``metric_regs``, ``knobs_in_tree``) operate on an
already-parsed AST so the engine runs them on its single shared parse,
while the ``*_main`` entry points reproduce the legacy scripts'
standalone behavior — same argv conventions, same stdout, same exit
codes — so the script files themselves are thin wrappers and the
existing test wiring stays green.
"""
from __future__ import annotations

import ast
import os
import re

from ..core import FileContext, KeyCounter, Rule, register

__all__ = ["WirePickleRule", "MetricNamesRule", "EnvKnobsRule",
           "BenchSchemaRule", "REQUIRED_METRICS", "wire_hits",
           "metric_regs", "knobs_in_tree", "wire_main", "metric_main",
           "env_main", "bench_schema_main", "bench_result_paths"]


# ---------------------------------------------------------------------------
# no-pickle-on-the-wire (from check_no_wire_pickle.py)
# ---------------------------------------------------------------------------

BANNED_PICKLE_ATTRS = {"load", "loads", "Unpickler"}
PICKLE_MODULES = {"pickle", "cPickle", "_pickle", "dill"}

# subtrees held to the data-only rule when scanning the shipped tree
# (relative to paddle_tpu/): the transport package and every
# checkpoint RESTORE path (docs/PS_WIRE_PROTOCOL.md, CHECKPOINT.md).
# incubate/ joined when its CheckpointSaver moved onto the store: its
# one legacy pickle read lives in fluid/io.legacy_pickle_load (a
# position-exempt disk-archive shim, like fluid/io's own)
WIRE_SUBTREES = ("distributed/", "checkpoint/", "incubate/")


def _pickle_aliases(tree: ast.AST) -> set[str]:
    """Names that refer to a pickle module or its load/loads in this
    module (import pickle / import pickle as p / from pickle import
    loads as x)."""
    mods, funcs = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.split(".")[0] in PICKLE_MODULES:
                    mods.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.module.split(".")[0] \
                    in PICKLE_MODULES:
                for a in node.names:
                    if a.name in BANNED_PICKLE_ATTRS:
                        funcs.add(a.asname or a.name)
    return mods | funcs


def wire_hits(tree: ast.AST) -> list[tuple[int, str]]:
    """(line, what) pickle-deserialization sites in one parsed file."""
    aliases = _pickle_aliases(tree)
    hits = []
    for node in ast.walk(tree):
        # pickle.load(...)/pickle.loads(...)/pickle.Unpickler(...)
        if isinstance(node, ast.Attribute) \
                and node.attr in BANNED_PICKLE_ATTRS \
                and isinstance(node.value, ast.Name) \
                and node.value.id in aliases:
            hits.append((node.lineno,
                         f"{node.value.id}.{node.attr}"))
        # from pickle import loads; loads(...)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Name) \
                and node.func.id in aliases:
            hits.append((node.lineno, f"{node.func.id}(...)"))
        # np.load(..., allow_pickle=True)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "load":
            for kw in node.keywords:
                if kw.arg == "allow_pickle" \
                        and isinstance(kw.value, ast.Constant) \
                        and kw.value.value is True:
                    hits.append((node.lineno,
                                 "np.load(allow_pickle=True)"))
    return hits


def _wire_check_path(path: str) -> list[tuple[int, str]]:
    """Standalone-file form (legacy script path): parse + scan."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, path)
    except SyntaxError as e:
        return [(e.lineno or 0, f"unparseable: {e.msg}")]
    return wire_hits(tree)


def wire_main(argv: list[str], repo: str) -> int:
    """check_no_wire_pickle.py behavior, byte-identical output."""
    if len(argv) > 1:
        roots = argv[1:]
    else:
        roots = [os.path.join(repo, "paddle_tpu", "distributed"),
                 os.path.join(repo, "paddle_tpu", "checkpoint"),
                 os.path.join(repo, "paddle_tpu", "incubate")]
    bad = []
    for root in roots:
        for dirpath, _dirs, files in os.walk(root):
            for fn in sorted(files):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                for lineno, what in _wire_check_path(path):
                    bad.append(f"{path}:{lineno}: {what}")
    shown = ", ".join(roots)
    if bad:
        print("pickle deserialization is banned under "
              f"{shown} (wire-safety, see docs/PS_WIRE_PROTOCOL.md "
              "and docs/CHECKPOINT.md):")
        print("\n".join(bad))
        return 1
    print(f"OK: no pickle deserialization under {shown}")
    return 0


@register
class WirePickleRule(Rule):
    name = "wire-pickle"
    description = ("pickle deserialization in the transport/checkpoint "
                   "trees (RCE-on-the-wire hazard)")

    def visit(self, ctx: FileContext):
        # a file INSIDE the shipped tree is judged by its position
        # there whatever the scan root (paddle_tpu/fluid/io.py's
        # legacy disk-archive pickle is exempt even under
        # `--root paddle_tpu/fluid`); files outside the tree
        # (fixtures) are all held to the rule
        if ctx.tree_rel is not None and not ctx.tree_rel.startswith(
                WIRE_SUBTREES):
            return ()
        dedup = KeyCounter()   # content-based keys; #2.. on repeats
        keypath = ctx.tree_rel or ctx.relpath  # stable across roots
        return [self.finding(
            ctx, line,
            f"{what} — pickle deserialization is banned here "
            f"(wire-safety: docs/PS_WIRE_PROTOCOL.md, "
            f"docs/CHECKPOINT.md)",
            key=dedup(f"{keypath}::{what}"))
            for line, what in wire_hits(ctx.tree)]


# ---------------------------------------------------------------------------
# metric naming (from check_metric_names.py)
# ---------------------------------------------------------------------------

REGISTER_FUNCS = {"counter", "gauge", "histogram"}
NAME_RE = re.compile(r"^paddle_tpu_[a-z][a-z0-9_]*$")
# the registry's own implementation/docs mention registration calls in
# prose/examples; skip only files that themselves DEFINE the helpers
SKIP_FILES = {os.path.join("observability", "registry.py"),
              os.path.join("observability", "__init__.py")}

# metric families whose presence is contractual (docs/CHECKPOINT.md,
# docs/DEBUGGING.md): a registration site must exist for each, or the
# check fails
REQUIRED_METRICS = {
    "paddle_tpu_ckpt_save_seconds",
    "paddle_tpu_ckpt_restore_seconds",
    "paddle_tpu_ckpt_bytes_written_total",
    "paddle_tpu_ckpt_chunks_written_total",
    "paddle_tpu_ckpt_chunks_dedup_hits_total",
    "paddle_tpu_ckpt_wal_rows_appended_total",
    "paddle_tpu_ckpt_wal_compactions_total",
    "paddle_tpu_ckpt_manifests_committed_total",
    # checkpoint async-writer queue (docs/DEBUGGING.md): a rising depth
    # means the save cadence is outrunning the writer
    "paddle_tpu_ckpt_writer_queue_depth",
    "paddle_tpu_ckpt_writer_pending_bytes",
    "paddle_tpu_ckpt_inflight_save_seconds",
    # stall watchdog + flight recorder (docs/DEBUGGING.md): the
    # postmortem tier's own observability is part of its acceptance
    # contract — deleting it would ship silent hang detection
    "paddle_tpu_watchdog_checks_total",
    "paddle_tpu_watchdog_stalls_total",
    "paddle_tpu_watchdog_stalled",
    "paddle_tpu_watchdog_progress_age_seconds",
    "paddle_tpu_flight_events_total",
    "paddle_tpu_flight_dropped_total",
    # SLO harness (docs/SERVING.md production traffic harness): the
    # load generator's attainment/goodput surface and the scheduler's
    # admission-control decisions are acceptance-contractual — the
    # chaos drills assert against these exact names
    "paddle_tpu_slo_ttft_seconds",
    "paddle_tpu_slo_inter_token_seconds",
    "paddle_tpu_slo_deadline_met_total",
    "paddle_tpu_slo_deadline_missed_total",
    "paddle_tpu_slo_goodput_tokens_total",
    "paddle_tpu_slo_attainment_ratio",
    "paddle_tpu_serving_expired_in_queue_total",
    "paddle_tpu_serving_shed_total",
    "paddle_tpu_serving_quota_rejected_total",
    # serving router (docs/SERVING.md replicated serving): failover,
    # replica health and respawn visibility is the fleet's acceptance
    # contract — the chaos drills assert against these exact names
    "paddle_tpu_router_requests_total",
    "paddle_tpu_router_dispatch_total",
    "paddle_tpu_router_failovers_total",
    "paddle_tpu_router_replica_state",
    "paddle_tpu_router_respawns_total",
    "paddle_tpu_router_stream_stalls_total",
    "paddle_tpu_router_inflight",
    # autobench persistent tuning cache (docs/KERNELS.md): whether a
    # replica is measuring in-process (cold) or adopting pre-warmed
    # decisions (hit) is the cache's acceptance contract
    "paddle_tpu_autobench_cache_hits_total",
    "paddle_tpu_autobench_cache_misses_total",
    "paddle_tpu_autobench_cache_stale_total",
    "paddle_tpu_autobench_cache_corrupt_total",
    "paddle_tpu_autobench_measure_total",
    # multiplexed RPC transport (docs/PS_WIRE_PROTOCOL.md mux framing):
    # in-flight depth, pool size, zero-copy proof (bytes-copied by
    # path) and reply reordering are the transport's acceptance
    # contract — the transport bench asserts against these exact names
    "paddle_tpu_rpc_mux_inflight",
    "paddle_tpu_rpc_mux_channels",
    "paddle_tpu_rpc_mux_bytes_copied_total",
    "paddle_tpu_rpc_mux_out_of_order_total",
    # online-learning publish pipeline (docs/ONLINE_LEARNING.md):
    # publication/rollback counts, cross-version chunk dedup, hot-swap
    # phase timing and subscriber staleness are the loop's acceptance
    # contract — the swap-under-load drill and the online bench assert
    # against these exact names
    "paddle_tpu_publish_publications_total",
    "paddle_tpu_publish_rollbacks_total",
    "paddle_tpu_publish_dedup_ratio",
    "paddle_tpu_publish_seconds",
    "paddle_tpu_publish_swap_seconds",
    "paddle_tpu_publish_subscriber_lag_versions",
    # fleet telemetry plane (docs/OBSERVABILITY.md): span-ring loss,
    # agent-side backpressure drops and the tail-sampling verdict split
    # are the plane's honesty surface — without them telemetry loss is
    # silent and every downstream dashboard lies
    "paddle_tpu_trace_dropped_total",
    "paddle_tpu_telemetry_agent_dropped_total",
    "paddle_tpu_telemetry_traces_total",
    # perf observability plane (docs/OBSERVABILITY.md perf plane): the
    # cost registry, live MFU/breakdown attribution, compile wall-time
    # and memory headroom gauges are the plane's acceptance contract —
    # the perfwatch sentinel and the `top` perf pane read these exact
    # names
    "paddle_tpu_perf_flops",
    "paddle_tpu_perf_bytes",
    "paddle_tpu_perf_mfu",
    "paddle_tpu_perf_step_breakdown_seconds",
    "paddle_tpu_perf_compile_seconds",
    "paddle_tpu_perf_hbm_bytes",
    "paddle_tpu_perf_kv_cache_bytes",
    # elastic training (docs/ELASTIC.md): hang-vs-straggler split,
    # restart/give-up accounting and resume latency are the gang-
    # restart tier's acceptance contract — the chaos drills and the
    # launcher's watchdog read these exact names
    "paddle_tpu_elastic_heartbeats_total",
    "paddle_tpu_elastic_stale_ranks",
    "paddle_tpu_elastic_straggler_ranks",
    "paddle_tpu_elastic_step_lag",
    "paddle_tpu_elastic_restarts_total",
    "paddle_tpu_elastic_crash_loop_giveups_total",
    "paddle_tpu_elastic_resume_seconds",
    # PS high availability (docs/PS_HA.md): role/epoch/fencing state,
    # per-standby replication lag, semi-sync degradation and the
    # promotion/handoff/resync counts are the HA plane's acceptance
    # contract — the failover drills and the ps_ha bench read these
    # exact names
    "paddle_tpu_ps_ha_role",
    "paddle_tpu_ps_ha_epoch",
    "paddle_tpu_ps_ha_standbys_connected",
    "paddle_tpu_ps_ha_replication_lag_rows",
    "paddle_tpu_ps_ha_replication_lag_bytes",
    "paddle_tpu_ps_ha_replication_lag_seconds",
    "paddle_tpu_ps_ha_records_shipped_total",
    "paddle_tpu_ps_ha_semisync_total",
    "paddle_tpu_ps_ha_fenced_writes_total",
    "paddle_tpu_ps_ha_promotions_total",
    "paddle_tpu_ps_ha_handoffs_total",
    "paddle_tpu_ps_ha_resyncs_total",
    # tiered embedding store (docs/PS_TIERED.md): per-tier hit/miss
    # and residency, demand-page faults, demotions, cold-read errors
    # and the by-tier pull latency histogram are the tier hierarchy's
    # acceptance contract — the tiered bench and the collector/top
    # tier pane read these exact names
    "paddle_tpu_ps_tier_hits_total",
    "paddle_tpu_ps_tier_misses_total",
    "paddle_tpu_ps_tier_resident_rows",
    "paddle_tpu_ps_tier_resident_bytes",
    "paddle_tpu_ps_tier_faults_total",
    "paddle_tpu_ps_tier_demotions_total",
    "paddle_tpu_ps_tier_cold_read_errors_total",
    "paddle_tpu_ps_tier_pull_seconds",
    # fleet time-series plane (docs/OBSERVABILITY.md): TSDB
    # durability/retention accounting, alert lifecycle counts and the
    # per-tenant usage series are the plane's acceptance contract —
    # the burn-rate chaos drill, `top history/alerts/tenants` and the
    # tsdb bench read these exact names
    "paddle_tpu_tsdb_samples_total",
    "paddle_tpu_tsdb_series",
    "paddle_tpu_tsdb_bytes_on_disk",
    "paddle_tpu_tsdb_blocks_sealed_total",
    "paddle_tpu_tsdb_blocks_compacted_total",
    "paddle_tpu_tsdb_blocks_deleted_total",
    "paddle_tpu_tsdb_torn_tail_truncated_total",
    "paddle_tpu_alerts_evaluations_total",
    "paddle_tpu_alerts_transitions_total",
    "paddle_tpu_alerts_firing",
    "paddle_tpu_tenant_tokens_in_total",
    "paddle_tpu_tenant_tokens_out_total",
    "paddle_tpu_tenant_queue_seconds_total",
    "paddle_tpu_tenant_kv_page_seconds_total",
    "paddle_tpu_tenant_flops_total",
    "paddle_tpu_tenant_requests_total",
    "paddle_tpu_tenant_router_requests_total",
    "paddle_tpu_tenant_overflow_total",
    "paddle_tpu_telemetry_procs_retired_total",
    # shared-prefix KV reuse + replayable sampling (docs/SERVING.md):
    # cache effectiveness (hit/miss/tokens-saved), the COW and
    # eviction safety valves, residency gauges, and how much traffic
    # rides stochastic decode — the prefix bench and the `top` prefix
    # row read these exact names
    "paddle_tpu_prefix_lookup_hits_total",
    "paddle_tpu_prefix_lookup_misses_total",
    "paddle_tpu_prefix_prefill_tokens_saved_total",
    "paddle_tpu_prefix_cow_copies_total",
    "paddle_tpu_prefix_evicted_pages_total",
    "paddle_tpu_prefix_cached_pages",
    "paddle_tpu_prefix_shared_pages",
    "paddle_tpu_sampling_requests_total",
    "paddle_tpu_sampling_tokens_total",
}


def _call_name(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def metric_regs(tree: ast.AST) -> tuple[list[tuple[int, str]],
                                        list[tuple[str, int]]]:
    """(violations, registrations): violations are (line, message);
    registrations are (metric_name, line) for the duplicate pass."""
    bad: list[tuple[int, str]] = []
    regs: list[tuple[str, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _call_name(node) not in REGISTER_FUNCS:
            continue
        if not node.args:
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            continue
        name = first.value
        if not NAME_RE.match(name):
            bad.append((node.lineno,
                        f"metric name {name!r} must match "
                        f"{NAME_RE.pattern}"))
        else:
            regs.append((name, node.lineno))
    return bad, regs


def _metric_check_path(path: str):
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, path)
    except SyntaxError as e:
        return [(e.lineno or 0, f"unparseable: {e.msg}")], []
    return metric_regs(tree)


def metric_main(argv: list[str], repo: str) -> int:
    """check_metric_names.py behavior, byte-identical output."""
    default_root = len(argv) <= 1
    if not default_root:
        root = argv[1]
    else:
        root = os.path.join(repo, "paddle_tpu")
    violations: list[str] = []
    sites: dict[str, list[str]] = {}
    for dirpath, _dirs, files in os.walk(root):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            if rel in SKIP_FILES:
                continue
            bad, regs = _metric_check_path(path)
            for lineno, what in bad:
                violations.append(f"{path}:{lineno}: {what}")
            for name, lineno in regs:
                sites.setdefault(name, []).append(f"{path}:{lineno}")
    for name, where in sorted(sites.items()):
        if len(where) > 1:
            violations.append(
                f"duplicate registration of {name!r} at "
                + ", ".join(where))
    if default_root:  # an explicit root is a partial tree by design
        for name in sorted(REQUIRED_METRICS - set(sites)):
            violations.append(
                f"required metric {name!r} has no registration site "
                "(checkpoint-tier instrumentation is contractual — "
                "docs/CHECKPOINT.md)")
    if violations:
        print(f"metric naming violations under {root} "
              "(see docs/OBSERVABILITY.md naming scheme):")
        print("\n".join(violations))
        return 1
    print(f"OK: {sum(len(w) for w in sites.values())} metric "
          f"registrations under {root} are well-named and unique")
    return 0


@register
class MetricNamesRule(Rule):
    name = "metric-names"
    description = ("metric naming scheme, single registration site, "
                   "required-metric ratchet")

    def __init__(self):
        self._sites: dict[str, list[tuple[str, str, int]]] = {}

    def visit(self, ctx: FileContext):
        # SKIP_FILES are positions in the SHIPPED tree — honored for
        # any scan root that reaches them (registry.py defines the
        # helpers; its example strings are not registrations)
        if ctx.tree_rel is not None \
                and ctx.tree_rel.replace("/", os.sep) in SKIP_FILES:
            return ()
        bad, regs = metric_regs(ctx.tree)
        for name, lineno in regs:
            self._sites.setdefault(name, []).append(
                (ctx.path, ctx.relpath, lineno))
        dedup = KeyCounter()   # content-based keys; #2.. on repeats
        keypath = ctx.tree_rel or ctx.relpath  # stable across roots
        return [self.finding(ctx, line, msg,
                             key=dedup(f"{keypath}::{msg}"))
                for line, msg in bad]

    def finalize(self, run):
        out = []
        for name, where in sorted(self._sites.items()):
            if len(where) > 1:
                shown = ", ".join(f"{p}:{ln}" for p, _r, ln in where)
                out.append(self.finding(
                    where[0][0], where[0][2],
                    f"duplicate registration of {name!r} at {shown}",
                    key=f"dup::{name}"))
        if run.default_scan:  # a subtree is a partial view by design
            for name in sorted(REQUIRED_METRICS - set(self._sites)):
                out.append(self.finding(
                    run.root, 0,
                    f"required metric {name!r} has no registration "
                    f"site (its tier's instrumentation is "
                    f"contractual — docs/CHECKPOINT.md, "
                    f"docs/DEBUGGING.md)",
                    key=f"required::{name}"))
        return out


# ---------------------------------------------------------------------------
# env-knob documentation (from check_env_knobs.py)
# ---------------------------------------------------------------------------

# full uppercase-snake knob names only: the trailing-underscore prefix
# literals the typo guard scans with ("PADDLE_PS_FAULT_") are not knobs
KNOB_RE = re.compile(r"^PADDLE_(?:TPU|PS)_[A-Z0-9]+(?:_[A-Z0-9]+)*$")
FIND_RE = re.compile(r"PADDLE_(?:TPU|PS)_[A-Z0-9_]*[A-Z0-9]")


def _knob_names_in(text: str):
    for m in FIND_RE.finditer(text):
        # a match the text continues with "_" is a prefix literal
        # ("PADDLE_PS_FAULT_" in the typo guard, "PADDLE_PS_FAULT_*"
        # in prose), not a knob name
        if m.end() < len(text) and text[m.end()] == "_":
            continue
        if KNOB_RE.match(m.group(0)):
            yield m.group(0)


def knobs_in_tree(tree: ast.AST) -> dict[str, int]:
    """knob name -> first line, from string literals in one file."""
    out: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value,
                                                        str):
            for name in _knob_names_in(node.value):
                out.setdefault(name, node.lineno)
    return out


def _knobs_in_path(path: str) -> dict[str, str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, path)
    except SyntaxError:
        return {}
    return {name: f"{path}:{line}"
            for name, line in knobs_in_tree(tree).items()}


def knobs_in_docs(paths: list[str]) -> set[str]:
    found: set[str] = set()
    for path in paths:
        if not os.path.isfile(path):
            continue
        with open(path, encoding="utf-8") as f:
            text = f.read()
        found.update(_knob_names_in(text))
    return found


def default_docs_paths(repo: str) -> list[str]:
    docs_dir = os.path.join(repo, "docs")
    paths = [os.path.join(docs_dir, f)
             for f in sorted(os.listdir(docs_dir))
             if f.endswith(".md")]
    paths.append(os.path.join(repo, "README.md"))
    return paths


def env_main(argv: list[str], repo: str) -> int:
    """check_env_knobs.py behavior, byte-identical output."""
    code_root = argv[1] if len(argv) > 1 else os.path.join(repo,
                                                           "paddle_tpu")
    if len(argv) > 2:
        docs_paths = [os.path.join(argv[2], f)
                      for f in sorted(os.listdir(argv[2]))
                      if f.endswith(".md")]
    else:
        docs_paths = default_docs_paths(repo)
    code: dict[str, str] = {}
    for dirpath, _dirs, files in os.walk(code_root):
        if "__pycache__" in dirpath:
            continue
        for fn in sorted(files):
            if fn.endswith(".py"):
                for name, site in _knobs_in_path(
                        os.path.join(dirpath, fn)).items():
                    code.setdefault(name, site)
    documented = knobs_in_docs(docs_paths)
    missing = sorted(set(code) - documented)
    if missing:
        print(f"undocumented env knobs under {code_root} "
              "(add them to a docs/ table — docs/ENV_KNOBS.md is the "
              "master index):")
        for name in missing:
            print(f"  {name}  (first read at {code[name]})")
        return 1
    print(f"OK: {len(code)} env knobs under {code_root} are all "
          f"documented across {len(docs_paths)} docs files")
    return 0


@register
class EnvKnobsRule(Rule):
    name = "env-knobs"
    description = ("every PADDLE_TPU_*/PADDLE_PS_* knob read by the "
                   "code is documented in docs/")

    # tests may point the docs side elsewhere
    docs_paths: list[str] | None = None

    def __init__(self):
        self._code: dict[str, tuple[str, int]] = {}

    def visit(self, ctx: FileContext):
        for name, line in knobs_in_tree(ctx.tree).items():
            self._code.setdefault(name, (ctx.path, line))
        return ()

    def finalize(self, run):
        from ..core import repo_root
        paths = self.docs_paths
        if paths is None:
            # fixture/subtree roots are still held to the REPO docs
            # contract — a knob is documented or it is not, regardless
            # of which subtree the scan started from
            paths = default_docs_paths(repo_root())
        documented = knobs_in_docs(paths)
        return [self.finding(
            self._code[name][0], self._code[name][1],
            f"undocumented env knob {name!r} — add a row to "
            f"docs/ENV_KNOBS.md (master index)",
            key=f"knob::{name}")
            for name in sorted(set(self._code) - documented)]


# ---------------------------------------------------------------------------
# bench-result schema (perfwatch sentinel inputs)
# ---------------------------------------------------------------------------

# the repo-root benchmark artifacts the perf-regression sentinel
# compares across revisions (docs/OBSERVABILITY.md perf plane)
BENCH_RESULT_RE = re.compile(r"^BENCH_r\d+.*\.json$")


def _load_perfwatch():
    """The perfwatch validator WITHOUT importing the jax-heavy
    paddle_tpu package (same trick as scripts/_analysis_loader.py):
    observability/perfwatch.py is stdlib-only at module level by
    contract, so it loads standalone straight from its file."""
    import importlib.util
    import sys
    if "paddle_tpu.observability.perfwatch" in sys.modules:
        return sys.modules["paddle_tpu.observability.perfwatch"]
    if "pt_perfwatch" not in sys.modules:
        from ..core import repo_root
        path = os.path.join(repo_root(), "paddle_tpu",
                            "observability", "perfwatch.py")
        spec = importlib.util.spec_from_file_location(
            "pt_perfwatch", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["pt_perfwatch"] = mod
        spec.loader.exec_module(mod)
    return sys.modules["pt_perfwatch"]


def bench_result_paths(repo: str) -> list[str]:
    return [os.path.join(repo, fn) for fn in sorted(os.listdir(repo))
            if BENCH_RESULT_RE.match(fn)]


def bench_schema_main(argv: list[str], repo: str) -> int:
    """check_bench_schema.py behavior: every benchmark artifact must
    parse under the perfwatch record schema, or `perfwatch compare`
    against a future revision silently loses metrics."""
    paths = argv[1:] or bench_result_paths(repo)
    pw = _load_perfwatch()
    bad = []
    for path in paths:
        try:
            problems = pw.validate_file(path)
        except OSError as e:
            problems = [f"unreadable: {e}"]
        bad.extend(f"{path}: {p}" for p in problems)
    if bad:
        print("bench result files violate the perfwatch record schema "
              "(docs/OBSERVABILITY.md perf plane — `perfwatch "
              "compare` reads these):")
        print("\n".join(bad))
        return 1
    print(f"OK: {len(paths)} bench result file(s) conform to the "
          f"perfwatch record schema")
    return 0


@register
class BenchSchemaRule(Rule):
    name = "bench-schema"
    description = ("repo-root BENCH_r*.json artifacts parse under the "
                   "perfwatch record schema (the perf-regression "
                   "sentinel's input contract)")

    def visit(self, ctx: FileContext):
        return ()

    def finalize(self, run):
        if not run.default_scan:  # fixture/subtree scans carry no
            return ()             # benchmark artifacts
        from ..core import repo_root
        out = []
        dedup = KeyCounter()
        for path in bench_result_paths(repo_root()):
            try:
                problems = _load_perfwatch().validate_file(path)
            except Exception as e:  # a validator crash must not take
                problems = [f"validator error: {e}"]  # down the scan
            rel = os.path.basename(path)
            out.extend(self.finding(
                path, 0, f"bench artifact {problem}",
                key=dedup(f"bench::{rel}::{problem}"))
                for problem in problems)
        return out
