"""paddle_tpu.analysis — unified static-analysis engine + runtime
lock-order sanitizer.

The codebase is a heavily threaded system (engine step lock, scheduler
lock, PS apply lock, checkpoint writer, autobench publish lock)
stitched to a jit-compiled hot path. This package is its correctness
tooling, replacing the three ad-hoc AST scripts with ONE engine
(reference analog: PADDLE_ENFORCE-style invariant tooling at every
tier, PAPER.md L0):

  * ``core``       — one AST parse per file, a rule registry, findings
                     as file:line JSON + human text, and a per-rule
                     shrink-only baseline/ratchet file;
  * ``rules``      — three rule families: concurrency (lock-order
                     graph, blocking calls under hot locks, opaque
                     callbacks under locks), jit-hazards (host syncs
                     and recompile bombs inside jit-reachable code),
                     and the invariants migrated from the legacy
                     scripts (wire-pickle, metric-name, env-knob);
  * ``lockcheck``  — a test-mode runtime sanitizer that wraps
                     ``threading.Lock/RLock/Condition`` under
                     ``PADDLE_TPU_LOCKCHECK=1``, records the per-thread
                     acquisition graph, and fails on lock-order cycles
                     — the dynamic complement validating the static
                     lock model.

CLI: ``python -m paddle_tpu.analysis [--rule NAME ...] [--root DIR]
[--baseline update] [--json]`` (docs/STATIC_ANALYSIS.md).

This package (and everything it imports) is stdlib-only on purpose:
the legacy ``scripts/check_*.py`` wrappers and the ``PADDLE_TPU_
LOCKCHECK`` install hook load it WITHOUT importing the jax-heavy
``paddle_tpu`` parent, and the lockcheck install in
``paddle_tpu/__init__`` must run before any framework lock exists.
"""
# NOTE: keep this module import-light (no submodule imports at package
# import time) — see the docstring. `from paddle_tpu.analysis import
# core` / `... import lockcheck` are the entry points.
