"""Per-op microbenchmark harness over the registry.

The reference ships a standalone per-op latency tool
(/root/reference/paddle/fluid/operators/benchmark/op_tester.cc:1 with
OpTesterConfig files naming an op, its input shapes and repeat count).
This is its registry-native equivalent: each case jits one op kernel at
a configured shape, times `repeat` dispatches with a single device sync,
and emits one JSON record per case — wall ms, achieved GB/s against the
case's array-IO bytes, and the output signature.

Usage:
  python -m paddle_tpu.tools.op_bench                 # built-in sweep
  python -m paddle_tpu.tools.op_bench --ops matmul,softmax
  python -m paddle_tpu.tools.op_bench --config cases.json --out r.json

Config file: JSON list of cases,
  {"op": "matmul", "inputs": {"X": {"shape": [4096, 4096]},
   "Y": {"shape": [4096, 4096]}}, "attrs": {}, "repeat": 20}
dtype defaults to float32 ("int64"/"int32" inputs draw random indices
bounded by "high").
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np

__all__ = ["run_case", "run_cases", "DEFAULT_CASES"]


def _make_input(spec, rng):
    shape = tuple(spec.get("shape", ()))
    dtype = spec.get("dtype", "float32")
    if "value" in spec:
        return np.asarray(spec["value"], dtype)
    if dtype.startswith("int") or dtype == "bool":
        return rng.randint(0, spec.get("high", 8), shape).astype(dtype)
    return rng.randn(*shape).astype(dtype)


def run_case(case: dict) -> dict:
    import jax
    import jax.numpy as jnp

    from ..fluid import registry
    from ..fluid.executor import ExecContext

    op = case["op"]
    repeat = int(case.get("repeat", 20))
    opdef = registry.lookup(op)
    if opdef is None:
        return {"op": op, "error": "not registered"}
    rng = np.random.RandomState(int(case.get("seed", 0)))
    ins_np = {slot: [_make_input(s, rng) for s in
                     (spec if isinstance(spec, list) else [spec])]
              for slot, spec in case.get("inputs", {}).items()}
    attrs = dict(case.get("attrs", {}))
    opdef.fill_default_attrs(attrs)
    if opdef.stochastic:
        attrs.setdefault("_rng_id", 0)

    ins = {k: [jnp.asarray(a) for a in v] for k, v in ins_np.items()}
    ctx = ExecContext(jax.random.PRNGKey(0), is_test=bool(
        case.get("is_test", False)))

    def fn(ins):
        return opdef.compute(ctx, ins, attrs)

    try:
        jitted = jax.jit(fn)
        out = jitted(ins)
    except Exception as e:
        return {"op": op, "error": f"{type(e).__name__}: {e}"[:200]}
    leaves = [v for v in jax.tree_util.tree_leaves(out)
              if hasattr(v, "shape")]
    sync = jax.jit(lambda t: jnp.ravel(t)[:1])
    np.asarray(sync(leaves[0]))
    t0 = time.perf_counter()
    for _ in range(repeat):
        out = jitted(ins)
    np.asarray(sync(jax.tree_util.tree_leaves(out)[0]))
    loop = time.perf_counter() - t0
    t1 = time.perf_counter()
    np.asarray(sync(jax.tree_util.tree_leaves(out)[0]))
    dt = max(loop - (time.perf_counter() - t1), loop * 0.5) / repeat

    in_bytes = sum(a.nbytes for v in ins_np.values() for a in v)
    out_bytes = sum(int(np.prod(v.shape)) * v.dtype.itemsize
                    for v in leaves)
    rec = {"op": op, "ms": round(dt * 1e3, 4),
           "io_gb_per_s": round((in_bytes + out_bytes) / dt / 1e9, 2),
           "in_bytes": in_bytes, "out_bytes": out_bytes,
           "outputs": {k: [list(v.shape) for v in vs if
                           hasattr(v, "shape")]
                       for k, vs in out.items()},
           "repeat": repeat}
    if "flops" in case:
        rec["tflops_per_s"] = round(case["flops"] / dt / 1e12, 2)
    return rec


# shapes chosen at BERT/ResNet working points so the numbers relate to
# the model benches; flops given where the op is matmul-shaped
DEFAULT_CASES = [
    {"op": "matmul", "inputs": {"X": {"shape": [4096, 1024]},
                                "Y": {"shape": [1024, 4096]}},
     "flops": 2 * 4096 * 1024 * 4096},
    {"op": "matmul_v2", "inputs": {"X": {"shape": [8192, 768]},
                                   "Y": {"shape": [768, 3072]}},
     "flops": 2 * 8192 * 768 * 3072},
    {"op": "softmax", "inputs": {"X": {"shape": [64, 12, 128, 128]}},
     "attrs": {"axis": -1}},
    {"op": "layer_norm", "inputs": {
        "X": {"shape": [8192, 768]}, "Scale": {"shape": [768]},
        "Bias": {"shape": [768]}}},
    {"op": "gelu", "inputs": {"X": {"shape": [64, 128, 3072]}}},
    {"op": "relu", "inputs": {"X": {"shape": [256, 56, 56, 256]}}},
    {"op": "conv2d", "inputs": {
        "Input": {"shape": [64, 64, 56, 56]},
        "Filter": {"shape": [64, 64, 3, 3]}},
     "attrs": {"strides": [1, 1], "paddings": [1, 1],
               "dilations": [1, 1], "groups": 1},
     "flops": 2 * 64 * 64 * 64 * 9 * 56 * 56},
    {"op": "batch_norm", "inputs": {
        "X": {"shape": [64, 56, 56, 64]}, "Scale": {"shape": [64]},
        "Bias": {"shape": [64]}, "Mean": {"shape": [64]},
        "Variance": {"shape": [64]}},
     "attrs": {"data_layout": "NHWC"}},
    {"op": "dropout", "inputs": {"X": {"shape": [64, 128, 768]}},
     "attrs": {"dropout_prob": 0.1}},
    {"op": "transpose2", "inputs": {"X": {"shape": [64, 128, 12, 64]}},
     "attrs": {"axis": [0, 2, 1, 3]}},
    {"op": "reduce_sum", "inputs": {"X": {"shape": [64, 128, 3072]}},
     "attrs": {"dim": [-1]}},
    {"op": "elementwise_add", "inputs": {
        "X": {"shape": [64, 128, 768]}, "Y": {"shape": [64, 128, 768]}}},
    {"op": "lookup_table_v2", "inputs": {
        "W": {"shape": [30522, 768]},
        "Ids": {"shape": [64, 128], "dtype": "int64", "high": 30522}}},
    {"op": "softmax_with_cross_entropy", "inputs": {
        "Logits": {"shape": [8192, 30522]},
        "Label": {"shape": [8192, 1], "dtype": "int64", "high": 30522}}},
    {"op": "concat", "inputs": {
        "X": [{"shape": [64, 128, 768]}, {"shape": [64, 128, 768]}]},
     "attrs": {"axis": -1}},
    {"op": "slice", "inputs": {"X": {"shape": [64, 128, 768]}},
     "attrs": {"axes": [1], "starts": [0], "ends": [64]}},
    {"op": "scale", "inputs": {"X": {"shape": [64, 128, 768]}},
     "attrs": {"scale": 2.0}},
    {"op": "adam", "inputs": {
        "Param": {"shape": [3072, 768]}, "Grad": {"shape": [3072, 768]},
        "Moment1": {"shape": [3072, 768]},
        "Moment2": {"shape": [3072, 768]},
        "LearningRate": {"value": [1e-3]},
        "Beta1Pow": {"value": [0.9]}, "Beta2Pow": {"value": [0.999]}}},
    {"op": "cholesky", "inputs": {"X": {"value": None}},  # filled below
     "repeat": 5},
    {"op": "gru", "inputs": {
        "Input": {"shape": [32, 64, 384]},
        "Weight": {"shape": [128, 384]}}, "repeat": 5},
]

# positive-definite input for cholesky — located by op name, not index
_m = np.random.RandomState(0).randn(256, 256).astype("float32")
next(c for c in DEFAULT_CASES
     if c["op"] == "cholesky")["inputs"]["X"]["value"] = \
    (_m @ _m.T + 256 * np.eye(256, dtype="float32")).tolist()


def run_cases(cases, ops_filter=None):
    recs = []
    for c in cases:
        if ops_filter and not any(s in c["op"] for s in ops_filter):
            continue
        recs.append(run_case(c))
    return recs


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", help="JSON file with a list of cases")
    ap.add_argument("--ops", help="comma-separated op-name substrings")
    ap.add_argument("--repeat", type=int, default=None)
    ap.add_argument("--out", help="write JSON records here (else stdout)")
    args = ap.parse_args(argv)
    cases = DEFAULT_CASES
    if args.config:
        with open(args.config) as f:
            cases = json.load(f)
    if args.repeat:
        cases = [{**c, "repeat": args.repeat} for c in cases]
    flt = args.ops.split(",") if args.ops else None
    recs = run_cases(cases, flt)
    blob = json.dumps(recs, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(blob)
    else:
        print(blob)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
