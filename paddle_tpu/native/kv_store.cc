// Native sparse-row KV store — the C++ hot path behind LargeScaleKV.
//
// Reference counterpart: paddle/fluid/operators/distributed/large_scale_kv.h
// (in-memory sharded sparse table with init rules serving the PS runtime).
// This implementation keeps the same contract as the Python LargeScaleKV
// (batched pull initialises missing rows once per unique key; push is an
// SGD-style scatter-accumulate over possibly-duplicated keys) but runs the
// id->slot mapping in an open-addressing hash table and the row math over
// a contiguous float arena, so million-row pulls don't touch the Python
// interpreter per key.
//
// C ABI only (ctypes binding in native/__init__.py) — no pybind11 in the
// image by design.

#include <cstdint>
#include <cstring>
#include <random>
#include <vector>

namespace {

constexpr int64_t kEmpty = INT64_MIN;  // not a legal key (checked in
                                        // the ctypes wrapper) — -1 IS a
                                        // legal id (padding indices)

struct KvStore {
  int64_t dim;
  float init_std;
  uint64_t seed;
  // open addressing, power-of-two capacity, empty = kEmpty
  std::vector<int64_t> keys;
  std::vector<int64_t> slots;
  int64_t size = 0;
  std::vector<float> data;  // arena: size*dim floats
  std::mt19937_64 rng;

  explicit KvStore(int64_t d, float std_, uint64_t seed_)
      : dim(d), init_std(std_), seed(seed_), keys(1024, kEmpty),
        slots(1024, 0), rng(seed_) {}

  static uint64_t hash(int64_t k) {
    uint64_t x = static_cast<uint64_t>(k);
    x ^= x >> 33;
    x *= 0xff51afd7ed558ccdULL;
    x ^= x >> 33;
    x *= 0xc4ceb9fe1a85ec53ULL;
    x ^= x >> 33;
    return x;
  }

  void grow() {
    std::vector<int64_t> old_keys = std::move(keys);
    std::vector<int64_t> old_slots = std::move(slots);
    size_t cap = old_keys.size() * 2;
    keys.assign(cap, kEmpty);
    slots.assign(cap, 0);
    for (size_t i = 0; i < old_keys.size(); ++i) {
      if (old_keys[i] == kEmpty) continue;
      size_t j = hash(old_keys[i]) & (cap - 1);
      while (keys[j] != kEmpty) j = (j + 1) & (cap - 1);
      keys[j] = old_keys[i];
      slots[j] = old_slots[i];
    }
  }

  // slot for key, creating (and initialising) the row if absent
  int64_t ensure(int64_t k) {
    if (size * 4 >= static_cast<int64_t>(keys.size()) * 3) grow();
    size_t cap = keys.size();
    size_t j = hash(k) & (cap - 1);
    while (keys[j] != kEmpty && keys[j] != k) j = (j + 1) & (cap - 1);
    if (keys[j] == k) return slots[j];
    keys[j] = k;
    slots[j] = size;
    data.resize((size + 1) * dim);
    float* row = data.data() + size * dim;
    if (init_std > 0.f) {
      std::normal_distribution<float> nd(0.f, init_std);
      for (int64_t c = 0; c < dim; ++c) row[c] = nd(rng);
    } else {
      std::memset(row, 0, sizeof(float) * dim);
    }
    return size++;
  }

};

}  // namespace

extern "C" {

void* kv_create(int64_t dim, float init_std, uint64_t seed) {
  return new KvStore(dim, init_std, seed);
}

void kv_destroy(void* h) { delete static_cast<KvStore*>(h); }

int64_t kv_size(void* h) { return static_cast<KvStore*>(h)->size; }

// out: [n, dim] row-major float32
void kv_pull(void* h, const int64_t* ks, int64_t n, float* out) {
  auto* s = static_cast<KvStore*>(h);
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = s->ensure(ks[i]);
    std::memcpy(out + i * s->dim, s->data.data() + slot * s->dim,
                sizeof(float) * s->dim);
  }
}

// grads: [n, dim]; applies row -= lr * grad (duplicates accumulate)
void kv_push(void* h, const int64_t* ks, int64_t n, const float* grads,
             float lr) {
  auto* s = static_cast<KvStore*>(h);
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = s->ensure(ks[i]);
    float* row = s->data.data() + slot * s->dim;
    const float* g = grads + i * s->dim;
    for (int64_t c = 0; c < s->dim; ++c) row[c] -= lr * g[c];
  }
}

// export for snapshot: keys_out [size], rows_out [size, dim]
void kv_export(void* h, int64_t* keys_out, float* rows_out) {
  auto* s = static_cast<KvStore*>(h);
  for (size_t j = 0; j < s->keys.size(); ++j) {
    if (s->keys[j] == kEmpty) continue;
    int64_t slot = s->slots[j];
    keys_out[slot] = s->keys[j];
    std::memcpy(rows_out + slot * s->dim, s->data.data() + slot * s->dim,
                sizeof(float) * s->dim);
  }
}

// bulk import (load): n rows with given keys
void kv_import(void* h, const int64_t* ks, int64_t n, const float* rows) {
  auto* s = static_cast<KvStore*>(h);
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = s->ensure(ks[i]);
    std::memcpy(s->data.data() + slot * s->dim, rows + i * s->dim,
                sizeof(float) * s->dim);
  }
}

}  // extern "C"
