"""Native (C++) runtime tier — built on demand, bound via ctypes.

The reference's runtime around the compute path is C++ (executors, PS
runtime, data feed — SURVEY §2.1); this package holds the TPU build's
native equivalents. Compute stays in XLA/Pallas; these are HOST-side hot
paths. Components:

  kv_store.cc — sparse-row KV behind LargeScaleKV (reference
      operators/distributed/large_scale_kv.h): open-addressing id->slot
      hash + contiguous float arena; pull/push never enter the Python
      interpreter per row.

Build: one `g++ -O3 -shared -fPIC` at first use, cached under
native/build/ and invalidated by source mtime. No pybind11 (not in the
image) — plain C ABI + ctypes.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["load_library", "NativeKV", "available"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "build")
_lock = threading.Lock()
_lib = None
_load_failed = False


def load_library():
    """Compile (if stale) and dlopen the native library; None when no
    toolchain is available (callers fall back to pure Python)."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        src = os.path.join(_DIR, "kv_store.cc")
        so = os.path.join(_BUILD, "libpaddle_tpu_native.so")
        try:
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                os.makedirs(_BUILD, exist_ok=True)
                # per-process temp name: concurrent first-use compiles
                # from multiple launcher workers must not interleave
                # writes into one .tmp before the atomic replace
                import tempfile
                fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD)
                os.close(fd)
                try:
                    subprocess.run(
                        ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                         src, "-o", tmp],
                        check=True, capture_output=True, text=True)
                    os.replace(tmp, so)
                finally:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
            lib = ctypes.CDLL(so)
        except (OSError, subprocess.CalledProcessError) as e:
            import logging
            logging.getLogger(__name__).warning(
                "native tier unavailable (%s); using Python fallback", e)
            _load_failed = True
            return None
        lib.kv_create.restype = ctypes.c_void_p
        lib.kv_create.argtypes = [ctypes.c_int64, ctypes.c_float,
                                  ctypes.c_uint64]
        lib.kv_destroy.argtypes = [ctypes.c_void_p]
        lib.kv_size.restype = ctypes.c_int64
        lib.kv_size.argtypes = [ctypes.c_void_p]
        P_i64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        P_f32 = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        lib.kv_pull.argtypes = [ctypes.c_void_p, P_i64, ctypes.c_int64,
                                P_f32]
        lib.kv_push.argtypes = [ctypes.c_void_p, P_i64, ctypes.c_int64,
                                P_f32, ctypes.c_float]
        lib.kv_export.argtypes = [ctypes.c_void_p, P_i64, P_f32]
        lib.kv_import.argtypes = [ctypes.c_void_p, P_i64, ctypes.c_int64,
                                  P_f32]
        _lib = lib
        return _lib


def available() -> bool:
    return load_library() is not None


class NativeKV:
    """ctypes wrapper over kv_store.cc (same contract as the Python
    LargeScaleKV core)."""

    def __init__(self, dim: int, init_std: float = 0.01, seed: int = 0):
        self._lib = load_library()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self.dim = int(dim)
        self._h = self._lib.kv_create(self.dim, float(init_std), int(seed))

    def __del__(self):
        h = getattr(self, "_h", None)
        if h and getattr(self, "_lib", None) is not None:
            self._lib.kv_destroy(h)
            self._h = None

    _SENTINEL = np.int64(np.iinfo(np.int64).min)

    @classmethod
    def _check_keys(cls, ks):
        if len(ks) and ks.min() == cls._SENTINEL:
            raise ValueError(
                "key INT64_MIN is reserved (open-addressing empty "
                "sentinel)")
        return ks

    def pull(self, keys) -> np.ndarray:
        ks = self._check_keys(
            np.ascontiguousarray(np.asarray(keys, np.int64).ravel()))
        out = np.empty((len(ks), self.dim), np.float32)
        self._lib.kv_pull(self._h, ks, len(ks), out)
        return out

    def push(self, keys, grads, lr: float = 1.0):
        ks = self._check_keys(
            np.ascontiguousarray(np.asarray(keys, np.int64).ravel()))
        g = np.ascontiguousarray(
            np.asarray(grads, np.float32).reshape(len(ks), self.dim))
        self._lib.kv_push(self._h, ks, len(ks), g, float(lr))

    def size(self) -> int:
        return int(self._lib.kv_size(self._h))

    def export(self):
        n = self.size()
        keys = np.empty((n,), np.int64)
        rows = np.empty((n, self.dim), np.float32)
        if n:
            self._lib.kv_export(self._h, keys, rows)
        return keys, rows

    def import_(self, keys, rows):
        ks = np.ascontiguousarray(np.asarray(keys, np.int64).ravel())
        r = np.ascontiguousarray(
            np.asarray(rows, np.float32).reshape(len(ks), self.dim))
        self._lib.kv_import(self._h, ks, len(ks), r)
