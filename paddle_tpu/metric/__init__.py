"""paddle.metric (reference python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


class Metric:
    def __init__(self, name=None):
        self._name = name or type(self).__name__.lower()

    def name(self):
        return self._name

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__(name or "acc")
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred = np.asarray(pred.numpy() if hasattr(pred, "numpy") else pred)
        label = np.asarray(label.numpy() if hasattr(label, "numpy")
                           else label)
        idx = np.argsort(-pred, axis=-1)[..., :self.maxk]
        lab = label.reshape(label.shape[0], -1)[:, :1]
        return (idx == lab).astype("float32")

    def update(self, correct, *args):
        correct = np.asarray(correct.numpy() if hasattr(correct, "numpy")
                             else correct)
        accs = []
        for i, k in enumerate(self.topk):
            num = correct[..., :k].sum()
            self.total[i] += num
            self.count[i] += correct.shape[0]
            accs.append(float(num) / correct.shape[0])
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return [f"{self._name}_top{k}" for k in self.topk] \
            if len(self.topk) > 1 else [self._name]


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__(name or "precision")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.asarray(preds.numpy() if hasattr(preds, "numpy")
                           else preds).reshape(-1)
        labels = np.asarray(labels.numpy() if hasattr(labels, "numpy")
                            else labels).reshape(-1)
        pred_pos = preds > 0.5
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fp += int(np.sum(pred_pos & (labels == 0)))

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__(name or "recall")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.asarray(preds.numpy() if hasattr(preds, "numpy")
                           else preds).reshape(-1)
        labels = np.asarray(labels.numpy() if hasattr(labels, "numpy")
                            else labels).reshape(-1)
        pred_pos = preds > 0.5
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fn += int(np.sum(~pred_pos & (labels == 1)))

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__(name or "auc")
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, dtype=np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, dtype=np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds.numpy() if hasattr(preds, "numpy")
                           else preds)
        labels = np.asarray(labels.numpy() if hasattr(labels, "numpy")
                            else labels).reshape(-1)
        p1 = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 \
            else preds.reshape(-1)
        bins = np.clip((p1 * self.num_thresholds).astype(int), 0,
                       self.num_thresholds)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tot_pos, tot_neg = tp[-1], fp[-1]
        if not tot_pos or not tot_neg:
            return 0.0
        tp_prev = np.concatenate([[0], tp[:-1]])
        fp_prev = np.concatenate([[0], fp[:-1]])
        area = np.sum((fp - fp_prev) * (tp + tp_prev) / 2.0)
        return float(area / (tot_pos * tot_neg))


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    from ..common_ops import run_op_multi
    topv = run_op_multi("top_k_v2", {"X": input}, {"k": int(k), "axis": -1},
                        {"Out": 1, "Indices": "int64"})
    res = run_op_multi("accuracy",
                       {"Out": topv["Out"][0], "Indices": topv["Indices"][0],
                        "Label": label},
                       {}, {"Accuracy": 1, "Correct": "int32",
                            "Total": "int32"})
    return res["Accuracy"][0]
