"""paddle.text (reference python/paddle/text/): NLP datasets.
Zero-egress: synthetic corpora with realistic shapes."""
from __future__ import annotations

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "WMT14", "UCIHousing", "Imikolov",
           "Movielens", "Conll05st", "ViterbiDecoder",
           "viterbi_decode"]


class Imdb(Dataset):
    def __init__(self, data_file=None, mode="train", cutoff=150):
        rng = np.random.RandomState(7 if mode == "train" else 8)
        self.n = 256 if mode == "train" else 64
        self.seq_len = 128
        self.vocab = 5000
        self.docs = rng.randint(1, self.vocab, (self.n, self.seq_len)) \
            .astype("int64")
        self.labels = rng.randint(0, 2, self.n).astype("int64")
        # plant sentiment signal: ~25% of tokens come from a class-specific
        # range ([1,100) positive / [100,200) negative) so models can
        # actually learn, not only memorise
        signal = rng.random_sample((self.n, self.seq_len)) < 0.25
        tok = rng.randint(1, 100, (self.n, self.seq_len))
        tok = tok + 100 * (1 - self.labels)[:, None]
        self.docs = np.where(signal, tok, self.docs).astype("int64")

    def __getitem__(self, idx):
        return self.docs[idx], np.array([self.labels[idx]], dtype="int64")

    def __len__(self):
        return self.n

    def word_idx(self):
        return {f"w{i}": i for i in range(self.vocab)}


class WMT14(Dataset):
    def __init__(self, data_file=None, mode="train", dict_size=30000):
        rng = np.random.RandomState(11)
        self.n = 128
        self.src = rng.randint(1, dict_size, (self.n, 32)).astype("int64")
        self.tgt = rng.randint(1, dict_size, (self.n, 32)).astype("int64")

    def __getitem__(self, idx):
        return self.src[idx], self.tgt[idx], self.tgt[idx]

    def __len__(self):
        return self.n


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train"):
        rng = np.random.RandomState(3 if mode == "train" else 4)
        self.n = 404 if mode == "train" else 102
        self.x = rng.randn(self.n, 13).astype("float32")
        w = rng.randn(13, 1).astype("float32")
        self.y = (self.x @ w + 0.1 * rng.randn(self.n, 1)).astype("float32")

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return self.n


class Imikolov(Dataset):
    """PTB-style n-gram LM dataset (reference text/datasets/imikolov.py).
    Synthetic Zipf-distributed token stream with Markov structure so an
    n-gram model has signal to learn."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50):
        rng = np.random.RandomState(11 if mode == "train" else 12)
        self.window = window_size
        self.vocab = 2000
        n_tokens = 20000 if mode == "train" else 4000
        # first-order Markov chain over a Zipf marginal
        zipf = 1.0 / np.arange(1, self.vocab + 1)
        zipf /= zipf.sum()
        toks = [int(rng.choice(self.vocab, p=zipf))]
        for _ in range(n_tokens - 1):
            if rng.rand() < 0.3:     # sticky transitions: bigram signal
                toks.append((toks[-1] * 7 + 3) % self.vocab)
            else:
                toks.append(int(rng.choice(self.vocab, p=zipf)))
        self.stream = np.asarray(toks, np.int64)
        self.n = len(self.stream) - window_size

    def __getitem__(self, idx):
        w = self.stream[idx:idx + self.window]
        return w[:-1].copy(), w[-1:].copy()

    def __len__(self):
        return self.n


class Movielens(Dataset):
    """User/movie rating tuples (reference text/datasets/movielens.py):
    (user_id, gender, age, job, movie_id, category, title, rating).
    Synthetic with a planted low-rank preference structure."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0):
        rng = np.random.RandomState(rand_seed + (0 if mode == "train"
                                                 else 1))
        self.n_users, self.n_movies = 500, 800
        n = 4000 if mode == "train" else 400
        self.users = rng.randint(0, self.n_users, n).astype("int64")
        self.movies = rng.randint(0, self.n_movies, n).astype("int64")
        # low-rank taste model -> learnable ratings in [1, 5]
        uf = rng.randn(self.n_users, 4)
        mf = rng.randn(self.n_movies, 4)
        raw = (uf[self.users] * mf[self.movies]).sum(1)
        self.ratings = np.clip(np.round(3.0 + raw), 1, 5).astype("float32")
        self.genders = (self.users % 2).astype("int64")
        self.ages = (self.users % 7).astype("int64")
        self.jobs = (self.users % 21).astype("int64")
        self.cats = (self.movies % 18).astype("int64")

    def __getitem__(self, idx):
        return (self.users[idx], self.genders[idx], self.ages[idx],
                self.jobs[idx], self.movies[idx], self.cats[idx],
                np.array([self.ratings[idx]], "float32"))

    def __len__(self):
        return len(self.users)


class Conll05st(Dataset):
    """SRL-style tagged sequences (reference text/datasets/conll05.py):
    (words, predicate-context windows, label sequence). Synthetic BIO
    tags correlated with token ranges."""

    def __init__(self, data_file=None, mode="train"):
        rng = np.random.RandomState(21 if mode == "train" else 22)
        self.n = 128 if mode == "train" else 32
        self.seq_len = 40
        self.word_vocab = 4000
        self.n_labels = 9
        self.words = rng.randint(1, self.word_vocab,
                                 (self.n, self.seq_len)).astype("int64")
        # labels depend on token bucket => learnable
        self.labels = (self.words % self.n_labels).astype("int64")
        self.predicates = rng.randint(0, self.seq_len,
                                      self.n).astype("int64")

    def __getitem__(self, idx):
        return (self.words[idx], self.predicates[idx:idx + 1].copy(),
                self.labels[idx])

    def __len__(self):
        return self.n


class ViterbiDecoder:
    """paddle.text.ViterbiDecoder (reference text/viterbi_decode.py):
    argmax path through emissions [B, T, N] + transitions [N, N] with a
    length mask — runs the crf_decoding kernel (padded/Length form,
    paddle transition layout adds start/stop rows internally as zeros)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        import jax.numpy as jnp
        from ..fluid.registry import require
        trans = self.transitions
        tv = trans._value if hasattr(trans, "_value") else jnp.asarray(trans)
        pv = potentials._value if hasattr(potentials, "_value") \
            else jnp.asarray(potentials)
        lv = lengths._value if hasattr(lengths, "_value") \
            else jnp.asarray(lengths)
        n = tv.shape[-1]
        full = jnp.concatenate([jnp.zeros((2, n), tv.dtype), tv], axis=0)
        outs = require("crf_decoding").compute(
            None, {"Emission": [pv], "Transition": [full],
                   "Length": [lv]}, {})
        path = outs["ViterbiPath"][0]
        # scores of the decoded paths
        t_idx = jnp.arange(pv.shape[1])
        em = jnp.take_along_axis(pv, path[:, :, None], axis=2)[:, :, 0]
        mask = (t_idx[None, :] < lv.reshape(-1, 1)).astype(pv.dtype)
        scores = jnp.sum(em * mask, axis=1)
        pair = tv[path[:, :-1], path[:, 1:]]
        scores = scores + jnp.sum(pair * mask[:, 1:], axis=1)
        from ..fluid.dygraph.varbase import Tensor
        return Tensor(scores, stop_gradient=True), \
            Tensor(path, stop_gradient=True)


def viterbi_decode(potentials, transitions, lengths,
                   include_bos_eos_tag=True, name=None):
    return ViterbiDecoder(transitions, include_bos_eos_tag)(
        potentials, lengths)
