"""paddle.text (reference python/paddle/text/): NLP datasets.
Zero-egress: synthetic corpora with realistic shapes."""
from __future__ import annotations

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "WMT14", "UCIHousing"]


class Imdb(Dataset):
    def __init__(self, data_file=None, mode="train", cutoff=150):
        rng = np.random.RandomState(7 if mode == "train" else 8)
        self.n = 256 if mode == "train" else 64
        self.seq_len = 128
        self.vocab = 5000
        self.docs = rng.randint(1, self.vocab, (self.n, self.seq_len)) \
            .astype("int64")
        self.labels = rng.randint(0, 2, self.n).astype("int64")
        # plant sentiment signal: ~25% of tokens come from a class-specific
        # range ([1,100) positive / [100,200) negative) so models can
        # actually learn, not only memorise
        signal = rng.random_sample((self.n, self.seq_len)) < 0.25
        tok = rng.randint(1, 100, (self.n, self.seq_len))
        tok = tok + 100 * (1 - self.labels)[:, None]
        self.docs = np.where(signal, tok, self.docs).astype("int64")

    def __getitem__(self, idx):
        return self.docs[idx], np.array([self.labels[idx]], dtype="int64")

    def __len__(self):
        return self.n

    def word_idx(self):
        return {f"w{i}": i for i in range(self.vocab)}


class WMT14(Dataset):
    def __init__(self, data_file=None, mode="train", dict_size=30000):
        rng = np.random.RandomState(11)
        self.n = 128
        self.src = rng.randint(1, dict_size, (self.n, 32)).astype("int64")
        self.tgt = rng.randint(1, dict_size, (self.n, 32)).astype("int64")

    def __getitem__(self, idx):
        return self.src[idx], self.tgt[idx], self.tgt[idx]

    def __len__(self):
        return self.n


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train"):
        rng = np.random.RandomState(3 if mode == "train" else 4)
        self.n = 404 if mode == "train" else 102
        self.x = rng.randn(self.n, 13).astype("float32")
        w = rng.randn(13, 1).astype("float32")
        self.y = (self.x @ w + 0.1 * rng.randn(self.n, 1)).astype("float32")

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return self.n
