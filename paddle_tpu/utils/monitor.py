"""Global named stat gauges (reference platform/monitor.h/.cc
STAT_ADD/STAT_RESET + pybind graph_num/... exposure)."""
from __future__ import annotations

import threading

__all__ = ["StatRegistry", "stat_add", "stat_set", "stat_get",
           "stat_reset", "get_all_stats"]

_lock = threading.Lock()
_stats: dict[str, float] = {}


class StatRegistry:
    @staticmethod
    def add(name: str, value=1):
        return stat_add(name, value)

    @staticmethod
    def set(name: str, value):
        return stat_set(name, value)

    @staticmethod
    def get(name: str):
        return stat_get(name)


def stat_add(name: str, value=1):
    with _lock:
        _stats[name] = _stats.get(name, 0) + value
        return _stats[name]


def stat_set(name: str, value):
    with _lock:
        _stats[name] = value
        return value


def stat_get(name: str):
    with _lock:
        return _stats.get(name, 0)


def stat_reset(name: str | None = None):
    with _lock:
        if name is None:
            _stats.clear()
        else:
            _stats.pop(name, None)


def get_all_stats() -> dict[str, float]:
    with _lock:
        return dict(_stats)
