"""Profiler (reference platform/profiler.h + python/paddle/fluid/profiler.py).

TPU-native: jax.profiler (XPlane) traces device + host; op-phase markers come
from the executor's jax.named_scope per op (replacing RecordEvent RAII at
framework/operator.cc:984). View with TensorBoard or Perfetto.

Version tolerance: older jax builds ship a ``jax.profiler`` missing
``start_trace``/``stop_trace``/``TraceAnnotation`` (or no ``profiler``
attr at all). Every wrapper here degrades to a graceful no-op in that
case — the per-op host report still works, only the XPlane trace is
skipped. ``RecordEvent`` now also records a host span into
``paddle_tpu.observability.tracing`` (same bounded ring the serving/PS
tiers write), so marker events land in the Chrome trace export next to
the engine/rpc spans.
"""
from __future__ import annotations

import contextlib
import os
import time

import jax

from ..observability import tracing as _tracing

__all__ = ["Profiler", "profiler", "start_profiler", "stop_profiler",
           "RecordEvent", "op_profile_report"]

_trace_dir = None
_trace_started = False


def _prof_attr(name: str):
    """jax.profiler.<name>, or None when jax/profiler lacks it (older
    jax) — callers no-op instead of raising AttributeError."""
    return getattr(getattr(jax, "profiler", None), name, None)


# ---------------------------------------------------------------------------
# per-op aggregation (reference profiler.cc sorted event report: the
# C++ profiler times every op's Run; here the eager tracer is hooked and
# each kernel is synchronously timed — trace-accurate for dygraph, while
# jitted static steps are one fused computation by design and show up in
# the XPlane trace instead)
# ---------------------------------------------------------------------------

_op_stats: dict[str, list] = {}  # op -> [calls, total_s, max_s]
_hooked = False


def _hook_tracer():
    global _hooked
    if _hooked:
        return
    from ..fluid.dygraph import tracer as trmod
    orig = trmod.Tracer.trace_op

    def timed(self, op_type, *a, **kw):
        if _trace_dir is None:  # profiler off -> zero overhead path
            return orig(self, op_type, *a, **kw)
        t0 = time.perf_counter()
        res = orig(self, op_type, *a, **kw)
        jax.block_until_ready([t._value for lst in res.values()
                               for t in lst if t is not None])
        dt = time.perf_counter() - t0
        st = _op_stats.setdefault(op_type, [0, 0.0, 0.0])
        st[0] += 1
        st[1] += dt
        st[2] = max(st[2], dt)
        return res

    trmod.Tracer.trace_op = timed
    _hooked = True


def op_profile_report(sorted_key="total") -> str:
    """Aggregated per-op table (reference profiler.cc PrintProfiler)."""
    key = {"total": 1, "calls": 0, "max": 2,
           "ave": None}.get(sorted_key, 1)
    rows = sorted(
        _op_stats.items(),
        key=(lambda kv: kv[1][1] / max(kv[1][0], 1)) if key is None
        else (lambda kv: kv[1][key]), reverse=True)
    total = sum(v[1] for v in _op_stats.values()) or 1.0
    lines = [f"{'Op':<28}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>10}"
             f"{'Max(ms)':>10}{'Ratio':>8}"]
    for op, (calls, tot, mx) in rows:
        lines.append(
            f"{op:<28}{calls:>8}{tot * 1e3:>12.3f}"
            f"{tot / calls * 1e3:>10.3f}{mx * 1e3:>10.3f}"
            f"{tot / total:>8.1%}")
    return "\n".join(lines)


def start_profiler(state="All", tracer_option="Default",
                   trace_dir="/tmp/paddle_tpu_trace"):
    global _trace_dir, _trace_started
    _op_stats.clear()
    _hook_tracer()
    _trace_dir = trace_dir
    os.makedirs(trace_dir, exist_ok=True)
    start = _prof_attr("start_trace")
    if start is not None:  # older jax: host-side report only
        start(trace_dir)
        _trace_started = True


def stop_profiler(sorted_key=None, profile_path=None):
    global _trace_dir, _trace_started
    stop = _prof_attr("stop_trace")
    if stop is not None and _trace_started:
        stop()
    _trace_started = False
    out = _trace_dir
    _trace_dir = None
    if _op_stats:
        report = op_profile_report(sorted_key or "total")
        if profile_path:
            with open(profile_path, "w") as f:
                f.write(report + "\n")
        else:
            print(report, flush=True)
    return out


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None,
             tracer_option="Default"):
    """profile_path is where the REPORT file goes (reference
    fluid/profiler.py contract); the XPlane trace always lands in a trace
    directory."""
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class RecordEvent:
    """Host event marker (reference platform/profiler.h:126).

    Backed by observability.tracing: records a host span (Chrome trace
    export) AND enters jax.profiler.TraceAnnotation when this jax has
    it, so the marker shows up in the XPlane device trace too. On older
    jax without TraceAnnotation the span alone is recorded — no-op
    degradation instead of AttributeError."""

    def __init__(self, name: str):
        self.name = name
        self._cm = None

    def __enter__(self):
        self._cm = _tracing.span(self.name)
        self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        if self._cm is None:
            return False
        cm, self._cm = self._cm, None
        return cm.__exit__(*(exc or (None, None, None)))

    begin = __enter__

    def end(self):
        self.__exit__(None, None, None)


class Profiler:
    """2.0-style paddle.profiler.Profiler."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 trace_dir="/tmp/paddle_tpu_trace"):
        self.trace_dir = trace_dir
        self._running = False

    def start(self):
        start_profiler(trace_dir=self.trace_dir)
        self._running = True

    def stop(self):
        if self._running:
            stop_profiler()
            self._running = False

    def step(self):
        pass

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def summary(self, **kw):
        return f"trace written to {self.trace_dir} (view with TensorBoard)"
