"""Profiler (reference platform/profiler.h + python/paddle/fluid/profiler.py).

TPU-native: jax.profiler (XPlane) traces device + host; op-phase markers come
from the executor's jax.named_scope per op (replacing RecordEvent RAII at
framework/operator.cc:984). View with TensorBoard or Perfetto.
"""
from __future__ import annotations

import contextlib
import os
import time

import jax

__all__ = ["Profiler", "profiler", "start_profiler", "stop_profiler",
           "RecordEvent"]

_trace_dir = None


def start_profiler(state="All", tracer_option="Default",
                   trace_dir="/tmp/paddle_tpu_trace"):
    global _trace_dir
    _trace_dir = trace_dir
    os.makedirs(trace_dir, exist_ok=True)
    jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key=None, profile_path=None):
    jax.profiler.stop_trace()
    return _trace_dir


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None,
             tracer_option="Default"):
    start_profiler(state, tracer_option,
                   profile_path or "/tmp/paddle_tpu_trace")
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class RecordEvent:
    """Host event marker (reference platform/profiler.h:126)."""

    def __init__(self, name: str):
        self.name = name
        self._cm = None

    def __enter__(self):
        self._cm = jax.profiler.TraceAnnotation(self.name)
        self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)

    begin = __enter__

    def end(self):
        self.__exit__(None, None, None)


class Profiler:
    """2.0-style paddle.profiler.Profiler."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 trace_dir="/tmp/paddle_tpu_trace"):
        self.trace_dir = trace_dir
        self._running = False

    def start(self):
        start_profiler(trace_dir=self.trace_dir)
        self._running = True

    def stop(self):
        if self._running:
            stop_profiler()
            self._running = False

    def step(self):
        pass

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def summary(self, **kw):
        return f"trace written to {self.trace_dir} (view with TensorBoard)"
