"""paddle.utils (reference python/paddle/utils/)."""
from . import profiler
from .profiler import Profiler

__all__ = ["profiler", "Profiler", "try_import", "unique_name"]

from ..fluid import unique_name


def try_import(module_name, err_msg=None):
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"cannot import {module_name}")


def run_check():
    """paddle.utils.run_check — verify the install can run a training step."""
    import numpy as np
    import paddle_tpu as paddle
    x = paddle.to_tensor(np.ones((2, 2), dtype="float32"))
    y = paddle.matmul(x, x)
    assert np.allclose(y.numpy(), 2 * np.ones((2, 2)))
    print("paddle_tpu is installed successfully!")
