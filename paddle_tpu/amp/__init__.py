"""paddle.amp — automatic mixed precision.

TPU-native AMP = bfloat16 (no loss scaling needed for bf16; fp16 path keeps
the dynamic loss-scale state machine for parity — reference
contrib/mixed_precision/decorator.py:27 + dygraph/amp/*).
"""
from .auto_cast import auto_cast, amp_guard, white_list, black_list
from .grad_scaler import GradScaler, AmpScaler

__all__ = ["auto_cast", "amp_guard", "GradScaler", "AmpScaler",
           "white_list", "black_list"]
