"""Trace-time autocast (reference imperative/amp_auto_cast.cc +
dygraph/amp/auto_cast.py:90 amp_guard).

In eager mode the tracer consults these lists per op and casts float inputs:
white-list ops run in bf16 (MXU-friendly), black-list ops stay fp32.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..fluid import framework

# ops that benefit from bf16 on the MXU (reference fp16_lists.py white list)
white_list = {
    "matmul", "matmul_v2", "mul", "bmm", "conv2d", "depthwise_conv2d",
    "fc", "addmm", "fused_attention",
}
# numerically sensitive ops kept in fp32 (reference black list).
# batch_norm is deliberately NOT here: the kernel computes statistics in
# f32 internally whatever the IO dtype (cuDNN-BN-style mixed precision, the
# path the reference uses under AMP), and forcing f32 IO materialised
# activation-sized f32 buffers around every BN — 2-3x the HBM traffic of
# a ResNet step.
black_list = {
    "softmax", "log_softmax", "cross_entropy", "softmax_with_cross_entropy",
    "layer_norm", "group_norm", "instance_norm", "mean",
    "reduce_mean", "reduce_sum", "sum", "exp", "log", "square", "sqrt",
    "rsqrt", "p_norm", "squared_l2_norm",
}

_AMP_DTYPE = {"O1": jnp.bfloat16, "O2": jnp.bfloat16}


# per-op slots that must stay f32 even when the op itself runs bf16:
# batch_norm's running stats and affine params are f32 state (bf16 IO
# applies to X only — re-rounding Mean/Variance through bf16 every step
# would decay the running statistics)
_KEEP_F32_SLOTS = {
    "batch_norm": {"Mean", "Variance", "Scale", "Bias"},
    "sync_batch_norm": {"Mean", "Variance", "Scale", "Bias"},
}


def _autocast_inputs(op_type, in_tensors, level):
    from ..fluid.dygraph.varbase import Tensor
    if level == 0:
        return in_tensors
    target = None
    if op_type in white_list:
        target = jnp.bfloat16
    elif op_type in black_list:
        target = jnp.float32
    elif level == 2:  # O2: everything except black list in bf16
        target = jnp.bfloat16
    if target is None:
        return in_tensors
    keep_f32 = _KEEP_F32_SLOTS.get(op_type, ())
    out = {}
    for slot, lst in in_tensors.items():
        if target == jnp.bfloat16 and slot in keep_f32:
            out[slot] = lst
            continue
        res = []
        for t in lst:
            if t is not None and hasattr(t, "_value") and \
                    jnp.issubdtype(t._value.dtype, jnp.floating) and \
                    t._value.dtype != target:
                nt = Tensor(t._value.astype(target),
                            stop_gradient=t.stop_gradient)
                nt._producer = t._producer
                # keep autograd linkage: casting for compute only
                res.append(_CastView(t, nt))
            else:
                res.append(t)
        out[slot] = res
    return out


class _CastView:
    """Tensor proxy that computes in the cast dtype but routes gradients to
    the original tensor (grad flows through the cast transparently because
    the tape stores the ORIGINAL tensor object)."""

    def __init__(self, orig, cast):
        self._orig = orig
        self._cast = cast

    @property
    def _value(self):
        return self._cast._value

    @property
    def stop_gradient(self):
        return self._orig.stop_gradient

    def __getattr__(self, k):
        return getattr(self._orig, k)


@contextlib.contextmanager
def amp_guard(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1"):
    tr = framework._dygraph_tracer()
    if tr is None:
        yield
        return
    added_w = set(custom_white_list or []) - white_list
    added_b = set(custom_black_list or []) - black_list
    white_list.update(added_w)
    black_list.update(added_b)
    prev = tr._amp_level
    tr._amp_level = (1 if level == "O1" else 2) if enable else 0
    try:
        yield
    finally:
        tr._amp_level = prev
        white_list.difference_update(added_w)
        black_list.difference_update(added_b)


auto_cast = amp_guard
