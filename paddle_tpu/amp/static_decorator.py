"""Static-graph AMP decorator (reference contrib/mixed_precision/decorator.py:27
OptimizerWithMixedPrecision).

TPU-native design: instead of inserting cast ops per black/white list into the
Program (the reference's rewrite_program), the executor traces the forward in
a bf16 compute policy — matmuls/convs run bf16 on the MXU, reductions stay
fp32 — by setting per-op dtype hints; dynamic loss scaling uses the
check_finite_and_unscale / update_loss_scaling ops (operators/amp/).
Round-1 scope: bf16 policy flag on the program + loss-scaling ops wired for
fp16 parity.
"""
from __future__ import annotations

from ..fluid import layers
from ..fluid.layer_helper import LayerHelper

__all__ = ["decorate_static", "OptimizerWithMixedPrecision"]


class OptimizerWithMixedPrecision:
    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=2.0**15,
                 use_dynamic_loss_scaling=True, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, incr_ratio=2.0, decr_ratio=0.5,
                 use_pure_bf16=True):
        self._optimizer = optimizer
        self._init_loss_scaling = init_loss_scaling
        self._use_dynamic = use_dynamic_loss_scaling
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._use_pure_bf16 = use_pure_bf16
        self._scale_var = None
        self._good_var = None
        self._bad_var = None

    def __getattr__(self, k):
        return getattr(self._optimizer, k)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        program = loss.block.program
        program._amp_policy = "bf16" if self._use_pure_bf16 else "fp16"
        if not self._use_pure_bf16 and self._use_dynamic:
            # Scale the LOSS before backward (reference decorator.py:218
            # OptimizerWithMixedPrecision.backward scales then appends
            # backward); check_finite_and_unscale later divides the grads by
            # the same scale var, restoring true magnitudes.
            scale = self._ensure_scaling_vars()
            scaled_loss = layers.elementwise_mul(loss, scale)
            params_grads = self._optimizer.backward(
                scaled_loss, startup_program, parameter_list, no_grad_set)
            params_grads = self._scale_and_check(params_grads)
        else:
            params_grads = self._optimizer.backward(
                loss, startup_program, parameter_list, no_grad_set)
        ops = self._optimizer.apply_gradients(params_grads)
        return ops, params_grads

    def _ensure_scaling_vars(self):
        from ..fluid.framework import default_main_program
        # re-create when minimize() is called under a DIFFERENT main program:
        # cached Variables belong to their program; a fresh program has no
        # such vars and its startup program never initialises them
        if (self._scale_var is not None and
                self._scale_var.block.program is default_main_program()):
            return self._scale_var
        helper = LayerHelper("amp_scaling")
        self._scale_var = helper.create_global_variable(
            shape=[1], dtype="float32", persistable=True,
            value=self._init_loss_scaling)
        self._good_var = helper.create_global_variable(
            shape=[1], dtype="int32", persistable=True, value=0.0)
        self._bad_var = helper.create_global_variable(
            shape=[1], dtype="int32", persistable=True, value=0.0)
        return self._scale_var

    def _scale_and_check(self, params_grads):
        helper = LayerHelper("amp_scaling")
        scale, good, bad = self._scale_var, self._good_var, self._bad_var
        grads = [g for _, g in params_grads]
        found = helper.create_variable_for_type_inference("bool", True)
        unscaled = [helper.create_variable_for_type_inference(g.dtype)
                    for g in grads]
        helper.append_op(
            type="check_finite_and_unscale",
            inputs={"X": grads, "Scale": [scale]},
            outputs={"Out": unscaled, "FoundInfinite": [found]})
        outs = [helper.create_variable_for_type_inference(g.dtype)
                for g in grads]
        helper.append_op(
            type="update_loss_scaling",
            inputs={"X": unscaled, "FoundInfinite": [found],
                    "PrevLossScaling": [scale], "InGoodSteps": [good],
                    "InBadSteps": [bad]},
            outputs={"Out": outs, "LossScaling": [scale.name],
                     "OutGoodSteps": [good.name], "OutBadSteps": [bad.name]},
            attrs={"incr_every_n_steps": self._incr_every,
                   "decr_every_n_nan_or_inf": self._decr_every,
                   "incr_ratio": self._incr_ratio,
                   "decr_ratio": self._decr_ratio})
        return [(p, o) for (p, _), o in zip(params_grads, outs)]


def decorate_static(optimizer, amp_configs: dict):
    return OptimizerWithMixedPrecision(
        optimizer,
        init_loss_scaling=amp_configs.get("init_loss_scaling", 2.0**15),
        use_dynamic_loss_scaling=amp_configs.get(
            "use_dynamic_loss_scaling", True),
        incr_every_n_steps=amp_configs.get("incr_every_n_steps", 1000),
        decr_every_n_nan_or_inf=amp_configs.get("decr_every_n_nan_or_inf", 2),
        incr_ratio=amp_configs.get("incr_ratio", 2.0),
        decr_ratio=amp_configs.get("decr_ratio", 0.5),
        use_pure_bf16=amp_configs.get("use_pure_bf16", True))


decorate = decorate_static
