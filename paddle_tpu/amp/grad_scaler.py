"""Dynamic loss scaling (reference dygraph/amp/loss_scaler.py:27 AmpScaler).

bf16 training doesn't need scaling (exponent range matches fp32), so with the
default bf16 policy this is a near-no-op that still tracks found_inf for
parity; fp16 users get the full state machine.
"""
from __future__ import annotations

import numpy as np

__all__ = ["GradScaler", "AmpScaler"]


class AmpScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0**15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good = 0
        self._bad = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def minimize(self, optimizer, scaled_loss, *args, **kwargs):
        self._unscale(optimizer)
        if not self._found_inf:
            optimizer.step() if hasattr(optimizer, "step") else \
                optimizer.minimize(scaled_loss)
        self._update()

    def step(self, optimizer):
        self._unscale(optimizer)
        if not self._found_inf:
            optimizer.step()

    def update(self):
        self._update()

    def unscale_(self, optimizer):
        self._unscale(optimizer)

    def _unscale(self, optimizer):
        if not self._enable:
            self._found_inf = False
            return
        import jax.numpy as jnp
        params = getattr(optimizer, "_parameters", None) or []
        found = False
        for p in params:
            if p.grad is None:
                continue
            g = p.grad._value / self._scale
            found = found or not bool(jnp.all(jnp.isfinite(g)))
            p.grad._set_value(g)
        self._found_inf = found

    def _update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad += 1
            self._good = 0
            if self._bad >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad = 0
        else:
            self._good += 1
            self._bad = 0
            if self._good >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good = 0

    def is_enable(self):
        return self._enable

    def get_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "good": self._good, "bad": self._bad}

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good = sd.get("good", 0)
        self._bad = sd.get("bad", 0)


class GradScaler(AmpScaler):
    """2.0 name (paddle.amp.GradScaler)."""
