"""Post-training quantization (reference contrib/slim/quantization/
post_training_quantization.py:120 + quantization_pass.py fake-quant
rewriting).

TPU stance: XLA has no public int8 matmul path, so the value here is
(a) INT8 WEIGHT STORAGE — deployed params shrink ~4x, dequantized on
load — and (b) SIMULATED quantization (fake-quant ops on activations and
weights) so accuracy under int8 rounding is measurable before committing
to an int8 serving stack. Both reuse the Program IR: the pass rewrites
blocks in place, exactly like the reference's IrGraph passes.
"""
from __future__ import annotations

import os

import numpy as np

__all__ = ["PostTrainingQuantization", "quant_dequant",
           "QUANTIZABLE_OP_TYPES"]

QUANTIZABLE_OP_TYPES = ("mul", "matmul", "matmul_v2", "conv2d",
                        "depthwise_conv2d")

# weight input slot per quantizable op
_W_SLOT = {"mul": "Y", "matmul": "Y", "matmul_v2": "Y",
           "conv2d": "Filter", "depthwise_conv2d": "Filter"}
_X_SLOT = {"mul": "X", "matmul": "X", "matmul_v2": "X",
           "conv2d": "Input", "depthwise_conv2d": "Input"}


def quant_dequant(x: np.ndarray, scale, bits: int = 8):
    """Simulate int-N rounding: q = clip(round(x/s*qmax)), back to float."""
    qmax = 2 ** (bits - 1) - 1
    s = np.maximum(np.asarray(scale, np.float32), 1e-8)
    q = np.clip(np.round(x / s * qmax), -qmax, qmax)
    return (q * s / qmax).astype(np.float32)


def _channel_scales(w: np.ndarray, channel_axis: int) -> np.ndarray:
    red = tuple(i for i in range(w.ndim) if i != channel_axis)
    return np.abs(w).max(axis=red) if w.ndim > 1 else \
        np.abs(w).max(keepdims=True)


class PostTrainingQuantization:
    """Calibrate activation scales on sample batches, then rewrite the
    program with fake-quant sim and export int8 weights.

    Usage (reference surface):
        ptq = PostTrainingQuantization(
            executor, model_dir, sample_generator=batches,
            algo="abs_max", quantizable_op_type=[...])
        program = ptq.quantize()
        ptq.save_quantized_model(out_dir)
    """

    def __init__(self, executor, model_dir, model_filename=None,
                 params_filename=None, sample_generator=None,
                 batch_nums=10, algo="abs_max",
                 activation_quantize_type="abs_max",
                 weight_quantize_type="channel_wise_abs_max",
                 quantizable_op_type=QUANTIZABLE_OP_TYPES,
                 weight_bits=8, activation_bits=8, is_full_quantize=False,
                 scope=None):
        from ..fluid.scope import Scope
        self._exe = executor
        self._model_dir = model_dir
        self._model_filename = model_filename
        self._params_filename = params_filename
        self._samples = sample_generator
        self._batch_nums = batch_nums
        if algo not in ("abs_max", "avg"):
            raise ValueError(f"unsupported algo {algo!r}")
        self._algo = algo
        self._w_type = weight_quantize_type
        self._w_bits = weight_bits
        self._a_bits = activation_bits
        self._op_types = tuple(quantizable_op_type)
        self._scope = scope or Scope()
        self._act_scales: dict[str, float] = {}
        self._weight_int8: dict[str, tuple] = {}
        self._program = None
        self._feed_names = None
        self._fetch_vars = None

    # ------------------------------------------------------------------
    def quantize(self):
        from ..fluid import io
        from ..fluid.scope import scope_guard
        with scope_guard(self._scope):
            self._program, self._feed_names, self._fetch_vars = \
                io.load_inference_model(
                    self._model_dir, self._exe,
                    model_filename=self._model_filename,
                    params_filename=self._params_filename)
            self._collect_activation_scales()
        self._quantize_weights()
        self._rewrite_program()
        return self._program

    # -- calibration ----------------------------------------------------
    def _targets(self):
        gb = self._program.global_block()
        for op in gb.ops:
            if op.type in self._op_types:
                yield op

    def _collect_activation_scales(self):
        acts = []
        seen = set()
        for op in self._targets():
            n = op.input(_X_SLOT[op.type])[0]
            if n not in seen:
                seen.add(n)
                acts.append(n)
        if self._samples is None:
            raise ValueError("PostTrainingQuantization needs "
                             "sample_generator batches for calibration")
        sums: dict[str, list] = {n: [] for n in acts}
        for i, feed in enumerate(self._samples):
            if i >= self._batch_nums:
                break
            if not isinstance(feed, dict):
                feed = dict(zip(self._feed_names, feed))
            vals = self._exe.run(self._program, feed=feed,
                                 fetch_list=acts)
            for n, v in zip(acts, vals):
                sums[n].append(float(np.abs(np.asarray(v)).max()))
        for n, hist in sums.items():
            if not hist:
                raise ValueError("sample_generator yielded no batches")
            self._act_scales[n] = (max(hist) if self._algo == "abs_max"
                                   else float(np.mean(hist)))

    # -- weights --------------------------------------------------------
    def _quantize_weights(self):
        qmax = 2 ** (self._w_bits - 1) - 1
        for op in self._targets():
            wname = op.input(_W_SLOT[op.type])[0]
            if wname in self._weight_int8:
                continue
            wv = self._scope.find_var(wname)
            if wv is None:
                # the "weight" slot holds an activation (e.g. attention
                # scores via matmul(h, h)) — only persistable vars get
                # weight quantization (reference quantization_pass.py
                # filters on var.persistable)
                continue
            w = np.asarray(wv, np.float32)
            # conv filters quantize per output channel (axis 0); matmul
            # weights per output column (last axis)
            axis = 0 if op.type.endswith("conv2d") else w.ndim - 1
            if self._w_type == "abs_max":
                scales = np.asarray([np.abs(w).max()], np.float32)
                bshape = [1] * w.ndim
            else:  # channel_wise_abs_max
                scales = _channel_scales(w, axis)
                bshape = [1] * w.ndim
                bshape[axis] = -1
            s = np.maximum(scales.astype(np.float32), 1e-8)
            q = np.clip(np.round(w / s.reshape(bshape) * qmax),
                        -qmax, qmax).astype(np.int8)
            self._weight_int8[wname] = (q, s, axis)
            # scope gets the dequantized (simulated) weight so inference
            # reflects int8 rounding
            self._scope.set(wname, (q.astype(np.float32)
                                    * s.reshape(bshape) / qmax))

    # -- program rewrite ------------------------------------------------
    def _rewrite_program(self):
        """Insert fake_quantize_dequantize on each quantized op's
        activation input (reference quantization_pass.py insert of
        fake_quantize_dequantize_moving_average_abs_max)."""
        from ..fluid.framework import Operator
        gb = self._program.global_block()
        new_ops = []
        replaced: dict[str, str] = {}
        for op in gb.ops:
            if op.type in self._op_types:
                slot = _X_SLOT[op.type]
                xn = op.input(slot)[0]
                if xn not in replaced:
                    qn = f"{xn}.quantized"
                    gb.create_var(name=qn)
                    new_ops.append(Operator(
                        gb, "fake_quantize_dequantize_abs_max",
                        inputs={"X": [xn]}, outputs={"Out": [qn]},
                        attrs={"scale": float(self._act_scales[xn]),
                               "bit_length": self._a_bits}))
                    replaced[xn] = qn
                op.inputs = dict(op.inputs)
                op.inputs[slot] = [replaced[xn]]
            new_ops.append(op)
        gb.ops[:] = new_ops
        self._program._bump_version()

    # -- export ---------------------------------------------------------
    def save_quantized_model(self, save_model_path, model_filename=None,
                             params_filename=None):
        """Save the fake-quant program + params, with quantized weights
        stored INT8 (+ scales) — ~4x smaller on disk; the loader
        dequantizes (reference save_quantized_model)."""
        import pickle

        from ..fluid import io
        from ..fluid.scope import scope_guard
        os.makedirs(save_model_path, exist_ok=True)
        with scope_guard(self._scope):
            io.save_inference_model(
                save_model_path, list(self._feed_names),
                list(self._fetch_vars), self._exe,
                main_program=self._program,
                model_filename=model_filename,
                params_filename=params_filename)
        # quantized weights ship INT8-only: drop their fp32 copies from
        # the params blob (that's the 4x size win) and store int8+scales
        ppath = os.path.join(save_model_path,
                             params_filename or "__all__.pdparams")
        with open(ppath, "rb") as f:
            params = pickle.load(f)
        for n in self._weight_int8:
            params.pop(n, None)
        with open(ppath, "wb") as f:
            pickle.dump(params, f, protocol=4)
        blob = {"__bits__": np.asarray(self._w_bits)}
        for n, (q, s, a) in self._weight_int8.items():
            blob[f"{n}.int8"] = q
            blob[f"{n}.scale"] = s
            blob[f"{n}.axis"] = np.asarray(a)
        np.savez(os.path.join(save_model_path, "__quant_weights__"),
                 **blob)
        return save_model_path


def load_quantized_weights(dirname, scope):
    """Reconstruct int8-stored weights into `scope` (dequantize); called
    by the inference Predictor after load_inference_model."""
    qpath = os.path.join(dirname, "__quant_weights__.npz")
    if not os.path.exists(qpath):
        return False
    blob = np.load(qpath)
    names = {k[:-5] for k in blob.files if k.endswith(".int8")}
    bits = int(blob["__bits__"]) if "__bits__" in blob.files else 8
    qmax = float(2 ** (bits - 1) - 1)
    for n in names:
        q = blob[f"{n}.int8"].astype(np.float32)
        s = blob[f"{n}.scale"].astype(np.float32)
        axis = int(blob[f"{n}.axis"])
        bshape = [1] * q.ndim
        bshape[axis] = -1
        scope.set(n, q * s.reshape(bshape) / qmax)
    return True
