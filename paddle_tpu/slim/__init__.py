"""paddle.slim — model compression (reference
python/paddle/fluid/contrib/slim/)."""
from .qat import ImperativeQuantAware, QuantizationTransformPass
from .quantization import (PostTrainingQuantization, load_quantized_weights,
                           quant_dequant, QUANTIZABLE_OP_TYPES)

__all__ = ["ImperativeQuantAware", "QuantizationTransformPass",
           "PostTrainingQuantization", "load_quantized_weights",
           "quant_dequant", "QUANTIZABLE_OP_TYPES"]
