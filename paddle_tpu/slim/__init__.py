"""paddle.slim — model compression (reference
python/paddle/fluid/contrib/slim/)."""
from .quantization import (PostTrainingQuantization, load_quantized_weights,
                           quant_dequant, QUANTIZABLE_OP_TYPES)

__all__ = ["PostTrainingQuantization", "load_quantized_weights",
           "quant_dequant", "QUANTIZABLE_OP_TYPES"]
