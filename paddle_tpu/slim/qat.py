"""Quantization-aware training (reference contrib/slim/quantization/
quantization_pass.py:1 QuantizationTransformPass +
imperative/qat.py ImperativeQuantAware).

QAT simulates int8 rounding DURING training so the model learns weights
robust to quantization: fake_quantize_dequantize ops (already in the op
registry, with straight-through-estimator gradients —
fluid/ops/nn_ops.py) are inserted on the weight and activation inputs of
every quantizable op. On TPU the training math stays float (XLA has no
public int8 matmul path; SURVEY §7) — the value is the same as the
reference's: the exported int8 weights have been trained under rounding,
so post-export accuracy matches the QAT accuracy.

Static flow (apply the pass BEFORE optimizer.minimize so autodiff builds
the STE backward through the fake-quant ops):

    pass_ = QuantizationTransformPass()
    pass_.apply(main_program)
    optimizer.SGD(...).minimize(loss)

Dygraph flow (reference ImperativeQuantAware.quantize):

    qat = ImperativeQuantAware()
    qat.quantize(model)          # wraps Conv2D/Linear forwards in place
    ... train ...
    qat.save_quantized_model(model, path)   # int8 weight export (PTQ
                                            # shared path)
"""
from __future__ import annotations

import numpy as np

from .quantization import (QUANTIZABLE_OP_TYPES, _W_SLOT, _X_SLOT,
                           _channel_scales, quant_dequant)

__all__ = ["QuantizationTransformPass", "ImperativeQuantAware"]

_FQ_OP = "fake_quantize_dequantize_abs_max"


class QuantizationTransformPass:
    """Insert fake-quant on weights + activations of quantizable ops in a
    (forward) Program. Apply before building backward; the registered
    STE gradient then trains through the rounding."""

    def __init__(self, scope=None, place=None, weight_bits: int = 8,
                 activation_bits: int = 8,
                 skip_pattern=("skip_quant",),
                 quantizable_op_type=QUANTIZABLE_OP_TYPES):
        self._wbits = weight_bits
        self._abits = activation_bits
        self._types = tuple(quantizable_op_type)
        self._skip = tuple(skip_pattern)

    def apply(self, program):
        from ..fluid.framework import Operator
        n = 0
        for block in program.blocks:
            quanted: dict[tuple[str, int], str] = {}
            new_ops = []
            for op in block.ops:
                if op.type in self._types and not any(
                        s in op.attrs.get("name_scope", "")
                        for s in self._skip):
                    for slot, bits in ((_X_SLOT[op.type], self._abits),
                                       (_W_SLOT[op.type], self._wbits)):
                        names = op.input(slot)
                        if not names:
                            continue
                        vn = names[0]
                        key = (vn, bits)
                        if key not in quanted:
                            qn = f"{vn}.quant_dequant"
                            src = block._var_recursive(vn)
                            block.create_var(
                                name=qn,
                                shape=getattr(src, "shape", None),
                                dtype=getattr(src, "dtype", "float32"))
                            sn = f"{vn}.quant_dequant@scale"
                            block.create_var(name=sn, shape=(1,),
                                             dtype="float32")
                            new_ops.append(Operator(
                                block, _FQ_OP, inputs={"X": [vn]},
                                outputs={"Out": [qn], "OutScale": [sn]},
                                attrs={"bit_length": bits}))
                            quanted[key] = qn
                            n += 1
                        op.inputs[slot] = [quanted[key]]
                new_ops.append(op)
            block.ops[:] = new_ops
        program._bump_version()
        return n


class ImperativeQuantAware:
    """Dygraph QAT (reference imperative/qat.py): wraps each quantizable
    sublayer's forward so weights and inputs pass through fake-quant
    (with STE gradients) before the real compute."""

    def __init__(self, weight_bits: int = 8, activation_bits: int = 8,
                 quantizable_layer_type=("Conv2D", "Linear")):
        self._wbits = weight_bits
        self._abits = activation_bits
        self._types = tuple(quantizable_layer_type)
        self._wrapped: list = []

    def _fq(self, t, bits):
        from ..common_ops import run_op
        return run_op(_FQ_OP, {"X": t}, {"bit_length": bits})

    def quantize(self, model):
        from .. import nn
        import paddle_tpu.nn.functional as F
        for _, layer in model.named_sublayers():
            kind = type(layer).__name__
            if kind not in self._types or getattr(layer, "_qat_wrapped",
                                                  False):
                continue
            if kind == "Linear":
                def fwd(x, _l=layer):
                    return F.linear(self._fq(x, self._abits),
                                    self._fq(_l.weight, self._wbits),
                                    _l.bias)
            else:  # Conv2D
                def fwd(x, _l=layer):
                    return F.conv2d(
                        self._fq(x, self._abits),
                        self._fq(_l.weight, self._wbits), _l.bias,
                        _l._stride, _l._padding, _l._dilation, _l._groups,
                        _l._data_format)
            layer.forward = fwd
            layer._qat_wrapped = True
            self._wrapped.append(layer)
        return model

    def save_quantized_model(self, model, path: str, input_spec=None):
        """Export int8 weights of the wrapped layers (shared PTQ int8
        format: {path}.int8.npz with per-channel scales) plus the full
        fp32 state_dict for everything else."""
        blobs = {}
        for i, layer in enumerate(self._wrapped):
            w = np.asarray(layer.weight._value)
            # per-OUTPUT-channel: Conv2D OIHW axis 0; Linear [in, out]
            # last axis — mirrors PTQ (slim/quantization.py) and the
            # reference's quant_axis=1 for mul/matmul weights
            axis = 0 if w.ndim == 4 else w.ndim - 1
            scales = _channel_scales(w, axis)
            qmax = 2 ** (self._wbits - 1) - 1
            shp = [1] * w.ndim
            shp[axis] = -1
            sh = scales.reshape(shp)
            q = np.clip(np.round(w / np.maximum(sh, 1e-8) * qmax),
                        -qmax, qmax).astype(np.int8)
            blobs[f"w{i}.int8"] = q
            blobs[f"w{i}.scale"] = scales.astype(np.float32)
            blobs[f"w{i}.axis"] = np.asarray(axis)
        np.savez(path + ".int8.npz", **blobs)
        state = {k: np.asarray(getattr(v, "_value", v))
                 for k, v in model.state_dict().items()}
        np.savez(path + ".state.npz", **state)
        return path
