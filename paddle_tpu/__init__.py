"""paddle_tpu — a TPU-native deep-learning framework with the capabilities of
PaddlePaddle (~v2.0-beta "Fluid" era, reference at /root/reference).

Architecture (see SURVEY.md §7):
  * static-graph-first: Python builds a Program IR; the Executor lowers whole
    blocks to single jitted XLA computations (no per-op interpreter loop)
  * imperative (dygraph) mode: eager Tensors on jax arrays + tape autograd,
    sharing the same op registry
  * distribution: jax.sharding Mesh + XLA collectives over ICI/DCN behind the
    fleet / paddle.distributed API surface

Top-level namespace mirrors `import paddle` of the reference 2.0 API.
"""
from __future__ import annotations

__version__ = "0.1.0"

import os as _os

if _os.environ.get("PADDLE_TPU_LOCKCHECK", "") not in ("", "0"):
    # test-mode runtime lock-order sanitizer (docs/STATIC_ANALYSIS.md):
    # must install BEFORE any framework module creates its locks, so
    # every paddle_tpu lock is an order-checked proxy. analysis.* is
    # stdlib-only, so this costs nothing on the normal import path.
    from .analysis import lockcheck as _lockcheck
    _lockcheck.install()

import jax as _jax

if _os.environ.get("PADDLE_TPU_PRNG", "rbg") == "rbg":
    # Hardware RBG PRNG for jax.random: threefry mask generation costs
    # ~30% of a BERT-base seq-512 train step on v5e (measured: 26.8% ->
    # 35.2% MFU switching to rbg). Same determinism contract (keyed,
    # fold_in-able); opt out with PADDLE_TPU_PRNG=threefry.
    _jax.config.update("jax_default_prng_impl", "rbg")

from . import fluid
from .fluid import (CPUPlace, TPUPlace, CUDAPlace, ParamAttr, Program,
                    get_flags, set_flags)
from .fluid.core import Place
from .fluid.dygraph import (guard, no_grad, to_variable, enable_dygraph,
                            disable_dygraph, grad)
from .fluid.dygraph.varbase import Tensor
from .fluid.framework import in_dygraph_mode

# 2.0-style namespaces
from . import tensor
from .tensor import *  # noqa: F401,F403
# tensor functions double as Tensor/Variable METHODS (reference
# monkey_patch_varbase / monkey_patch_variable)
from .fluid.dygraph.math_op_patch import monkey_patch_tensor_methods
monkey_patch_tensor_methods()
from . import nn
from . import static
from . import optimizer
from . import metric
from . import io
from . import distributed
from . import amp
from . import vision
from . import text
from . import jit
from . import incubate
from . import observability
from . import checkpoint
from . import utils
from . import models
from . import ops as _pallas_ops  # pallas kernels register themselves

from .tensor.creation import to_tensor
from .framework_api import (get_default_dtype, set_default_dtype, seed,
                            save, load, set_device, get_device, DataParallel,
                            set_grad_enabled, is_grad_enabled, summary, flops)

# dygraph is the default mode for the 2.0 API surface, like the reference
enable_dygraph()


def disable_static(place=None):
    enable_dygraph()


def enable_static():
    disable_dygraph()


def in_dynamic_mode():
    return in_dygraph_mode()


# commonly used aliases at top level (reference python/paddle/__init__.py)
version = __version__


def __getattr__(name):
    if name == "Model":  # lazy: hapi pulls in io/callbacks
        from .hapi import Model
        return Model
    if name == "hapi":
        from . import hapi
        return hapi
    if name == "distribution":
        from . import distribution
        return distribution
    if name == "inference":
        from . import inference
        return inference
    raise AttributeError(name)
