"""paddle.jit (reference python/paddle/fluid/dygraph/jit.py +
dygraph_to_static/ ProgramTranslator).

TPU-native dynamic-to-static: jax tracing stages all fixed Python control
flow for free, so `to_static` only needs dy2static.py's AST pass for
*tensor-dependent* `if`/`while` — those become cond/while sub-block ops
(lax.cond / lax.while_loop) in static builds and eager Python branches in
dygraph (Tensor.__bool__). `save`/`load` serialise the traced Program;
TracedLayer wraps a layer trace as a runnable static program.
"""
from __future__ import annotations

import functools

import numpy as np

from . import dy2static

__all__ = ["to_static", "save", "load", "TranslatedLayer", "not_to_static",
           "ProgramTranslator", "TracedLayer"]


def to_static(function=None, input_spec=None, build_strategy=None):
    """Convert a dygraph callable for static compilation: tensor-dependent
    Python control flow is AST-rewritten into cond/while converter calls
    (dy2static.convert_to_static, reference program_translator.py:250).
    Eager calls keep dygraph semantics (tape autograd intact); tracing
    under a static Program (jit.save / declarative build) emits real
    control-flow ops."""
    def decorate(fn):
        converted = dy2static.convert_to_static(fn) \
            if ProgramTranslator().enable_to_static else fn

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return converted(*args, **kwargs)
        wrapper._original_fn = fn
        wrapper._converted_fn = converted
        wrapper._input_spec = input_spec
        return wrapper
    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


class ProgramTranslator:
    """Singleton toggle (reference ProgramTranslator.get_instance())."""
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance.enable_to_static = True
        return cls._instance

    def enable(self, enable_to_static: bool):
        self.enable_to_static = bool(enable_to_static)

    @classmethod
    def get_instance(cls):
        return cls()


def save(layer, path, input_spec=None, **configs):
    """Trace `layer` into a static Program and save (reference jit.save)."""
    from ..fluid import framework, layers, io
    from ..fluid.executor import Executor, global_scope
    from ..static import InputSpec
    import jax.numpy as jnp

    specs = input_spec or getattr(layer.forward, "_input_spec", None)
    if specs is None:
        raise ValueError("paddle.jit.save needs input_spec")
    main = framework.Program()
    startup = framework.Program()
    was_dygraph = framework.in_dygraph_mode()
    tracer = framework._dygraph_tracer_
    framework._dygraph_tracer_ = None
    try:
        with framework.program_guard(main, startup):
            feeds = []
            for i, spec in enumerate(specs):
                shape = [s if s is not None else -1 for s in spec.shape]
                feeds.append(layers.data(spec.name or f"input_{i}", shape,
                                         spec.dtype))
            # static re-trace of the layer: parameters need static mirrors
            _bind_eager_params_static(layer)
            outs = layer.forward(*feeds)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        exe = Executor()
        io.save_inference_model(path, [f.name for f in feeds], list(outs),
                                exe, main_program=main)
    finally:
        framework._dygraph_tracer_ = tracer


def _bind_eager_params_static(layer):
    """Copy eager parameter values into the global scope so the saved model
    has weights, and create persistable static Variable mirrors so shape
    inference sees param shapes during the re-trace."""
    from ..fluid import framework
    from ..fluid.executor import global_scope
    import jax.numpy as jnp
    block = framework.default_main_program().global_block()

    def bind(t):
        if not hasattr(t, "_value"):
            return
        global_scope().set(t.name, t._value)
        if block._var_recursive(t.name) is None:
            block.create_var(name=t.name, shape=tuple(t._value.shape),
                             dtype=str(t._value.dtype), persistable=True)

    for _, p in layer.named_parameters():
        bind(p)
    for _, b in layer.named_buffers():
        bind(b)


class TranslatedLayer:
    """Loaded inference model callable (reference TranslatedLayer)."""

    def __init__(self, program, feed_names, fetch_vars):
        from ..fluid.executor import Executor
        self._program = program
        self._feed_names = feed_names
        self._fetch_vars = fetch_vars
        self._exe = Executor()

    def __call__(self, *inputs):
        feed = {n: (x.numpy() if hasattr(x, "numpy") else np.asarray(x))
                for n, x in zip(self._feed_names, inputs)}
        outs = self._exe.run(self._program, feed=feed,
                             fetch_list=self._fetch_vars)
        from ..fluid.dygraph.varbase import Tensor
        res = [Tensor(o, stop_gradient=True) for o in outs]
        return res[0] if len(res) == 1 else res

    def eval(self):
        return self

    def train(self):
        return self


def load(path, **configs):
    from ..fluid import io
    from ..fluid.executor import Executor
    exe = Executor()
    program, feed_names, fetch_vars = io.load_inference_model(path, exe)
    return TranslatedLayer(program, feed_names, fetch_vars)


class TracedLayer:
    """Static-program trace of a dygraph Layer (reference
    dygraph/jit.py TracedLayer): `trace` runs the layer once eagerly for
    the dygraph result AND re-traces it into a Program the returned
    TracedLayer executes (whole-program jit via the Executor cache).
    `save_inference_model` exports the trace."""

    def __init__(self, program, feed_names, fetch_vars, layer):
        from ..fluid.executor import Executor
        self._program = program
        self._feed_names = feed_names
        self._fetch_vars = fetch_vars
        self._layer = layer
        self._exe = Executor()

    @staticmethod
    def trace(layer, inputs):
        from ..fluid import framework, layers
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        dygraph_out = layer(*inputs)
        main, startup = framework.Program(), framework.Program()
        was_tracer = framework._dygraph_tracer_
        framework._dygraph_tracer_ = None
        try:
            with framework.program_guard(main, startup):
                feeds = []
                for i, t in enumerate(inputs):
                    val = t._value if hasattr(t, "_value") else np.asarray(t)
                    feeds.append(layers.data(
                        f"traced_input_{i}", [-1] + list(val.shape[1:]),
                        str(val.dtype)))
                _bind_eager_params_static(layer)
                outs = layer.forward(*feeds)
        finally:
            framework._dygraph_tracer_ = was_tracer
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        return dygraph_out, TracedLayer(
            main, [f.name for f in feeds], list(outs), layer)

    def __call__(self, inputs):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        feed = {n: (t.numpy() if hasattr(t, "numpy") else np.asarray(t))
                for n, t in zip(self._feed_names, inputs)}
        outs = self._exe.run(self._program, feed=feed,
                             fetch_list=self._fetch_vars)
        from ..fluid.dygraph.varbase import Tensor
        res = [Tensor(o, stop_gradient=True) for o in outs]
        return res[0] if len(res) == 1 else res

    def save_inference_model(self, path, feed=None, fetch=None):
        from ..fluid import io
        from ..fluid.executor import Executor
        fetches = self._fetch_vars if fetch is None \
            else [self._fetch_vars[i] for i in fetch]
        feeds = self._feed_names if feed is None \
            else [self._feed_names[i] for i in feed]
        io.save_inference_model(path, feeds, fetches, Executor(),
                                main_program=self._program)
