"""paddle.jit (reference python/paddle/fluid/dygraph/jit.py +
dygraph_to_static/ ProgramTranslator).

TPU-native dynamic-to-static: `to_static` wraps a dygraph callable so the
whole call is traced once and compiled by XLA (jax.jit over the tape replay),
rather than AST-rewriting Python source like the reference's 13 transformers
— XLA's trace-based staging subsumes that machinery for the supported
(fixed-control-flow) subset. `save`/`load` serialise a traced Program.
"""
from __future__ import annotations

import functools

import numpy as np

__all__ = ["to_static", "save", "load", "TranslatedLayer", "not_to_static"]


def to_static(function=None, input_spec=None, build_strategy=None):
    """Compile a dygraph function/Layer.forward with XLA via jax.jit.

    The wrapped function still runs eagerly through the tracer (so autograd
    etc. work); jit acceleration of eager graphs arrives with the fused-step
    cache. The primary use — export via paddle.jit.save — traces to a static
    Program.
    """
    def decorate(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return fn(*args, **kwargs)
        wrapper._original_fn = fn
        wrapper._input_spec = input_spec
        return wrapper
    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    return fn


def save(layer, path, input_spec=None, **configs):
    """Trace `layer` into a static Program and save (reference jit.save)."""
    from ..fluid import framework, layers, io
    from ..fluid.executor import Executor, global_scope
    from ..static import InputSpec
    import jax.numpy as jnp

    specs = input_spec or getattr(layer.forward, "_input_spec", None)
    if specs is None:
        raise ValueError("paddle.jit.save needs input_spec")
    main = framework.Program()
    startup = framework.Program()
    was_dygraph = framework.in_dygraph_mode()
    tracer = framework._dygraph_tracer_
    framework._dygraph_tracer_ = None
    try:
        with framework.program_guard(main, startup):
            feeds = []
            for i, spec in enumerate(specs):
                shape = [s if s is not None else -1 for s in spec.shape]
                feeds.append(layers.data(spec.name or f"input_{i}", shape,
                                         spec.dtype))
            # static re-trace of the layer: parameters need static mirrors
            _bind_eager_params_static(layer)
            outs = layer.forward(*feeds)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        exe = Executor()
        io.save_inference_model(path, [f.name for f in feeds], list(outs),
                                exe, main_program=main)
    finally:
        framework._dygraph_tracer_ = tracer


def _bind_eager_params_static(layer):
    """Copy eager parameter values into the global scope so the saved model
    has weights, and create persistable static Variable mirrors so shape
    inference sees param shapes during the re-trace."""
    from ..fluid import framework
    from ..fluid.executor import global_scope
    import jax.numpy as jnp
    block = framework.default_main_program().global_block()

    def bind(t):
        if not hasattr(t, "_value"):
            return
        global_scope().set(t.name, t._value)
        if block._var_recursive(t.name) is None:
            block.create_var(name=t.name, shape=tuple(t._value.shape),
                             dtype=str(t._value.dtype), persistable=True)

    for _, p in layer.named_parameters():
        bind(p)
    for _, b in layer.named_buffers():
        bind(b)


class TranslatedLayer:
    """Loaded inference model callable (reference TranslatedLayer)."""

    def __init__(self, program, feed_names, fetch_vars):
        from ..fluid.executor import Executor
        self._program = program
        self._feed_names = feed_names
        self._fetch_vars = fetch_vars
        self._exe = Executor()

    def __call__(self, *inputs):
        feed = {n: (x.numpy() if hasattr(x, "numpy") else np.asarray(x))
                for n, x in zip(self._feed_names, inputs)}
        outs = self._exe.run(self._program, feed=feed,
                             fetch_list=self._fetch_vars)
        from ..fluid.dygraph.varbase import Tensor
        res = [Tensor(o, stop_gradient=True) for o in outs]
        return res[0] if len(res) == 1 else res

    def eval(self):
        return self

    def train(self):
        return self


def load(path, **configs):
    from ..fluid import io
    from ..fluid.executor import Executor
    exe = Executor()
    program, feed_names, fetch_vars = io.load_inference_model(path, exe)
    return TranslatedLayer(program, feed_names, fetch_vars)
