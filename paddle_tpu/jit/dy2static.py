"""Dygraph-to-static AST conversion (reference python/paddle/fluid/dygraph/
dygraph_to_static/ — program_translator.py:250 ProgramTranslator + the
ifelse/loop transformers).

The TPU build needs far less machinery than the reference's 13
transformers: jax tracing already stages all FIXED control flow, so only
*tensor-dependent* Python `if`/`while` must be rewritten. The transform
hoists branch/loop bodies into local functions and routes them through
runtime converters that pick the execution mode:

  * static graph build  -> layers.cond / layers.while_loop (sub-block ops
    compiled by lax.cond / lax.while_loop — the export path)
  * dygraph, tensor pred -> eager Python branch via Tensor.__bool__ (the
    tape records the taken branch; autograd intact)
  * plain Python values  -> untouched Python semantics

v1 constraints (checked, with clear errors or transform skips):
  * `return`/`break`/`continue` inside a converted branch/loop body are
    not hoisted — such statements leave the `if`/`while` untransformed
    (fine for Python preds; a tensor pred then raises via __bool__ in
    static mode).
  * loop carries must exist before the loop and keep shape/dtype (the
    XLA carry contract; reference while_op shares it).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap


class _Undefined:
    def __repr__(self):
        return "<undefined before control flow>"


UNDEFINED = _Undefined()

_CONVERTED_CACHE: dict = {}


# ---------------------------------------------------------------------------
# analysis helpers
# ---------------------------------------------------------------------------

def _assigned_names(node_list):
    names = []

    class V(ast.NodeVisitor):
        def visit_Assign(self, n):
            for t in n.targets:
                self._targets(t)
            self.generic_visit(n)

        def visit_AugAssign(self, n):
            self._targets(n.target)
            self.generic_visit(n)

        def visit_AnnAssign(self, n):
            if n.value is not None:
                self._targets(n.target)

        def visit_For(self, n):
            self._targets(n.target)
            self.generic_visit(n)

        def visit_FunctionDef(self, n):
            names.append(n.name)  # nested def binds the name; don't recurse

        def _targets(self, t):
            if isinstance(t, ast.Name):
                if t.id not in names:
                    names.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    self._targets(e)

    v = V()
    for n in node_list:
        v.visit(n)
    return names


def _read_names(node):
    names = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            names.add(n.id)
    return names


def _has_flow_escape(node_list):
    for n in node_list:
        for sub in ast.walk(n):
            if isinstance(sub, (ast.Return, ast.Break, ast.Continue)):
                return True
    return False


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _jst_call(fn_name, args):
    return ast.Call(
        func=ast.Attribute(value=_name("_jst"), attr=fn_name,
                           ctx=ast.Load()),
        args=args, keywords=[])


def _guard_stmts(names):
    """try: __pt_x = x / except NameError: __pt_x = _jst.UNDEFINED"""
    out = []
    for v in names:
        out.append(ast.Try(
            body=[ast.Assign(targets=[_name(f"__pt_{v}", ast.Store())],
                             value=_name(v))],
            handlers=[ast.ExceptHandler(
                type=ast.Tuple(elts=[_name("NameError"),
                                     _name("UnboundLocalError")],
                               ctx=ast.Load()),
                name=None,
                body=[ast.Assign(
                    targets=[_name(f"__pt_{v}", ast.Store())],
                    value=ast.Attribute(value=_name("_jst"),
                                        attr="UNDEFINED",
                                        ctx=ast.Load()))])],
            orelse=[], finalbody=[]))
    return out


# ---------------------------------------------------------------------------
# the transformer
# ---------------------------------------------------------------------------

class DygraphToStaticTransformer(ast.NodeTransformer):
    """Rewrites If/While whose bodies are hoistable into _jst converter
    calls (reference ifelse_transformer.py / loop_transformer.py)."""

    def __init__(self):
        self._uid = 0

    def _next(self):
        self._uid += 1
        return self._uid

    # -- if ---------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        if _has_flow_escape(node.body) or _has_flow_escape(node.orelse):
            return node
        mod = _assigned_names(node.body + node.orelse)
        uid = self._next()
        args = [ast.arg(arg=v) for v in mod]
        ret = ast.Return(value=ast.Tuple(
            elts=[_name(v) for v in mod], ctx=ast.Load()))
        tfn = ast.FunctionDef(
            name=f"__pt_true_{uid}", body=list(node.body) + [ret],
            args=ast.arguments(posonlyargs=[], args=args, kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            decorator_list=[])
        ffn = ast.FunctionDef(
            name=f"__pt_false_{uid}", body=list(node.orelse) + [ret],
            args=ast.arguments(posonlyargs=[], args=args, kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            decorator_list=[])
        call = _jst_call("convert_ifelse", [
            node.test, _name(tfn.name), _name(ffn.name),
            ast.Tuple(elts=[_name(f"__pt_{v}") for v in mod],
                      ctx=ast.Load())])
        if mod:
            assign = ast.Assign(
                targets=[ast.Tuple(elts=[_name(v, ast.Store())
                                         for v in mod], ctx=ast.Store())],
                value=call)
        else:
            assign = ast.Expr(value=call)
        return _guard_stmts(mod) + [tfn, ffn, assign]

    # -- while ------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if _has_flow_escape(node.body) or node.orelse:
            return node
        carries = _assigned_names(node.body)
        if not carries:
            return node
        uid = self._next()
        args = [ast.arg(arg=v) for v in carries]
        cfn = ast.FunctionDef(
            name=f"__pt_cond_{uid}",
            body=[ast.Return(value=node.test)],
            args=ast.arguments(posonlyargs=[], args=args, kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            decorator_list=[])
        bfn = ast.FunctionDef(
            name=f"__pt_body_{uid}",
            body=list(node.body) + [ast.Return(value=ast.Tuple(
                elts=[_name(v) for v in carries], ctx=ast.Load()))],
            args=ast.arguments(posonlyargs=[], args=args, kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            decorator_list=[])
        assign = ast.Assign(
            targets=[ast.Tuple(elts=[_name(v, ast.Store())
                                     for v in carries], ctx=ast.Store())],
            value=_jst_call("convert_while", [
                _name(cfn.name), _name(bfn.name),
                ast.Tuple(elts=[_name(f"__pt_{v}") for v in carries],
                          ctx=ast.Load())]))
        return _guard_stmts(carries) + [cfn, bfn, assign]


# ---------------------------------------------------------------------------
# runtime converters (the `_jst` module injected into converted globals)
# ---------------------------------------------------------------------------

def _is_var(v):
    from ..fluid.framework import Variable
    return isinstance(v, Variable)


def _is_tensor(v):
    from ..fluid.dygraph.varbase import Tensor
    return isinstance(v, Tensor)


def convert_ifelse(pred, true_fn, false_fn, args):
    if _is_var(pred):
        from ..fluid.layers import tensor as LT
        n = len(args)
        if n == 0:
            raise ValueError(
                "a tensor-pred `if` with no assigned variables has no "
                "effect in a static graph")
        res = LT.cond(pred, lambda: true_fn(*args),
                      lambda: false_fn(*args))
        return (res,) if n == 1 and not isinstance(res, (list, tuple)) \
            else tuple(res)
    taken = true_fn if bool(pred) else false_fn   # Tensor.__bool__ / python
    return taken(*args)


def convert_while(cond_fn, body_fn, args):
    first = cond_fn(*args)
    if _is_var(first):
        from ..fluid.layers import tensor as LT
        for a in args:
            if isinstance(a, _Undefined):
                raise ValueError(
                    "while-loop carry used before assignment — XLA loop "
                    "carries must exist before the loop")
        res = LT.while_loop(cond_fn, body_fn, list(args))
        return tuple(res) if isinstance(res, (list, tuple)) else (res,)
    while bool(cond_fn(*args)):
        new = body_fn(*args)
        args = new if isinstance(new, tuple) else (new,)
    return args


class _JstModule:
    UNDEFINED = UNDEFINED
    convert_ifelse = staticmethod(convert_ifelse)
    convert_while = staticmethod(convert_while)


# ---------------------------------------------------------------------------
# conversion entry
# ---------------------------------------------------------------------------

def convert_to_static(fn):
    """Return fn with tensor control flow rewritten (cached per code
    object). Falls back to the original fn when source is unavailable
    (REPL, builtins) — those can't carry tensor-dependent Python flow
    into export anyway."""
    key = getattr(fn, "__code__", None)
    if key in _CONVERTED_CACHE:
        return _CONVERTED_CACHE[key]
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn
    tree = ast.parse(src)
    fdef = tree.body[0]
    # drop @to_static-style decorators so exec doesn't recurse
    if isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        fdef.decorator_list = []
    new_tree = DygraphToStaticTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)
    glb = dict(fn.__globals__)
    glb["_jst"] = _JstModule
    if fn.__closure__:
        # free variables become globals of the converted function —
        # snapshot semantics, same trade the reference makes
        # (dygraph_to_static/utils.py func_to_source_code + exec)
        for nm, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb.setdefault(nm, cell.cell_contents)
            except ValueError:
                pass
    code = compile(new_tree, filename=f"<dy2static {fn.__qualname__}>",
                   mode="exec")
    loc: dict = {}
    exec(code, glb, loc)
    converted = functools.wraps(fn)(loc[fdef.name])
    _CONVERTED_CACHE[key] = converted
    return converted
