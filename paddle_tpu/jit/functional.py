"""Functionalize a dygraph model into ONE pure jitted XLA train step.

This is the TPU-native answer to the reference's dygraph-to-static
ProgramTranslator (dygraph_to_static/program_translator.py:250): instead of
AST-rewriting Python, we exploit that every eager op kernel is a jax function
— running the model under a jax trace yields the whole step as one
computation, with jax.value_and_grad for autodiff and the registered
optimizer-op kernels for the update. Donation makes params/opt-state updates
in-place on device.
"""
from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

from ..fluid import framework, registry
from ..fluid.dygraph.varbase import Tensor

__all__ = ["TrainStep", "make_train_step"]


class TrainStep:
    """Compiled training step: step(batch...) -> loss (host float array).

    Holds params + optimizer state as device arrays; `write_back()` syncs
    them into the model's eager tensors (for state_dict / eval)."""

    def __init__(self, model, loss_fn: Callable, optimizer: str = "adamw",
                 lr=1e-4, weight_decay: float = 0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, grad_clip_norm: float | None = None,
                 donate: bool = True, mesh=None, batch_spec=None,
                 remat: bool = False, amp_level: str | None = None):
        import jax
        import jax.numpy as jnp

        self.model = model
        self.loss_fn = loss_fn
        if remat:
            # wrap transformer layers so their activations rematerialise in
            # backward (jax.checkpoint; reference RecomputeOptimizer
            # optimizer.py:4518 / backward.py:629)
            from ..distributed.recompute import wrap_layer_recompute
            self.remat_layers = wrap_layer_recompute(model)
        else:
            self.remat_layers = 0
        self.params = [p for p in model.parameters() if p.trainable]
        self.buffers = [b for _, b in model.named_buffers()
                        if isinstance(b, Tensor)]
        self._lr = lr
        self._opt_kind = optimizer
        self._clip = grad_clip_norm
        self._mesh = mesh
        self._hyper = dict(beta1=beta1, beta2=beta2, epsilon=epsilon,
                           coeff=weight_decay)
        self.param_vals = [p._value for p in self.params]
        self.buffer_vals = [b._value for b in self.buffers]
        self.opt_state = self._init_opt_state()
        self._step_count = 0

        opt_type = {"adam": "adam", "adamw": "adamw", "sgd": "sgd",
                    "momentum": "momentum", "lamb": "lamb"}[optimizer]
        opdef = registry.require(opt_type)
        # registered per-op defaults (e.g. momentum's mu) under the shared
        # adam-style hypers
        hyper = dict(self._hyper)
        opdef.fill_default_attrs(hyper)
        clip = self._clip

        tracer = framework._dygraph_tracer()
        params = self.params
        buffers = self.buffers

        def step(param_vals, opt_state, buffer_vals, seed, lr, *batch):
            # bind traced values into the eager params and run the model —
            # every op kernel is jnp, so this traces into one computation
            def forward(vals):
                for p, v in zip(params, vals):
                    p._set_value(v)
                for b, v in zip(buffers, buffer_vals):
                    b._set_value(v)
                tracer._base_key_cache = jax.random.PRNGKey(seed)
                from ..fluid.dygraph.tracer import no_grad_guard
                import contextlib
                amp_cm = contextlib.nullcontext()
                if amp_level:
                    from ..amp.auto_cast import amp_guard
                    amp_cm = amp_guard(True, level=amp_level)
                with no_grad_guard(), amp_cm:  # no tape: jax differentiates
                    loss = loss_fn(model, *[Tensor(b, stop_gradient=True)
                                            for b in batch])
                # batch-norm style running stats were updated in-place on
                # the eager buffer tensors during the trace
                new_buf = [jax.lax.stop_gradient(b._value) for b in buffers]
                return loss._value.reshape(()), new_buf

            (loss, new_buf), grads = jax.value_and_grad(
                forward, has_aux=True)(list(param_vals))
            if clip:
                gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in grads))
                scale = jnp.minimum(1.0, clip / jnp.maximum(gnorm, 1e-12))
                grads = [g * scale for g in grads]
            lr_arr = jnp.asarray([lr], jnp.float32)
            new_vals, new_state = [], []
            for v, g, st in zip(param_vals, grads, opt_state):
                ins = {"Param": [v], "Grad": [g], "LearningRate": [lr_arr]}
                ins.update({k: [x] for k, x in st.items()})
                outs = opdef.compute(None, ins, dict(hyper))
                new_vals.append(outs["ParamOut"][0])
                new_state.append(self._next_state(st, outs))
            return loss, new_vals, new_state, new_buf

        donate_args = (0, 1, 2) if donate else ()
        if mesh is None:
            self._jit_step = jax.jit(step, donate_argnums=donate_args)
        else:
            # data-parallel: batch axis sharded over mesh axis "dp"; params,
            # optimizer state and buffers replicated. XLA's sharded autodiff
            # inserts the grad psum over ICI (replaces the reference's
            # fused-allreduce op handles).
            from jax.sharding import NamedSharding, PartitionSpec as P
            repl = NamedSharding(mesh, P())
            batch_sh = NamedSharding(mesh, P("dp"))
            self._batch_sharding = batch_sh

            # shardings come from the committed inputs: state is device_put
            # replicated here, batches are device_put batch-sharded per call
            self._jit_step = jax.jit(step, donate_argnums=donate_args)
            self.param_vals = [jax.device_put(v, repl)
                               for v in self.param_vals]
            self.opt_state = jax.tree_util.tree_map(
                lambda v: jax.device_put(v, repl), self.opt_state)
            self.buffer_vals = [jax.device_put(v, repl)
                                for v in self.buffer_vals]

    # -- optimizer state ----------------------------------------------------
    def _init_opt_state(self):
        import jax.numpy as jnp
        st = []
        for p in self.params:
            v = p._value
            if self._opt_kind in ("adam", "adamw", "lamb"):
                st.append({"Moment1": jnp.zeros(v.shape, jnp.float32),
                           "Moment2": jnp.zeros(v.shape, jnp.float32),
                           "Beta1Pow": jnp.ones((1,), jnp.float32),
                           "Beta2Pow": jnp.ones((1,), jnp.float32)})
            elif self._opt_kind == "momentum":
                st.append({"Velocity": jnp.zeros(v.shape, jnp.float32)})
            else:
                st.append({})
        return st

    @staticmethod
    def _next_state(st, outs):
        new = {}
        if "Moment1" in st:
            new = {"Moment1": outs["Moment1Out"][0],
                   "Moment2": outs["Moment2Out"][0],
                   "Beta1Pow": outs["Beta1PowOut"][0],
                   "Beta2Pow": outs["Beta2PowOut"][0]}
        elif "Velocity" in st:
            new = {"Velocity": outs["VelocityOut"][0]}
        return new

    # -- execution -----------------------------------------------------------
    def __call__(self, *batch, seed: int | None = None):
        import jax.numpy as jnp
        tracer = framework._dygraph_tracer()
        saved = [p._value for p in self.params]
        saved_key = tracer._base_key_cache if tracer else None
        self._step_count += 1
        seed = self._step_count if seed is None else seed
        lr = self._lr() if callable(self._lr) else float(self._lr)
        saved_buf = [b._value for b in self.buffers]
        batch_vals = [b._value if isinstance(b, Tensor) else jnp.asarray(b)
                      for b in batch]
        if self._mesh is not None:
            import jax
            batch_vals = [jax.device_put(b, self._batch_sharding)
                          for b in batch_vals]
        try:
            loss, self.param_vals, self.opt_state, self.buffer_vals = \
                self._jit_step(
                    self.param_vals, self.opt_state, self.buffer_vals,
                    np.uint32(seed), lr, *batch_vals)
        finally:
            for p, v in zip(self.params, saved):
                p._set_value(v)
            for b, v in zip(self.buffers, saved_buf):
                b._set_value(v)
            if tracer:
                tracer._base_key_cache = saved_key
                tracer.reset_tape()
        return loss

    def write_back(self):
        """Sync trained values into the model's eager parameters."""
        for p, v in zip(self.params, self.param_vals):
            p._set_value(v)
        for b, v in zip(self.buffers, self.buffer_vals):
            b._set_value(v)


def make_train_step(model, loss_fn, **kwargs) -> TrainStep:
    """loss_fn(model, *batch_tensors) -> scalar-ish Tensor."""
    return TrainStep(model, loss_fn, **kwargs)
