"""LeNet — BASELINE config 1 flagship (reference python/paddle/vision/models/lenet.py
and the recognize_digits book test fluid/tests/book/test_recognize_digits.py)."""
from __future__ import annotations

from .. import nn


class LeNet(nn.Layer):
    def __init__(self, num_classes: int = 10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1),
            nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0),
            nn.ReLU(),
            nn.MaxPool2D(2, 2))
        self.fc = nn.Sequential(
            nn.Linear(400, 120),
            nn.Linear(120, 84),
            nn.Linear(84, num_classes))

    def forward(self, x):
        from .. import tensor as T
        x = self.features(x)
        x = T.flatten(x, 1)
        return self.fc(x)


def build_lenet_program(batch_size: int = -1):
    """Static-graph LeNet (the fluid way): returns
    (main_program, startup_program, feeds, fetches)."""
    from ..fluid import framework, layers
    main = framework.Program()
    startup = framework.Program()
    with framework.program_guard(main, startup):
        img = layers.data("img", [batch_size, 1, 28, 28], "float32")
        label = layers.data("label", [batch_size, 1], "int64")
        conv1 = layers.conv2d(img, 6, 3, padding=1, act="relu")
        pool1 = layers.pool2d(conv1, 2, "max", 2)
        conv2 = layers.conv2d(pool1, 16, 5, act="relu")
        pool2 = layers.pool2d(conv2, 2, "max", 2)
        f = layers.flatten(pool2, axis=1)
        fc1 = layers.fc(f, 120, act="relu")
        fc2 = layers.fc(fc1, 84, act="relu")
        logits = layers.fc(fc2, 10)
        loss = layers.softmax_with_cross_entropy(logits, label)
        avg_loss = layers.mean(loss)
        acc = layers.accuracy(logits, label)
    return main, startup, {"img": img, "label": label}, \
        {"loss": avg_loss, "acc": acc, "logits": logits}
