"""VGG family (reference python/paddle/vision/models/vgg.py surface)."""
from __future__ import annotations

from .. import nn

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19"]

_CFGS = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
         512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
         "M", 512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(nn.Layer):
    def __init__(self, depth=16, num_classes=1000, batch_norm=False,
                 dropout=0.5):
        super().__init__()
        layers = []
        in_c = 3
        for v in _CFGS[depth]:
            if v == "M":
                layers.append(nn.MaxPool2D(kernel_size=2, stride=2))
            else:
                layers.append(nn.Conv2D(in_c, v, 3, padding=1))
                if batch_norm:
                    layers.append(nn.BatchNorm2D(v))
                layers.append(nn.ReLU())
                in_c = v
        self.features = nn.Sequential(*layers)
        self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        self.classifier = nn.Sequential(
            nn.Flatten(),
            nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(dropout),
            nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(dropout),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        return self.classifier(self.avgpool(self.features(x)))


def vgg11(pretrained=False, batch_norm=False, **kw):
    return VGG(11, batch_norm=batch_norm, **kw)


def vgg13(pretrained=False, batch_norm=False, **kw):
    return VGG(13, batch_norm=batch_norm, **kw)


def vgg16(pretrained=False, batch_norm=False, **kw):
    return VGG(16, batch_norm=batch_norm, **kw)


def vgg19(pretrained=False, batch_norm=False, **kw):
    return VGG(19, batch_norm=batch_norm, **kw)
