"""ResNet family — BASELINE config 2 flagship (ResNet-50 ImageNet).

Capability parity with the reference model zoo
(/root/reference/python/paddle/vision/models/resnet.py:1) on paddle_tpu.nn
layers.  TPU notes: convs lower to XLA conv_general_dilated on the MXU;
BatchNorm runs per-shard under data-parallel jit, which with batch-sharded
inputs gives the same semantics the reference's sync_batch_norm_op.cu
achieves with an explicit ncclAllReduce (SPMD psum is inserted by XLA for
the grads; running stats stay per-replica as in the reference default BN).
Train via jit.functional.make_train_step (whole step = one XLA program).
"""
from __future__ import annotations

from .. import nn

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "BasicBlock", "BottleneckBlock"]


def _conv_bn(in_c, out_c, k, stride=1, groups=1, act=True,
             data_format="NCHW"):
    pad = (k - 1) // 2
    layers = [nn.Conv2D(in_c, out_c, k, stride=stride, padding=pad,
                        groups=groups, bias_attr=False,
                        data_format=data_format),
              nn.BatchNorm2D(out_c, data_format=data_format)]
    if act:
        layers.append(nn.ReLU())
    return nn.Sequential(*layers)


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, in_c, c, stride=1, downsample=None,
                 data_format="NCHW"):
        super().__init__()
        self.conv1 = _conv_bn(in_c, c, 3, stride, data_format=data_format)
        self.conv2 = _conv_bn(c, c, 3, act=False, data_format=data_format)
        self.downsample = downsample
        self.relu = nn.ReLU()

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = self.conv2(self.conv1(x))
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, in_c, c, stride=1, downsample=None,
                 data_format="NCHW"):
        super().__init__()
        self.conv1 = _conv_bn(in_c, c, 1, data_format=data_format)
        self.conv2 = _conv_bn(c, c, 3, stride, data_format=data_format)
        self.conv3 = _conv_bn(c, c * 4, 1, act=False,
                              data_format=data_format)
        self.downsample = downsample
        self.relu = nn.ReLU()

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = self.conv3(self.conv2(self.conv1(x)))
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """depth in {18, 34, 50, 101, 152}; `with_pool`/`num_classes` follow the
    reference constructor surface."""

    _SPECS = {18: (BasicBlock, [2, 2, 2, 2]),
              34: (BasicBlock, [3, 4, 6, 3]),
              50: (BottleneckBlock, [3, 4, 6, 3]),
              101: (BottleneckBlock, [3, 4, 23, 3]),
              152: (BottleneckBlock, [3, 8, 36, 3])}

    def __init__(self, block=None, depth=50, num_classes=1000,
                 with_pool=True, data_format="NCHW"):
        super().__init__()
        if block is None:
            block, counts = self._SPECS[depth]
        else:
            _, counts = self._SPECS[depth]
        self.num_classes = num_classes
        self.with_pool = with_pool
        # data_format="NHWC" runs the whole trunk channel-minor — the
        # native TPU conv layout (inputs may stay NCHW; they are transposed
        # once at the stem). NCHW stays the default for reference parity.
        self._data_format = data_format
        df = data_format
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3, bias_attr=False,
                      data_format=df),
            nn.BatchNorm2D(64, data_format=df), nn.ReLU(),
            nn.MaxPool2D(kernel_size=3, stride=2, padding=1,
                         data_format=df))
        stages = []
        in_c = 64
        for i, (c, n) in enumerate(zip([64, 128, 256, 512], counts)):
            blocks = []
            for j in range(n):
                stride = 2 if i > 0 and j == 0 else 1
                down = None
                if stride != 1 or in_c != c * block.expansion:
                    down = _conv_bn(in_c, c * block.expansion, 1, stride,
                                    act=False, data_format=df)
                blocks.append(block(in_c, c, stride, down, data_format=df))
                in_c = c * block.expansion
            stages.append(nn.Sequential(*blocks))
        self.layer1, self.layer2, self.layer3, self.layer4 = stages
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1), data_format=df)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)
        self.flatten = nn.Flatten()

    def forward(self, x):
        if self._data_format == "NHWC" and x.shape[-1] != 3:
            # accept standard NCHW input with one edge transpose
            from .. import tensor as T
            x = T.transpose(x, [0, 2, 3, 1])
        x = self.stem(x)
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.flatten(x))
        return x


def _make(depth, **kwargs):
    return ResNet(depth=depth, **kwargs)


def resnet18(pretrained=False, **kwargs):
    return _make(18, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _make(34, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _make(50, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _make(101, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _make(152, **kwargs)
