"""MobileNet V1/V2 (reference python/paddle/vision/models/mobilenetv1.py,
mobilenetv2.py surface).  Depthwise convs use Conv2D(groups=C), which XLA
lowers to feature-group conv on TPU."""
from __future__ import annotations

from .. import nn

__all__ = ["MobileNetV1", "MobileNetV2", "mobilenet_v1", "mobilenet_v2"]


def _cbr(in_c, out_c, k, stride=1, groups=1, act="relu6"):
    layers = [nn.Conv2D(in_c, out_c, k, stride=stride, padding=(k - 1) // 2,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(out_c)]
    if act:
        layers.append(nn.ReLU6() if act == "relu6" else nn.ReLU())
    return nn.Sequential(*layers)


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000):
        super().__init__()
        c = lambda ch: max(8, int(ch * scale))
        cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
               *[(512, 1)] * 5, (1024, 2), (1024, 1)]
        layers = [_cbr(3, c(32), 3, 2, act="relu")]
        in_c = c(32)
        for out, s in cfg:
            layers.append(_cbr(in_c, in_c, 3, s, groups=in_c, act="relu"))
            layers.append(_cbr(in_c, c(out), 1, act="relu"))
            in_c = c(out)
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.flatten = nn.Flatten()
        self.fc = nn.Linear(in_c, num_classes)

    def forward(self, x):
        return self.fc(self.flatten(self.pool(self.features(x))))


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand):
        super().__init__()
        hid = in_c * expand
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand != 1:
            layers.append(_cbr(in_c, hid, 1))
        layers += [_cbr(hid, hid, 3, stride, groups=hid),
                   _cbr(hid, out_c, 1, act=None)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000):
        super().__init__()
        c = lambda ch: max(8, int(ch * scale))
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        layers = [_cbr(3, c(32), 3, 2)]
        in_c = c(32)
        for t, ch, n, s in cfg:
            for i in range(n):
                layers.append(_InvertedResidual(
                    in_c, c(ch), s if i == 0 else 1, t))
                in_c = c(ch)
        last = max(1280, int(1280 * scale))
        layers.append(_cbr(in_c, last, 1))
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.flatten = nn.Flatten()
        self.classifier = nn.Sequential(nn.Dropout(0.2),
                                        nn.Linear(last, num_classes))

    def forward(self, x):
        return self.classifier(self.flatten(self.pool(self.features(x))))


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    return MobileNetV1(scale=scale, **kw)


def mobilenet_v2(pretrained=False, scale=1.0, **kw):
    return MobileNetV2(scale=scale, **kw)
