"""Model zoo (reference python/paddle/vision/models + PaddleClas/NLP/Rec
flagships per BASELINE.json configs)."""
from .lenet import LeNet, build_lenet_program

__all__ = ["LeNet", "build_lenet_program", "ResNet", "resnet18", "resnet34",
           "resnet50", "resnet101", "resnet152", "VGG", "vgg11", "vgg13",
           "vgg16", "vgg19", "MobileNetV1", "MobileNetV2", "mobilenet_v1",
           "mobilenet_v2", "BertModel", "BertForPretraining", "BertConfig",
           "GPTConfig", "GPTForCausalLM"]

_LAZY = {
    "resnet": ("ResNet", "BasicBlock", "BottleneckBlock", "resnet18",
               "resnet34", "resnet50", "resnet101", "resnet152"),
    "vgg": ("VGG", "vgg11", "vgg13", "vgg16", "vgg19"),
    "mobilenet": ("MobileNetV1", "MobileNetV2", "mobilenet_v1",
                  "mobilenet_v2"),
    "bert": ("BertModel", "BertForPretraining", "BertConfig"),
    "gpt": ("GPTConfig", "GPTForCausalLM", "init_gpt_params", "gpt_forward",
            "gpt_loss"),
}


def __getattr__(name):
    # lazy heavy families
    for mod, names in _LAZY.items():
        if name in names:
            import importlib
            m = importlib.import_module(f".{mod}", __name__)
            return getattr(m, name)
    raise AttributeError(name)
