"""Model zoo (reference python/paddle/vision/models + PaddleClas/NLP/Rec
flagships per BASELINE.json configs)."""
from .lenet import LeNet, build_lenet_program

__all__ = ["LeNet", "build_lenet_program"]


def __getattr__(name):
    # lazy heavy families
    if name in ("ResNet", "resnet50", "resnet18"):
        from . import resnet
        return getattr(resnet, name)
    if name in ("BertModel", "BertForPretraining", "BertConfig"):
        from . import bert
        return getattr(bert, name)
    if name in ("GPTModel", "GPTConfig"):
        from . import gpt
        return getattr(gpt, name)
    raise AttributeError(name)
