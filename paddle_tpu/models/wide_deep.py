"""wide&deep CTR model — BASELINE config 4 flagship (PaddleRec wide_deep).

Reference counterpart: PaddleRec wide_deep on the PS runtime
(distributed_lookup_table_op + large_scale_kv.h pull/push).  TPU redesign:
the embedding tables are mesh-sharded device arrays
(paddle_tpu.parallel.embedding.ShardedEmbedding) and the "pull" is a
collective lookup; same functional-core pattern as models/gpt.py so one
implementation serves single-chip and the dp x mp mesh.
"""
from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["WideDeepConfig", "init_widedeep_params", "widedeep_forward",
           "widedeep_loss", "WideDeepTrainStep"]


@dataclasses.dataclass
class WideDeepConfig:
    """Criteo-style: `num_slots` categorical slots hashed into one unified
    vocab + `dense_dim` continuous features."""
    vocab_size: int = 1024 * 1024
    num_slots: int = 26
    embed_dim: int = 16
    dense_dim: int = 13
    hidden: tuple = (400, 400, 400)
    init_std: float = 0.01

    @classmethod
    def tiny(cls):
        return cls(vocab_size=4096, num_slots=8, embed_dim=8, dense_dim=4,
                   hidden=(32, 16))


def init_widedeep_params(cfg: WideDeepConfig, seed: int = 0) -> dict:
    rng = np.random.RandomState(seed)
    n = lambda *s: rng.normal(0, cfg.init_std, s).astype(np.float32)
    widths = [cfg.num_slots * cfg.embed_dim + cfg.dense_dim, *cfg.hidden, 1]
    mlp = []
    for i, (a, b) in enumerate(zip(widths[:-1], widths[1:])):
        mlp.append({"w": (rng.normal(0, np.sqrt(2.0 / a), (a, b))
                          .astype(np.float32)),
                    "b": np.zeros((b,), np.float32)})
    return {
        "embed": n(cfg.vocab_size, cfg.embed_dim),   # deep table
        "wide": n(cfg.vocab_size, 1),                # wide (linear) table
        "wide_dense": n(cfg.dense_dim, 1),
        "bias": np.zeros((1,), np.float32),
        "mlp": mlp,
    }


def widedeep_forward(params: dict, sparse_ids, dense, cfg: WideDeepConfig,
                     lookup=None):
    """sparse_ids [B, S] int, dense [B, F] -> logits [B, 1].

    `lookup(table, ids) -> [B, S, dim]` defaults to a dense take; the
    mesh trainer passes the sharded-collective lookup."""
    take = lookup or (lambda t, i: jnp.take(t, i.astype(jnp.int32), axis=0))
    emb = take(params["embed"], sparse_ids)          # [B, S, D]
    wide_rows = take(params["wide"], sparse_ids)     # [B, S, 1]
    B = sparse_ids.shape[0]
    h = jnp.concatenate([emb.reshape(B, -1), dense], axis=-1)
    for i, layer in enumerate(params["mlp"]):
        h = h @ layer["w"] + layer["b"]
        if i < len(params["mlp"]) - 1:
            h = jax.nn.relu(h)
    wide = jnp.sum(wide_rows, axis=1) + dense @ params["wide_dense"]
    return h + wide + params["bias"]


def widedeep_loss(params, sparse_ids, dense, label, cfg, lookup=None):
    """Mean sigmoid BCE-with-logits."""
    z = widedeep_forward(params, sparse_ids, dense, cfg, lookup)
    lab = label.astype(jnp.float32).reshape(z.shape)
    return jnp.mean(jnp.maximum(z, 0) - z * lab + jnp.log1p(
        jnp.exp(-jnp.abs(z))))


class WideDeepTrainStep:
    """step(sparse_ids, dense, label) -> loss over a ("dp","mp") mesh:
    batch sharded over dp, embedding tables row-sharded over mp with the
    collective lookup, MLP replicated; Adam state sharded like its param."""

    def __init__(self, cfg: WideDeepConfig, mesh=None, dp: int = 1,
                 mp: int = 1, lr=1e-3, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, seed: int = 0, devices=None):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        if mesh is None:
            devs = np.array(devices if devices is not None
                            else jax.devices())[:dp * mp]
            mesh = Mesh(devs.reshape(dp, mp), ("dp", "mp"))
        self.cfg, self.mesh = cfg, mesh
        self.mp = mesh.shape.get("mp", 1)
        self._lr = lr
        self._hyper = dict(beta1=beta1, beta2=beta2, epsilon=epsilon)

        params = jax.tree_util.tree_map(
            jnp.asarray, init_widedeep_params(cfg, seed))
        tbl = NamedSharding(mesh, P("mp", None))
        repl = NamedSharding(mesh, P())
        self._shardings = jax.tree_util.tree_map(lambda _: repl, params)
        self._shardings["embed"] = tbl
        self._shardings["wide"] = tbl
        self.params = jax.tree_util.tree_map(jax.device_put, params,
                                             self._shardings)
        self.opt_state = jax.tree_util.tree_map(
            lambda v, sh: {"m1": jax.device_put(
                               jnp.zeros(v.shape, jnp.float32), sh),
                           "m2": jax.device_put(
                               jnp.zeros(v.shape, jnp.float32), sh)},
            self.params, self._shardings)
        self._pows = (jax.device_put(jnp.ones((1,), jnp.float32), repl),
                      jax.device_put(jnp.ones((1,), jnp.float32), repl))
        self._batch_sh = NamedSharding(mesh, P("dp"))

        if self.mp > 1:
            from ..parallel.embedding import sharded_embedding_lookup
            lookup = lambda t, i: sharded_embedding_lookup(
                t, i, mesh, "mp")
        else:
            lookup = None

        from ..fluid import registry
        opdef = registry.require("adam")
        hyper = dict(self._hyper)
        opdef.fill_default_attrs(hyper)

        def step(params, opt_state, pows, lr, ids, dense, label):
            loss, grads = jax.value_and_grad(widedeep_loss)(
                params, ids, dense, label, cfg, lookup)
            lr_arr = jnp.asarray([lr], jnp.float32)
            b1p, b2p = pows

            def upd(p, g, st):
                ins = {"Param": [p], "Grad": [g], "LearningRate": [lr_arr],
                       "Moment1": [st["m1"]], "Moment2": [st["m2"]],
                       "Beta1Pow": [b1p], "Beta2Pow": [b2p]}
                outs = opdef.compute(None, ins, dict(hyper))
                return (outs["ParamOut"][0],
                        {"m1": outs["Moment1Out"][0],
                         "m2": outs["Moment2Out"][0]},
                        outs["Beta1PowOut"][0], outs["Beta2PowOut"][0])

            flat_p, tdef = jax.tree_util.tree_flatten(params)
            flat_g = jax.tree_util.tree_leaves(grads)
            flat_s = tdef.flatten_up_to(opt_state)
            new_p, new_s = [], []
            for p, g, st in zip(flat_p, flat_g, flat_s):
                p2, s2, b1n, b2n = upd(p, g, st)
                new_p.append(p2)
                new_s.append(s2)
            return (loss, jax.tree_util.tree_unflatten(tdef, new_p),
                    jax.tree_util.tree_unflatten(tdef, new_s), (b1n, b2n))

        self._jit_step = jax.jit(
            step, donate_argnums=(0, 1, 2),
            out_shardings=(repl, self._shardings,
                           jax.tree_util.tree_map(
                               lambda s: {"m1": s, "m2": s},
                               self._shardings,
                               is_leaf=lambda s: isinstance(
                                   s, NamedSharding)),
                           (repl, repl)))

    def __call__(self, sparse_ids, dense, label):
        args = [jax.device_put(jnp.asarray(a), self._batch_sh)
                for a in (sparse_ids, dense, label)]
        lr = self._lr() if callable(self._lr) else float(self._lr)
        loss, self.params, self.opt_state, self._pows = self._jit_step(
            self.params, self.opt_state, self._pows, np.float32(lr), *args)
        return loss
