"""BERT/ERNIE-style encoder — BASELINE config 3 flagship.

Reference equivalents: PaddleNLP BERT on top of the reference transformer
stack (python/paddle/nn/layer/transformer.py) with fused attention
(operators/fused/multihead_matmul_op, fused_embedding_eltwise_layernorm).
Built on paddle_tpu.nn; runs in eager mode and jits cleanly for the bench
(whole pretrain step = one XLA computation, bf16 on the MXU via amp).
"""
from __future__ import annotations

import dataclasses

import numpy as np

from .. import nn
from ..nn import functional as F


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12

    @classmethod
    def base(cls):
        return cls()

    @classmethod
    def tiny(cls):
        return cls(vocab_size=1024, hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, intermediate_size=128,
                   max_position_embeddings=128)


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        from ..fluid.param_attr import ParamAttr
        attr = lambda: ParamAttr(initializer=nn.initializer.Normal(
            0.0, cfg.initializer_range))
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                            weight_attr=attr())
        self.position_embeddings = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size, weight_attr=attr())
        self.token_type_embeddings = nn.Embedding(
            cfg.type_vocab_size, cfg.hidden_size, weight_attr=attr())
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        from .. import tensor as T
        seq_len = input_ids.shape[1]
        if position_ids is None:
            position_ids = T.arange(0, seq_len, 1, dtype="int64")
            position_ids = T.expand(T.unsqueeze(position_ids, 0),
                                    [input_ids.shape[0], seq_len])
        if token_type_ids is None:
            token_type_ids = T.zeros_like(input_ids)
        emb = T.add(
            T.add(self.word_embeddings(input_ids),
                  self.position_embeddings(position_ids)),
            self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(emb))


class BertPooler(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, hidden):
        from .. import tensor as T
        first = T.slice(hidden, [1], [0], [1])
        first = T.squeeze(first, [1])
        return F.tanh(self.dense(first))


class BertModel(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.config = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_attention_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation=cfg.hidden_act,
            attn_dropout=cfg.attention_probs_dropout_prob,
            act_dropout=0.0)
        self.encoder = nn.TransformerEncoder(enc_layer,
                                             cfg.num_hidden_layers)
        self.pooler = BertPooler(cfg)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        from .. import tensor as T
        if attention_mask is not None and len(attention_mask.shape) == 2:
            # (B, S) 1/0 -> additive (B, 1, 1, S)
            m = T.cast(attention_mask, "float32")
            m = T.unsqueeze(T.unsqueeze(m, 1), 1)
            # keep=1 -> 0, pad=0 -> -1e9 : additive mask = (m - 1) * 1e9
            attention_mask = T.scale(m, scale=1e9, bias=-1.0,
                                     bias_after_scale=False)
        emb = self.embeddings(input_ids, token_type_ids, position_ids)
        seq_out = self.encoder(emb, attention_mask)
        pooled = self.pooler(seq_out)
        return seq_out, pooled


class BertPretrainingHeads(nn.Layer):
    def __init__(self, cfg: BertConfig, embedding_weights=None):
        super().__init__()
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.act = getattr(F, cfg.hidden_act)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)
        self.decoder_weight = embedding_weights  # tied to word embeddings
        self.decoder_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True)
        self.seq_relationship = nn.Linear(cfg.hidden_size, 2)

    def forward(self, sequence_output, pooled_output,
                masked_positions=None):
        from .. import tensor as T
        if masked_positions is not None:
            # gather the masked rows BEFORE the vocab projection
            # (MLPerf-BERT / PaddleNLP practice): the [B*S, V] logits
            # shrink to [B*P, V] — the head's FLOPs and HBM traffic drop
            # by S/P (~7x at 15% masking)
            B, S = sequence_output.shape[0], sequence_output.shape[1]
            H = sequence_output.shape[2]
            flat = T.reshape(sequence_output, [-1, H])
            base = T.reshape(
                T.arange(0, B * S, S, dtype="int64"), [B, 1])
            idx = T.add(masked_positions, base)
            sequence_output = T.gather(flat, T.reshape(idx, [-1]))
        h = self.layer_norm(self.act(self.transform(sequence_output)))
        # tied softmax: logits = h @ word_embeddings^T
        logits = T.matmul(h, self.decoder_weight, transpose_y=True)
        logits = T.add(logits, self.decoder_bias)
        nsp = self.seq_relationship(pooled_output)
        return logits, nsp


class BertForPretraining(nn.Layer):
    """MLM + NSP pretraining objective (config 3)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.cls = BertPretrainingHeads(
            cfg, self.bert.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_positions=None):
        """masked_positions [B, P] selects the MLM prediction rows; the
        logits come back [B*P, V] (flattened) instead of [B, S, V], and
        loss() then takes labels [B, P]."""
        seq_out, pooled = self.bert(input_ids, token_type_ids,
                                    attention_mask=attention_mask)
        return self.cls(seq_out, pooled, masked_positions)

    def loss(self, prediction_logits, nsp_logits, masked_lm_labels,
             next_sentence_labels, ignore_index=-100):
        """Mean MLM xent over non-ignored positions + NSP xent."""
        from .. import tensor as T
        vocab = prediction_logits.shape[-1]
        logits2d = T.reshape(prediction_logits, [-1, vocab])
        labels = T.reshape(masked_lm_labels, [-1, 1])
        per_tok = F.softmax_with_cross_entropy(
            logits2d, labels, ignore_index=ignore_index)
        mask = T.cast(T.not_equal(
            labels, T.full_like(labels, ignore_index)), "float32")
        denom = T.clip(T.sum(mask), min=1.0)
        mlm = T.divide(T.sum(T.multiply(per_tok, mask)), denom)
        nsp = F.cross_entropy(nsp_logits, next_sentence_labels)
        return T.add(mlm, T.reshape(nsp, [1]))
