"""GPT-style causal decoder — BASELINE config 5 flagship (GPT-3 1.3B).

Reference parity target: the PaddleNLP GPT built on the reference
transformer stack (python/paddle/nn/layer/transformer.py) and trained with
PipelineOptimizer (/root/reference/python/paddle/fluid/optimizer.py:3666).
Here the model has a **functional core**: params are a pytree, the forward
is a pure jax function, and one implementation serves every execution mode —

  * single device / dygraph (`GPTForCausalLM` Layer wraps the core),
  * dp x tp via GSPMD PartitionSpec rules (`gpt_sharding_rules`),
  * pipeline parallel via stacked per-stage params
    (paddle_tpu.parallel.pipeline + hybrid.HybridParallelTrainStep).

Blocks are pre-LN transformer decoders; block params are stacked [L, ...]
and scanned with lax.scan (compile time stays O(1) in depth — the
TPU answer to the reference's per-op graph growing with depth).
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["GPTConfig", "init_gpt_params", "gpt_param_specs", "gpt_forward",
           "gpt_loss", "gpt_block_fn", "decoder_tail", "GPTForCausalLM"]


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_position_embeddings: int = 1024
    intermediate_size: int | None = None  # default 4*hidden
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    dropout: float = 0.0
    amp_dtype: str | None = None  # "bfloat16" casts block compute
    attn_impl: str = "xla"  # "xla" | "flash" (Pallas) | "ring" (sp mesh)
    # Mixture-of-Experts (num_experts > 0 replaces every block's dense FFN
    # with a routed expert bank — parallel/moe.py, "ep" mesh axis)
    num_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # rematerialise each block in backward: the lax.scan over layers would
    # otherwise stash every layer's attention probs ([L,B,H,T,T] — OOM at
    # 350M/seq-1024 on one v5e chip)
    remat: bool = True
    # epilogue-fused decoder sub-blocks (ops/pallas_block.py): the
    # attention-out projection + residual + LN2 and the FFN + residual
    # run as GEMM-epilogue Pallas programs where the autobench gate
    # measures them faster than the composed XLA chain (dense blocks,
    # dropout=0 path only; False pins the composed chain everywhere)
    fused_blocks: bool = True

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size
        assert self.hidden_size % self.num_heads == 0

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_layers", 4)
        kw.setdefault("num_heads", 4)
        kw.setdefault("max_position_embeddings", 128)
        return cls(**kw)

    @classmethod
    def gpt2_small(cls, **kw):
        return cls(**kw)

    @classmethod
    def gpt3_1p3b(cls, **kw):
        """GPT-3 XL: 24 layers, d_model 2048, 16 heads of 128."""
        kw.setdefault("hidden_size", 2048)
        kw.setdefault("num_layers", 24)
        kw.setdefault("num_heads", 16)
        kw.setdefault("max_position_embeddings", 2048)
        return cls(**kw)


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------

def init_gpt_params(cfg: GPTConfig, seed: int = 0) -> dict:
    """Pytree: embeddings + stacked blocks [L, ...] + final LN. LM head is
    tied to wte (Megatron/GPT-2 convention)."""
    rng = np.random.RandomState(seed)
    D, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    s = cfg.initializer_range

    def norm(*shape):
        return rng.normal(0.0, s, shape).astype(np.float32)

    blocks = {
        "ln1_s": np.ones((L, D), np.float32),
        "ln1_b": np.zeros((L, D), np.float32),
        "wq": norm(L, D, D), "bq": np.zeros((L, D), np.float32),
        "wk": norm(L, D, D), "bk": np.zeros((L, D), np.float32),
        "wv": norm(L, D, D), "bv": np.zeros((L, D), np.float32),
        # output/down projections scaled 1/sqrt(2L) (GPT-2 residual scaling)
        "wo": norm(L, D, D) / math.sqrt(2 * L),
        "bo": np.zeros((L, D), np.float32),
        "ln2_s": np.ones((L, D), np.float32),
        "ln2_b": np.zeros((L, D), np.float32),
    }
    E = cfg.num_experts
    if E > 0:
        blocks.update({
            "wg": norm(L, D, E),
            "we_up": norm(L, E, D, F),
            "be_up": np.zeros((L, E, F), np.float32),
            "we_down": norm(L, E, F, D) / math.sqrt(2 * L),
            "be_down": np.zeros((L, E, D), np.float32),
        })
    else:
        blocks.update({
            "w_up": norm(L, D, F), "b_up": np.zeros((L, F), np.float32),
            "w_down": norm(L, F, D) / math.sqrt(2 * L),
            "b_down": np.zeros((L, D), np.float32),
        })
    return {
        "wte": norm(cfg.vocab_size, D),
        "wpe": norm(cfg.max_position_embeddings, D),
        "blocks": blocks,
        "lnf_s": np.ones((D,), np.float32),
        "lnf_b": np.zeros((D,), np.float32),
    }


def gpt_param_specs(pp_stacked: bool = False, moe: bool = False) -> dict:
    """PartitionSpec pytree (megatron-style tp; blocks get a leading "pp"
    dim when stacked per-stage; expert banks shard E over "ep"). Axes not
    present in the mesh are dropped by ShardingRules._restrict-like
    resolution in hybrid.py."""
    from jax.sharding import PartitionSpec as P

    def blk(*entries):
        return P(*(("pp",) if pp_stacked else ()), None, *entries)

    blocks = {
        "ln1_s": blk(None), "ln1_b": blk(None),
        "wq": blk(None, "tp"), "bq": blk("tp"),
        "wk": blk(None, "tp"), "bk": blk("tp"),
        "wv": blk(None, "tp"), "bv": blk("tp"),
        "wo": blk("tp", None), "bo": blk(None),
        "ln2_s": blk(None), "ln2_b": blk(None),
    }
    if moe:
        blocks.update({
            "wg": blk(None, None),
            "we_up": blk("ep", None, "tp"), "be_up": blk("ep", "tp"),
            "we_down": blk("ep", "tp", None), "be_down": blk("ep", None),
        })
    else:
        blocks.update({
            "w_up": blk(None, "tp"), "b_up": blk("tp"),
            "w_down": blk("tp", None), "b_down": blk(None),
        })
    return {
        "wte": P("tp", None),
        "wpe": P(),
        "blocks": blocks,
        "lnf_s": P(),
        "lnf_b": P(),
    }


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _ln(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), -1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _causal_attention(q, k, v, n_heads, impl="xla"):
    """q,k,v: [B, T, D] -> [B, T, D]; softmax in fp32."""
    B, T, D = q.shape
    hd = D // n_heads
    q = q.reshape(B, T, n_heads, hd)
    k = k.reshape(B, T, n_heads, hd)
    v = v.reshape(B, T, n_heads, hd)
    if impl == "ring":
        # sequence-parallel ring attention over the ambient sp mesh axis
        # (parallel/sequence_parallel.py); T here is the LOCAL shard
        from ..parallel.sequence_parallel import current_ring, \
            ring_attention
        ctx = current_ring()
        if ctx is None:
            raise RuntimeError(
                "attn_impl='ring' needs an enclosing ring_context(mesh, "
                "axis)")
        mesh, axis = ctx
        o = ring_attention(q.transpose(0, 2, 1, 3),
                           k.transpose(0, 2, 1, 3),
                           v.transpose(0, 2, 1, 3), mesh, axis,
                           causal=True)
        return o.transpose(0, 2, 1, 3).reshape(B, T, D)
    if impl == "flash":
        from ..ops.flash_attention import _flash_wins
        from ..ops.pallas_attention import flash_attention
        qh, kh, vh = (a.transpose(0, 2, 1, 3) for a in (q, k, v))
        # same measure-once gate as the fused_attention op: the Pallas
        # kernel only keeps the hot path on shapes where it beats XLA
        if _flash_wins(qh, kh, vh, None, None, True):
            o = flash_attention(qh, kh, vh, causal=True)
            return o.transpose(0, 2, 1, 3).reshape(B, T, D)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(hd)
    mask = jnp.tril(jnp.ones((T, T), bool))
    scores = jnp.where(mask, scores.astype(jnp.float32), -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    o = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    return o.reshape(B, T, D)


def _dropout(x, rate, key):
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


def decoder_tail(p, a2, x2, cfg: GPTConfig):
    """Post-attention tail of a dense pre-LN decoder block, 2-d form:

        z = x2 + a2 @ wo + bo
        h = LN2(z)
        out = z + gelu_tanh(h @ w_up + b_up) @ w_down + b_down

    a2/x2: (M, D). ONE source of truth for this math: gpt_block_fn AND
    the serving decode model (prefill/decode bodies) both call it, so
    the serving token-parity contract survives the fused paths. Each
    sub-block runs as an epilogue-fused Pallas program
    (ops/pallas_block.py) where the autobench gate measures it faster
    than the composed XLA chain at this (M, D) shape; everywhere else
    the composed chain below runs bit-identically to the pre-PR-7
    code."""
    cdt = x2.dtype
    wo, bo = p["wo"].astype(cdt), p["bo"].astype(cdt)
    w_up, b_up = p["w_up"].astype(cdt), p["b_up"].astype(cdt)
    w_down, b_down = p["w_down"].astype(cdt), p["b_down"].astype(cdt)
    eps = cfg.layer_norm_eps
    m, d = x2.shape
    f = w_up.shape[-1]
    it = cdt.itemsize
    seed = jnp.zeros((1,), jnp.int32)
    z = h = None
    if cfg.fused_blocks:
        from ..ops.pallas_block import (can_use_fused_ffn_ln,
                                        can_use_fused_out_ln,
                                        ffn_ln_wins, fused_ffn_ln,
                                        fused_out_ln, out_ln_wins)
        if can_use_fused_out_ln(m, d, d, it) \
                and out_ln_wins(m, d, d, cdt, 0.0, eps):
            z, h = fused_out_ln(a2, wo, bo, x2, p["ln2_s"], p["ln2_b"],
                                seed, 0.0, eps)
    if z is None:
        z = x2 + (a2 @ wo + bo).astype(x2.dtype)
        h = _ln(z, p["ln2_s"], p["ln2_b"], eps)
    if cfg.fused_blocks and can_use_fused_ffn_ln(m, d, f, it) \
            and ffn_ln_wins(m, d, f, cdt, "gelu_tanh", "none"):
        ones = jnp.ones((d,), jnp.float32)
        zeros = jnp.zeros((d,), jnp.float32)
        return fused_ffn_ln(h.astype(cdt), w_up, b_up, w_down, b_down,
                            z, ones, zeros, seed, "gelu_tanh", "none",
                            0.0, eps)
    u = jax.nn.gelu(h.astype(cdt) @ w_up + b_up, approximate=True)
    dn = u @ w_down + b_down
    return z + dn.astype(z.dtype)


def gpt_block_fn(p: dict, x, cfg: GPTConfig, key=None):
    """One pre-LN decoder block; p leaves are unstacked ([D,...]).

    `key` enables residual dropout (GPT-2 placement: after the attention
    out-projection and after the FFN down-projection); None or
    cfg.dropout=0 is the deterministic path. The pipeline engines re-derive
    the same key at recompute time, so rematerialised backward sees
    identical masks.

    Returns (x, aux): aux is the MoE load-balance loss of this block's
    routed FFN (0.0 for the dense FFN)."""
    cdt = jnp.dtype(cfg.amp_dtype) if cfg.amp_dtype else x.dtype
    c = lambda a: a.astype(cdt)
    drop = cfg.dropout if (cfg.dropout and key is not None) else 0.0
    if drop:
        k1, k2 = jax.random.split(key)
    h = _ln(x, p["ln1_s"], p["ln1_b"], cfg.layer_norm_eps)
    q = c(h) @ c(p["wq"]) + c(p["bq"])
    k = c(h) @ c(p["wk"]) + c(p["bk"])
    v = c(h) @ c(p["wv"]) + c(p["bv"])
    a = _causal_attention(q, k, v, cfg.num_heads, cfg.attn_impl)
    if cfg.num_experts == 0 and not drop:
        # dense deterministic path: attention-out + FFN sub-blocks as
        # epilogue-fused Pallas programs behind the autobench gate
        # (composed-chain fallback inside decoder_tail is bit-identical
        # to the previous inline code)
        B, T, D = x.shape
        x = decoder_tail(p, c(a).reshape(B * T, D),
                         x.reshape(B * T, D), cfg).reshape(B, T, D)
        return x, jnp.zeros((), jnp.float32)
    proj = a @ c(p["wo"]) + c(p["bo"])
    if drop:
        proj = _dropout(proj, drop, k1)
    x = x + proj.astype(x.dtype)
    h = _ln(x, p["ln2_s"], p["ln2_b"], cfg.layer_norm_eps)
    if cfg.num_experts > 0:
        from ..parallel.moe import moe_ffn
        y, aux = moe_ffn(
            c(h), p["wg"], p["we_up"], p["be_up"], p["we_down"],
            p["be_down"], capacity_factor=cfg.moe_capacity_factor,
            top_k=cfg.moe_top_k)
        if drop:
            y = _dropout(y, drop, k2)
        return x + y.astype(x.dtype), aux
    u = jax.nn.gelu(c(h) @ c(p["w_up"]) + c(p["b_up"]), approximate=True)
    d = u @ c(p["w_down"]) + c(p["b_down"])
    if drop:
        d = _dropout(d, drop, k2)
    x = x + d.astype(x.dtype)
    return x, jnp.zeros((), jnp.float32)


def _embed(params, ids, cfg: GPTConfig):
    T = ids.shape[-1]
    if T > params["wpe"].shape[0]:
        raise ValueError(
            f"sequence length {T} exceeds max_position_embeddings="
            f"{params['wpe'].shape[0]}")
    x = jnp.take(params["wte"], ids, axis=0) + params["wpe"][:T]
    if cfg.amp_dtype:
        x = x.astype(jnp.dtype(cfg.amp_dtype))
    return x


def _head(params, x, cfg: GPTConfig):
    x = _ln(x, params["lnf_s"], params["lnf_b"], cfg.layer_norm_eps)
    # logits in fp32 for a stable softmax-xent
    return x.astype(jnp.float32) @ params["wte"].T.astype(jnp.float32)


def block_body(cfg: GPTConfig):
    """Scan body over stacked block params, rematerialised per layer when
    cfg.remat (jax.checkpoint — reference RecomputeOptimizer semantics at
    layer granularity). ys is the per-layer MoE aux loss."""
    def body(h, blk):
        return gpt_block_fn(blk, h, cfg)

    if cfg.remat:
        ck = jax.checkpoint(lambda blk, h: gpt_block_fn(blk, h, cfg))
        return lambda h, blk: ck(blk, h)
    return body


def block_body_keyed(cfg: GPTConfig):
    """Like block_body but the scan xs is (blk, per-layer dropout key)."""
    def inner(blk, h, key):
        return gpt_block_fn(blk, h, cfg, key)

    if cfg.remat:
        inner = jax.checkpoint(inner)

    def body(h, xs):
        blk, key = xs
        return inner(blk, h, key)

    return body


def gpt_forward_aux(params: dict, ids, cfg: GPTConfig, key=None):
    """(logits [B, T, V], aux): aux = summed MoE load-balance loss over
    layers (0.0 for dense models). `key` turns on dropout (training)."""
    x = _embed(params, ids, cfg)
    if cfg.dropout and key is not None:
        kemb, key = jax.random.split(key)
        x = _dropout(x, cfg.dropout, kemb)
        lkeys = jax.random.split(key, cfg.num_layers)
        x, auxs = jax.lax.scan(block_body_keyed(cfg), x,
                               (params["blocks"], lkeys))
    else:
        x, auxs = jax.lax.scan(block_body(cfg), x, params["blocks"])
    return _head(params, x, cfg), jnp.sum(auxs)


def gpt_forward(params: dict, ids, cfg: GPTConfig, key=None):
    """ids [B, T] int -> logits [B, T, V]. Blocks run under lax.scan over
    the stacked [L, ...] leaves."""
    return gpt_forward_aux(params, ids, cfg, key=key)[0]


def gpt_loss(params: dict, ids, cfg: GPTConfig, logits=None, key=None):
    """Mean next-token cross entropy; predicts ids[:,1:] from ids[:,:-1].
    MoE models add cfg.moe_aux_weight * load-balance aux."""
    aux = None
    if logits is None:
        logits, aux = gpt_forward_aux(params, ids, cfg, key=key)
    logits = logits[:, :-1]
    labels = ids[:, 1:]
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], -1)[..., 0]
    loss = jnp.mean(logz - gold)
    if aux is not None and cfg.num_experts > 0:
        loss = loss + cfg.moe_aux_weight * aux
    return loss


# ---------------------------------------------------------------------------
# dygraph wrapper (API parity with the Layer zoo)
# ---------------------------------------------------------------------------

from ..fluid.dygraph.layers import Layer as _Layer
from ..fluid.dygraph.varbase import Tensor as _Tensor


class GPTForCausalLM(_Layer):
    """Layer wrapper binding framework Parameters onto the functional core
    (trainable with the jit.functional.TrainStep pattern)."""

    def __init__(self, cfg: GPTConfig, seed: int = 0):
        super().__init__()
        self.cfg = cfg
        flat, self._treedef = jax.tree_util.tree_flatten(
            init_gpt_params(cfg, seed))
        self._param_list = []
        for i, leaf in enumerate(flat):
            p = _Tensor(jnp.asarray(leaf), stop_gradient=False,
                        persistable=True)
            self.add_parameter(f"p_{i}", p)
            self._param_list.append(p)

    def param_tree(self):
        return jax.tree_util.tree_unflatten(
            self._treedef, [p._value for p in self._param_list])

    def forward(self, ids):
        ids_v = ids._value if isinstance(ids, _Tensor) else ids
        return _Tensor(gpt_forward(self.param_tree(), ids_v, self.cfg),
                       stop_gradient=False)

    def loss(self, ids):
        ids_v = ids._value if isinstance(ids, _Tensor) else ids
        return _Tensor(gpt_loss(self.param_tree(), ids_v, self.cfg),
                       stop_gradient=False)
