"""Production traffic harness: open-loop load generation + SLO report.

The serving tier has only ever been driven closed-loop (submit, wait,
submit) — which can never expose queueing collapse, because a slow
server slows its own offered load. This module is the open-loop
replayer the ROADMAP's production-traffic item calls for: arrivals
fire on a precomputed schedule whether or not earlier requests
finished, the way traffic from millions of independent users does.

Design rules:

  * deterministic — every arrival time, prompt length, output length,
    tenant, tier and prompt token comes from a counter-based Philox
    stream keyed by ``TrafficConfig.seed``; two generators with the
    same config produce byte-identical schedules (no wall-clock
    randomness, so chaos tests can replay the exact same traffic
    around an injected fault);
  * open loop — `run` submits on schedule and NEVER waits for
    completions; backpressure shows up as rejected/shed counts in the
    report, not as a silenced arrival process;
  * arrival processes — `constant`, `diurnal` (sinusoidal rate
    modulation, a day compressed into `diurnal_period` seconds) and
    `bursty` (square-wave on/off bursts), all realised by thinning a
    homogeneous Poisson stream at the peak rate;
  * tagged requests — tenant, priority tier and per-tier relative
    deadline ride each request into the scheduler's admission control
    (priority aging, token-bucket quotas, shed-by-priority);
  * SLOs are first-class — `slo_report` turns the finished handles
    into p50/p99 TTFT, p99 inter-token latency, deadline attainment
    and goodput (tokens from requests that met their deadline), and
    mirrors them onto ``paddle_tpu_slo_*`` registry metrics so a
    scrape sees the same numbers the bench JSON reports.

No jax imports — the generator drives an Engine (in-process), a
ServingClient (wire) or any submit callable, and is unit-testable
against a bare Scheduler (tests/test_slo_harness.py).
"""
from __future__ import annotations

import itertools
import math
import threading
import time
import weakref

import numpy as np

from ..observability import registry as _obs
from .scheduler import QueueFull

__all__ = ["TrafficConfig", "Arrival", "LoadGenerator", "LoadResult",
           "slo_report"]

# SLO surface (docs/SERVING.md): the load generator writes what it
# measured, labeled per generator run, so `/metrics` exposes the same
# attainment/goodput numbers the bench JSON rows carry
_TTFT_H = _obs.histogram(
    "paddle_tpu_slo_ttft_seconds",
    "submit-to-first-token latency of generated traffic", ["gen"])
_ITL_H = _obs.histogram(
    "paddle_tpu_slo_inter_token_seconds",
    "mean inter-token latency per finished request", ["gen"])
_MET = _obs.counter(
    "paddle_tpu_slo_deadline_met_total",
    "generated requests that completed within their deadline", ["gen"])
_MISSED = _obs.counter(
    "paddle_tpu_slo_deadline_missed_total",
    "generated requests that expired, were preempted, shed, rejected "
    "or errored", ["gen"])
_GOODPUT = _obs.counter(
    "paddle_tpu_slo_goodput_tokens_total",
    "tokens from requests that met their deadline", ["gen"])
_ATTAIN = _obs.gauge(
    "paddle_tpu_slo_attainment_ratio",
    "met requests / offered requests for the latest report", ["gen"])

_gen_ids = itertools.count()


def _drop_gen_series(gen: str):
    for m in (_TTFT_H, _ITL_H, _MET, _MISSED, _GOODPUT, _ATTAIN):
        m.remove_matching(gen=gen)


def _weighted(rng: np.random.Generator, choices):
    """choices: dict value -> weight (or list of (value, weight))."""
    items = list(choices.items()) if isinstance(choices, dict) \
        else list(choices)
    vals = [v for v, _ in items]
    w = np.asarray([float(p) for _, p in items], np.float64)
    return vals[int(rng.choice(len(vals), p=w / w.sum()))]


class TrafficConfig:
    """One traffic mix. All rates are requests/sec of OFFERED load."""

    def __init__(self, rate: float = 20.0, duration: float = 5.0,
                 arrival: str = "constant",
                 diurnal_period: float = 10.0,
                 diurnal_depth: float = 0.8,
                 burst_period: float = 2.0, burst_fraction: float = 0.25,
                 burst_factor: float = 4.0,
                 prompt_lens=None, output_lens=None,
                 tenants=None, tiers=None, deadlines=None,
                 vocab_size: int = 256, seed: int = 0,
                 prefix_pool: int = 0, prefix_len: int = 0,
                 prefix_zipf: float = 1.1,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0):
        if arrival not in ("constant", "diurnal", "bursty"):
            raise ValueError(f"unknown arrival process {arrival!r}")
        if not 0.0 <= diurnal_depth < 1.0:
            raise ValueError("diurnal_depth must be in [0, 1)")
        self.rate = float(rate)
        self.duration = float(duration)
        self.arrival = arrival
        self.diurnal_period = float(diurnal_period)
        self.diurnal_depth = float(diurnal_depth)
        self.burst_period = float(burst_period)
        self.burst_fraction = float(burst_fraction)
        self.burst_factor = float(burst_factor)
        # mixed-length traffic (Ragged Paged Attention regime): short
        # chat turns next to long-context prompts, short and long
        # generations interleaved
        self.prompt_lens = prompt_lens or {4: 4, 8: 3, 16: 2, 32: 1}
        self.output_lens = output_lens or {2: 3, 4: 3, 8: 2, 16: 1}
        self.tenants = tenants or {"default": 1}
        self.tiers = tiers or {0: 1, 1: 2, 2: 1}
        # per-tier RELATIVE deadline seconds (None = unbounded)
        self.deadlines = deadlines if deadlines is not None \
            else {0: 30.0, 1: 60.0, 2: None}
        self.vocab_size = int(vocab_size)
        self.seed = int(seed)
        # shared-prefix traffic (PR 19): each arrival prepends a
        # zipf-popular system prompt from a pool of `prefix_pool`
        # fixed prefixes of `prefix_len` tokens, then its own unique
        # suffix — the fleet-shaped workload the radix prefix cache
        # exists for. 0/0 (the default) leaves every existing config's
        # schedule byte-identical.
        self.prefix_pool = int(prefix_pool)
        self.prefix_len = int(prefix_len)
        self.prefix_zipf = float(prefix_zipf)
        if self.prefix_pool < 0 or self.prefix_len < 0:
            raise ValueError("prefix_pool/prefix_len must be >= 0")
        if self.prefix_zipf <= 0:
            raise ValueError("prefix_zipf must be > 0")
        # stochastic decode knobs stamped onto every arrival
        # (serving/sampling.py validates the same ranges server-side)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0")

    # -- time-varying offered rate --------------------------------------
    def rate_at(self, t: float) -> float:
        if self.arrival == "diurnal":
            return self.rate * (1.0 + self.diurnal_depth * math.sin(
                2.0 * math.pi * t / self.diurnal_period))
        if self.arrival == "bursty":
            frac = (t % self.burst_period) / self.burst_period
            return self.rate * self.burst_factor \
                if frac < self.burst_fraction else self.rate
        return self.rate

    @property
    def peak_rate(self) -> float:
        if self.arrival == "diurnal":
            return self.rate * (1.0 + self.diurnal_depth)
        if self.arrival == "bursty":
            return self.rate * self.burst_factor
        return self.rate


class Arrival:
    """One scheduled request: offset seconds from run start + tags."""

    __slots__ = ("index", "t", "prompt", "max_new_tokens", "tenant",
                 "tier", "deadline", "temperature", "top_k", "top_p",
                 "seed")

    def __init__(self, index, t, prompt, max_new_tokens, tenant, tier,
                 deadline, temperature=0.0, top_k=0, top_p=1.0,
                 seed=None):
        self.index = index
        self.t = t
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.tenant = tenant
        self.tier = tier
        self.deadline = deadline
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        # per-arrival seed (from the per-index Philox stream): the
        # whole point is that a same-config rerun resubmits the SAME
        # seed, so stochastic decode replays token-for-token
        self.seed = seed

    def __repr__(self):
        return (f"Arrival({self.index}, t={self.t:.4f}, "
                f"plen={len(self.prompt)}, mnt={self.max_new_tokens}, "
                f"tenant={self.tenant!r}, tier={self.tier}, "
                f"deadline={self.deadline})")


class LoadResult:
    """What a run produced: (arrival, handle) pairs for submitted
    requests plus the arrivals the scheduler turned away at submit."""

    def __init__(self, name: str, started_at: float, elapsed: float):
        self.name = name
        self.started_at = started_at
        self.elapsed = elapsed
        self.handles: list[tuple[Arrival, object]] = []
        self.rejected: list[Arrival] = []
        # gen labels slo_report already mirrored to the registry for
        # this result: re-reporting (full run, then a window slice)
        # must not double-count the paddle_tpu_slo_* series
        self._mirrored: set[str] = set()

    @property
    def offered(self) -> int:
        return len(self.handles) + len(self.rejected)

    def wait(self, timeout: float = 120.0) -> bool:
        """Block until every submitted request finished (including
        shed/preempted — anything that set its done event)."""
        deadline = time.monotonic() + timeout
        for _, h in self.handles:
            if not h.wait(max(0.0, deadline - time.monotonic())):
                return False
        return True


class LoadGenerator:
    """Deterministic open-loop replayer for one TrafficConfig."""

    def __init__(self, cfg: TrafficConfig, name: str | None = None):
        self.cfg = cfg
        self.name = name if name is not None else f"g{next(_gen_ids)}"
        # a dead generator's series leave the exposition
        weakref.finalize(self, _drop_gen_series, self.name)

    # -- schedule (pure, deterministic) ---------------------------------
    def schedule(self) -> list[Arrival]:
        """The full arrival list for this config — counter-based Philox
        streams only, so the same seed replays byte-identically."""
        cfg = self.cfg
        rng = np.random.Generator(np.random.Philox(key=cfg.seed))
        lam = cfg.peak_rate
        # shared system-prompt pool: its own key word ((1<<64)-1 can
        # never collide with a per-index stream), zipf-ranked weights
        # (entry 0 most popular) drawn per arrival from the main stream
        pool: list[np.ndarray] = []
        pool_w = None
        if cfg.prefix_pool > 0 and cfg.prefix_len > 0:
            prng0 = np.random.Generator(np.random.Philox(
                key=np.array([cfg.seed, (1 << 64) - 1], np.uint64)))
            pool = [prng0.integers(0, cfg.vocab_size,
                                   size=cfg.prefix_len,
                                   dtype=np.int64).astype(np.int32)
                    for _ in range(cfg.prefix_pool)]
            pool_w = 1.0 / np.arange(
                1, cfg.prefix_pool + 1) ** cfg.prefix_zipf
            pool_w = pool_w / pool_w.sum()
        out: list[Arrival] = []
        t = 0.0
        i = 0
        while True:
            t += float(rng.exponential(1.0 / lam))
            if t >= cfg.duration:
                break
            # thinning: keep the candidate with prob rate(t)/peak
            if float(rng.random()) > cfg.rate_at(t) / lam:
                continue
            plen = int(_weighted(rng, cfg.prompt_lens))
            mnt = int(_weighted(rng, cfg.output_lens))
            tenant = str(_weighted(rng, cfg.tenants))
            tier = int(_weighted(rng, cfg.tiers))
            deadline = cfg.deadlines.get(tier)
            # prompt tokens from a stream keyed by (seed, index): the
            # i-th request's content does not depend on how many
            # earlier candidates the thinning pass dropped
            prng = np.random.Generator(np.random.Philox(
                key=(cfg.seed, i)))
            prompt = prng.integers(0, cfg.vocab_size, size=plen,
                                   dtype=np.int64).astype(np.int32)
            if pool:
                # zipf-popular shared head + this request's unique
                # suffix (the suffix is the plen draw above, so prompt
                # content without a pool is unchanged byte-for-byte)
                j = int(rng.choice(len(pool), p=pool_w))
                prompt = np.concatenate([pool[j], prompt])
            seed = None
            if cfg.temperature > 0:
                # per-index stream again: the i-th arrival's seed never
                # depends on thinning, so a rerun replays it exactly
                seed = int(prng.integers(0, 1 << 62))
            out.append(Arrival(i, t, prompt, mnt, tenant, tier,
                               deadline, temperature=cfg.temperature,
                               top_k=cfg.top_k, top_p=cfg.top_p,
                               seed=seed))
            i += 1
        return out

    # -- execution ------------------------------------------------------
    def run(self, submit, *, now=time.monotonic, sleep=time.sleep,
            stop: threading.Event | None = None) -> LoadResult:
        """Open-loop replay: call ``submit(arrival)`` at each scheduled
        offset (late submits fire immediately — the generator never
        skips offered load). `submit` returns a handle with
        ``wait(timeout)`` (e.g. scheduler.Request) or None for
        fire-and-forget transports; QueueFull/QuotaExceeded count as
        rejected offered load, and so does a ValueError from an
        arrival the target cannot serve (prompt+max_new over the
        engine's max_seq_len) — one oversized arrival must not abort
        the replay, or the same-arrivals baseline/faulted comparison
        breaks. `stop` aborts the replay early."""
        t0 = now()
        res = LoadResult(self.name, t0, 0.0)
        for arr in self.schedule():
            if stop is not None and stop.is_set():
                break
            delay = (t0 + arr.t) - now()
            if delay > 0:
                sleep(delay)
            try:
                h = submit(arr)
            except (QueueFull, ValueError):
                res.rejected.append(arr)
                continue
            if h is not None:
                res.handles.append((arr, h))
        res.elapsed = now() - t0
        return res

    def run_engine(self, engine, **kw) -> LoadResult:
        """Replay against a serving Engine in-process."""
        def submit(arr: Arrival):
            return engine.submit(arr.prompt, arr.max_new_tokens,
                                 deadline=arr.deadline,
                                 priority=arr.tier, tenant=arr.tenant,
                                 temperature=arr.temperature,
                                 top_k=arr.top_k, top_p=arr.top_p,
                                 seed=arr.seed)
        return self.run(submit, **kw)

    def run_client(self, client, timeout: float = 120.0,
                   stream: bool = True, **kw) -> LoadResult:
        """Replay over the wire (serving/frontend.py ServingClient or
        a router). The blocking `generate` calls run on their own
        threads so the arrival process stays open-loop; since the
        multiplexed transport (PR 11) those threads genuinely share
        ONE client's pooled channels — concurrent calls interleave by
        request id on the same sockets instead of each opening a
        connection, so wire TTFT measures the server, not
        head-of-line queueing in the client. Each handle
        mimics Request enough for slo_report
        (wait/status/generated/deadline...). With ``stream=True`` (the
        default) each call rides the streaming wire generate: token
        frames stamp first/last-token times as they ARRIVE, so
        slo_report over a wire run carries real end-to-end TTFT and
        inter-token percentiles — including every network and router
        hop, which the in-process run_engine numbers can never see.
        ``stream=False`` restores the one-shot wire call (attainment +
        goodput only, ttft/itl percentiles None)."""
        threads: list[threading.Thread] = []

        class _WireHandle:
            def __init__(self, arr: Arrival, submitted_at: float):
                self.status = "pending"
                self.generated: list[int] = []
                self.trace_id = None
                self.deadline = None if arr.deadline is None \
                    else submitted_at + arr.deadline
                self._queued_at = submitted_at
                self.submitted_at = submitted_at
                self.finished_at = None
                self.first_token_at = None
                self.last_token_at = None
                self._streamed = 0
                self._done = threading.Event()

            def wait(self, t=None):
                return self._done.wait(t)

            def on_tokens(self, toks, idx):
                # ARRIVAL time of a pushed frame — the wire-true SLO
                # clock (includes queueing, prefill, network, router)
                t = time.monotonic()
                if self.first_token_at is None:
                    self.first_token_at = t
                self.last_token_at = t
                self._streamed = max(self._streamed, idx + len(toks))

            def ttft(self):
                if self.first_token_at is None:
                    return None
                return self.first_token_at - self._queued_at

            def inter_token(self):
                if self.first_token_at is None \
                        or self.last_token_at is None \
                        or self._streamed < 2:
                    return None
                return (self.last_token_at - self.first_token_at) \
                    / (self._streamed - 1)

        def submit(arr: Arrival):
            h = _WireHandle(arr, time.monotonic())

            def call():
                try:
                    rep = client.generate(
                        arr.prompt, arr.max_new_tokens,
                        deadline=arr.deadline, timeout=timeout,
                        priority=arr.tier, tenant=arr.tenant,
                        stream=stream,
                        on_token=h.on_tokens if stream else None,
                        temperature=arr.temperature, top_k=arr.top_k,
                        top_p=arr.top_p, seed=arr.seed)
                    h.status = rep.get("status", "error")
                    h.trace_id = rep.get("trace_id")
                    h.generated = list(np.asarray(
                        rep.get("tokens", ())).ravel())
                except Exception:
                    h.status = "error"
                h.finished_at = time.monotonic()
                h._done.set()

            th = threading.Thread(target=call, daemon=True)
            th.start()
            threads.append(th)
            return h

        res = self.run(submit, **kw)
        for th in threads:
            th.join(timeout)
        return res


def _pct(sorted_vals: list[float], p: float) -> float | None:
    """Nearest-rank percentile: the smallest value with at least p% of
    the samples at or below it (p50 of [a, b] is a, not b)."""
    if not sorted_vals:
        return None
    i = max(0, math.ceil(p / 100.0 * len(sorted_vals)) - 1)
    return sorted_vals[min(len(sorted_vals) - 1, i)]


def _pct_exemplar(sorted_pairs: list, p: float):
    """Trace id of the nearest-rank percentile sample — the request
    that IS the reported p99, so an SLO regression links straight to
    one assembled fleet trace instead of a number."""
    if not sorted_pairs:
        return None
    i = max(0, math.ceil(p / 100.0 * len(sorted_pairs)) - 1)
    return sorted_pairs[min(len(sorted_pairs) - 1, i)][1]


def slo_report(result: LoadResult, window: tuple | None = None,
               gen: str | None = None) -> dict:
    """SLO attainment over a LoadResult (call after `result.wait()`).

    A request MEETS its SLO when it finished with status "done" within
    its deadline (unbounded requests just need "done"); expired,
    preempted, shed, rejected and errored requests miss. Goodput counts
    only tokens from requests that met. `window=(lo, hi)` restricts the
    report to arrivals with lo <= arr.t < hi — how the chaos drills
    compare pre-fault / post-recovery slices of one run; rates
    (goodput_tokens_per_sec) are then per second of the WINDOW, not of
    the whole run.
    """
    gen = gen if gen is not None else result.name
    pairs = result.handles
    rejected = list(result.rejected)
    span = max(result.elapsed, 1e-9)
    if window is not None:
        lo, hi = window
        pairs = [(a, h) for a, h in pairs if lo <= a.t < hi]
        rejected = [a for a in rejected if lo <= a.t < hi]
        # rates are per second OF THE WINDOW, not of the whole run —
        # a post-recovery slice must not be diluted by pre-fault time
        span = max(min(hi, result.elapsed) - max(lo, 0.0), 1e-9)
    # mirror to the registry once per (result, gen): the docs idiom —
    # slo_report(res) then slo_report(res, window=...) — must not
    # double-count the scrape surface. Custom gen labels have no
    # LoadGenerator finalizer, so their series lifetime is tied to the
    # RESULT they were mirrored through (no unbounded exposition from
    # periodic windowed reports with unique labels).
    mirror = gen not in result._mirrored
    result._mirrored.add(gen)
    if mirror:
        weakref.finalize(result, _drop_gen_series, gen)
    ttfts: list[tuple] = []     # (seconds, trace id or None)
    itls: list[tuple] = []
    met = 0
    good_tokens = 0
    by_status: dict[str, int] = {}
    for arr, h in pairs:
        by_status[h.status] = by_status.get(h.status, 0) + 1
        # engine Requests carry .trace_id natively; wire handles learn
        # theirs from the generate reply — either way the histogram
        # observation carries the exemplar so a bucket links back to
        # the collector's assembled trace
        tid = getattr(h, "trace_id", None)
        tt = h.ttft()
        if tt is not None:
            ttfts.append((tt, tid))
            if mirror:
                _TTFT_H.labels(gen=gen).observe(tt, trace_id=tid)
        itl = h.inter_token()
        if itl is not None:
            itls.append((itl, tid))
            if mirror:
                _ITL_H.labels(gen=gen).observe(itl, trace_id=tid)
        ok = h.status == "done" and (
            h.deadline is None or h.finished_at is None
            or h.finished_at <= h.deadline)
        if ok:
            met += 1
            good_tokens += len(h.generated)
        if mirror:
            (_MET if ok else _MISSED).labels(gen=gen).inc()
    if mirror:
        _MISSED.labels(gen=gen).inc(len(rejected))
    by_status["rejected"] = by_status.get("rejected", 0) + len(rejected)
    offered = len(pairs) + len(rejected)
    attainment = met / offered if offered else None
    if mirror:
        if attainment is not None:
            _ATTAIN.labels(gen=gen).set(attainment)
        _GOODPUT.labels(gen=gen).inc(good_tokens)
    ttfts.sort(key=lambda p: p[0])
    itls.sort(key=lambda p: p[0])
    tt_vals = [v for v, _ in ttfts]
    itl_vals = [v for v, _ in itls]
    return {
        "offered": offered,
        "met": met,
        "attainment": round(attainment, 4) if attainment is not None
        else None,
        "goodput_tokens_per_sec": round(good_tokens / span, 2),
        "goodput_tokens": good_tokens,
        "ttft_ms_p50": None if not tt_vals
        else round(_pct(tt_vals, 50) * 1e3, 3),
        "ttft_ms_p99": None if not tt_vals
        else round(_pct(tt_vals, 99) * 1e3, 3),
        "ttft_p99_trace": _pct_exemplar(ttfts, 99),
        "itl_ms_p50": None if not itl_vals
        else round(_pct(itl_vals, 50) * 1e3, 3),
        "itl_ms_p99": None if not itl_vals
        else round(_pct(itl_vals, 99) * 1e3, 3),
        "itl_p99_trace": _pct_exemplar(itls, 99),
        "by_status": by_status,
        "elapsed_s": round(result.elapsed, 3),
    }
