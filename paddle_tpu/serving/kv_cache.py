"""Paged KV-cache management: fixed-size pages in preallocated pools.

Ragged Paged Attention (PAPERS.md) shape: the KV history of every
in-flight request lives in fixed-size pages of ONE preallocated device
pool per engine (no per-request HBM allocs, no reshape/realloc as
sequences grow), addressed through a per-request page table. This module
is the host-side accountant:

  * `PagePool` — free-list allocator over `num_pages` page slots with
    capacity-based admission control (`can_admit`) and occupancy stats;
  * `PageTable` — one request's ordered page list + logical length;
  * `defrag` — compacts live pages to the low end of the pool (device
    gather + table rewrite) so a long-running engine can shrink its pool
    or snapshot a dense prefix.

The device arrays themselves ([L, P, ps, H, d] pools) are built by the
model adapter (serving/model.py); the pool hands out page INDICES only,
so the accountant stays synchronous and lock-cheap while all array work
remains inside the jitted step.
"""
from __future__ import annotations

import itertools
import math
import threading
import weakref

from ..observability import registry as _obs

__all__ = ["PagePool", "PageTable", "pages_needed", "defrag_plan"]

# page accounting on the process-wide registry (labeled per pool
# instance); PagePool.stats() keys are unchanged — they now READ these
# (always=True: the legacy counters must keep counting even when the
# telemetry kill switch is on)
_PAGE_ALLOCS = _obs.counter(
    "paddle_tpu_serving_pages_alloc_total",
    "pages handed out by the pool", ["pool"], always=True)
_PAGE_FREES = _obs.counter(
    "paddle_tpu_serving_pages_freed_total",
    "pages returned to the pool", ["pool"], always=True)
_PAGE_ALLOC_FAILURES = _obs.counter(
    "paddle_tpu_serving_page_alloc_failures_total",
    "allocations refused for lack of free pages", ["pool"],
    always=True)

_pool_ids = itertools.count()


def _drop_pool_series(inst: str):
    for m in (_PAGE_ALLOCS, _PAGE_FREES, _PAGE_ALLOC_FAILURES):
        m.remove_matching(pool=inst)


def pages_needed(total_tokens: int, page_size: int) -> int:
    return max(1, math.ceil(total_tokens / page_size))


class PageTable:
    """Ordered page-index list for one request; `pages[i]` backs logical
    positions [i*page_size, (i+1)*page_size)."""

    __slots__ = ("pages", "page_size", "length")

    def __init__(self, page_size: int):
        self.pages: list[int] = []
        self.page_size = page_size
        self.length = 0          # logical tokens written

    def padded(self, max_pages: int, fill: int = 0) -> list[int]:
        """Fixed-width row for the jitted step (missing entries point at
        page `fill`; they are masked by ctx_len and never read live)."""
        if len(self.pages) > max_pages:
            raise ValueError(
                f"request uses {len(self.pages)} pages > bucket width "
                f"{max_pages}")
        return self.pages + [fill] * (max_pages - len(self.pages))


class PagePool:
    """Free-list page allocator with admission control.

    Thread-safe: the scheduler thread allocates/frees while frontend
    threads ask `can_admit` for backpressure decisions.
    """

    def __init__(self, num_pages: int, page_size: int,
                 inst: str | None = None):
        if num_pages <= 0 or page_size <= 0:
            raise ValueError("num_pages and page_size must be positive")
        self.num_pages = num_pages
        self.page_size = page_size
        self._lock = threading.Lock()
        self._free = list(range(num_pages - 1, -1, -1))  # pop() -> low idx
        # stats — registry-backed series labeled per pool instance
        # (`inst` lets an Engine align the pool's label with its own)
        self.inst = inst if inst is not None else f"p{next(_pool_ids)}"
        self._m_allocs = _PAGE_ALLOCS.labels(pool=self.inst)
        self._m_frees = _PAGE_FREES.labels(pool=self.inst)
        self._m_alloc_failures = _PAGE_ALLOC_FAILURES.labels(
            pool=self.inst)
        # a dead pool's series leave the exposition (else a process
        # that churns pools grows its /metrics forever)
        weakref.finalize(self, _drop_pool_series, self.inst)

    # legacy counter attributes (PR-2 stats surface) now read the
    # registry series
    @property
    def alloc_count(self) -> int:
        return int(self._m_allocs.value)

    @property
    def free_count(self) -> int:
        return int(self._m_frees.value)

    @property
    def alloc_failures(self) -> int:
        return int(self._m_alloc_failures.value)

    # -- capacity ------------------------------------------------------
    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - self.free_pages

    @property
    def occupancy(self) -> float:
        return self.used_pages / self.num_pages

    def can_admit(self, total_tokens: int) -> bool:
        """Admission control: admit only when the request's WORST-CASE
        page demand (prompt + max new tokens) fits in the free list, so
        an admitted request can never deadlock the pool mid-decode."""
        return pages_needed(total_tokens, self.page_size) <= self.free_pages

    # -- alloc/free ----------------------------------------------------
    def alloc(self, n: int) -> list[int] | None:
        """n pages, or None (and no partial allocation) if unavailable."""
        with self._lock:
            if n > len(self._free):
                self._m_alloc_failures.inc()
                return None
            got = [self._free.pop() for _ in range(n)]
        self._m_allocs.inc(n)
        return got

    def alloc_table(self, total_tokens: int) -> PageTable | None:
        pages = self.alloc(pages_needed(total_tokens, self.page_size))
        if pages is None:
            return None
        t = PageTable(self.page_size)
        t.pages = pages
        return t

    def free(self, table_or_pages) -> None:
        pages = table_or_pages.pages if isinstance(table_or_pages, PageTable) \
            else list(table_or_pages)
        with self._lock:
            live = set(self._free)
            for p in pages:
                if not 0 <= p < self.num_pages:
                    raise ValueError(f"page {p} outside pool")
                if p in live:
                    raise ValueError(f"double free of page {p}")
            self._free.extend(sorted(pages, reverse=True))
        self._m_frees.inc(len(pages))
        if isinstance(table_or_pages, PageTable):
            table_or_pages.pages = []

    def stats(self) -> dict:
        with self._lock:
            free = len(self._free)
        return {"num_pages": self.num_pages, "page_size": self.page_size,
                "free_pages": free,
                "used_pages": self.num_pages - free,
                "occupancy": round(1 - free / self.num_pages, 4),
                "alloc_count": self.alloc_count,
                "free_count": self.free_count,
                "alloc_failures": self.alloc_failures}


def defrag_plan(pool: PagePool, tables: list[PageTable]) -> dict[int, int]:
    """Mapping old_page -> new_page that compacts all live pages into the
    lowest indices (stable: table order, then page order). The caller
    applies it to the device pools (serving/model.py
    `apply_defrag`) and this function rewrites tables + the free list.

    Safe only while the engine step is quiesced (the scheduler calls it
    between steps)."""
    live: list[int] = [p for t in tables for p in t.pages]
    if len(set(live)) != len(live):
        raise ValueError("page shared by two tables — corrupt state")
    mapping = {old: new for new, old in enumerate(live)}
    for t in tables:
        t.pages = [mapping[p] for p in t.pages]
    with pool._lock:
        pool._free = list(range(pool.num_pages - 1, len(live) - 1, -1))
    return mapping
