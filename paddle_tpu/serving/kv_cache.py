"""Paged KV-cache management: fixed-size pages in preallocated pools.

Ragged Paged Attention (PAPERS.md) shape: the KV history of every
in-flight request lives in fixed-size pages of ONE preallocated device
pool per engine (no per-request HBM allocs, no reshape/realloc as
sequences grow), addressed through a per-request page table. This module
is the host-side accountant:

  * `PagePool` — free-list allocator over `num_pages` page slots with
    capacity-based admission control (`can_admit`), occupancy stats, and
    REFCOUNTS: a page handed out once may be shared by several holders
    (page tables of requests with a common prompt prefix, plus the
    prefix cache itself — serving/prefix_cache.py); `free` decrements
    and the page only returns to the free list at zero;
  * `PageTable` — one request's ordered page list + logical length;
  * `defrag` — compacts live pages to the low end of the pool (device
    gather + table rewrite) so a long-running engine can shrink its pool
    or snapshot a dense prefix. Shared pages move once and every holder
    is rewritten through the same mapping.

The device arrays themselves ([L, P, ps, H, d] pools) are built by the
model adapter (serving/model.py); the pool hands out page INDICES only,
so the accountant stays synchronous and lock-cheap while all array work
remains inside the jitted step.
"""
from __future__ import annotations

import itertools
import math
import threading
import weakref

from ..observability import registry as _obs

__all__ = ["PagePool", "PageTable", "pages_needed", "defrag_plan"]

# page accounting on the process-wide registry (labeled per pool
# instance); PagePool.stats() keys are unchanged — they now READ these
# (always=True: the legacy counters must keep counting even when the
# telemetry kill switch is on)
_PAGE_ALLOCS = _obs.counter(
    "paddle_tpu_serving_pages_alloc_total",
    "pages handed out by the pool", ["pool"], always=True)
_PAGE_FREES = _obs.counter(
    "paddle_tpu_serving_pages_freed_total",
    "pages returned to the pool", ["pool"], always=True)
_PAGE_ALLOC_FAILURES = _obs.counter(
    "paddle_tpu_serving_page_alloc_failures_total",
    "allocations refused for lack of free pages", ["pool"],
    always=True)

_pool_ids = itertools.count()


def _drop_pool_series(inst: str):
    for m in (_PAGE_ALLOCS, _PAGE_FREES, _PAGE_ALLOC_FAILURES):
        m.remove_matching(pool=inst)


def pages_needed(total_tokens: int, page_size: int) -> int:
    return max(1, math.ceil(total_tokens / page_size))


class PageTable:
    """Ordered page-index list for one request; `pages[i]` backs logical
    positions [i*page_size, (i+1)*page_size)."""

    __slots__ = ("pages", "page_size", "length")

    def __init__(self, page_size: int):
        self.pages: list[int] = []
        self.page_size = page_size
        self.length = 0          # logical tokens written

    def padded(self, max_pages: int, fill: int = 0) -> list[int]:
        """Fixed-width row for the jitted step (missing entries point at
        page `fill`; they are masked by ctx_len and never read live)."""
        if len(self.pages) > max_pages:
            raise ValueError(
                f"request uses {len(self.pages)} pages > bucket width "
                f"{max_pages}")
        return self.pages + [fill] * (max_pages - len(self.pages))


class PagePool:
    """Free-list page allocator with admission control.

    Thread-safe: the scheduler thread allocates/frees while frontend
    threads ask `can_admit` for backpressure decisions.
    """

    def __init__(self, num_pages: int, page_size: int,
                 inst: str | None = None):
        if num_pages <= 0 or page_size <= 0:
            raise ValueError("num_pages and page_size must be positive")
        self.num_pages = num_pages
        self.page_size = page_size
        self._lock = threading.Lock()
        self._free = list(range(num_pages - 1, -1, -1))  # pop() -> low idx
        # refcount per ALLOCATED page (absent = free). alloc() starts a
        # page at 1; ref() adds holders (prefix-cache hits); free()
        # decrements and recycles only at zero — the invariant the
        # shared-prefix machinery rests on.
        self._refs: dict[int, int] = {}
        # stats — registry-backed series labeled per pool instance
        # (`inst` lets an Engine align the pool's label with its own)
        self.inst = inst if inst is not None else f"p{next(_pool_ids)}"
        self._m_allocs = _PAGE_ALLOCS.labels(pool=self.inst)
        self._m_frees = _PAGE_FREES.labels(pool=self.inst)
        self._m_alloc_failures = _PAGE_ALLOC_FAILURES.labels(
            pool=self.inst)
        # a dead pool's series leave the exposition (else a process
        # that churns pools grows its /metrics forever)
        weakref.finalize(self, _drop_pool_series, self.inst)

    # legacy counter attributes (PR-2 stats surface) now read the
    # registry series
    @property
    def alloc_count(self) -> int:
        return int(self._m_allocs.value)

    @property
    def free_count(self) -> int:
        return int(self._m_frees.value)

    @property
    def alloc_failures(self) -> int:
        return int(self._m_alloc_failures.value)

    # -- capacity ------------------------------------------------------
    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - self.free_pages

    @property
    def occupancy(self) -> float:
        return self.used_pages / self.num_pages

    def can_admit(self, total_tokens: int) -> bool:
        """Admission control: admit only when the request's WORST-CASE
        page demand (prompt + max new tokens) fits in the free list, so
        an admitted request can never deadlock the pool mid-decode."""
        return pages_needed(total_tokens, self.page_size) <= self.free_pages

    @property
    def shared_pages(self) -> int:
        """Pages currently held by more than one holder."""
        with self._lock:
            return sum(1 for c in self._refs.values() if c > 1)

    def is_shared(self, page: int) -> bool:
        with self._lock:
            return self._refs.get(page, 0) > 1

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._refs.get(page, 0)

    # -- alloc/free ----------------------------------------------------
    def alloc(self, n: int) -> list[int] | None:
        """n pages, or None (and no partial allocation) if unavailable."""
        with self._lock:
            if n > len(self._free):
                self._m_alloc_failures.inc()
                return None
            got = [self._free.pop() for _ in range(n)]
            for p in got:
                self._refs[p] = 1
        self._m_allocs.inc(n)
        return got

    def ref(self, pages) -> None:
        """Add one holder to each (already-allocated) page — the
        prefix-cache hit path: a request admitted onto cached pages
        shares them until its own `free`."""
        with self._lock:
            for p in pages:
                if p not in self._refs:
                    raise ValueError(f"ref of free page {p}")
            for p in pages:
                self._refs[p] += 1

    def alloc_table(self, total_tokens: int) -> PageTable | None:
        pages = self.alloc(pages_needed(total_tokens, self.page_size))
        if pages is None:
            return None
        t = PageTable(self.page_size)
        t.pages = pages
        return t

    def free(self, table_or_pages) -> None:
        """Drop one holder per page; pages whose refcount reaches zero
        return to the free list (freed-page metric counts only those)."""
        pages = table_or_pages.pages if isinstance(table_or_pages, PageTable) \
            else list(table_or_pages)
        with self._lock:
            for p in pages:
                if not 0 <= p < self.num_pages:
                    raise ValueError(f"page {p} outside pool")
                if p not in self._refs:
                    raise ValueError(f"double free of page {p}")
            recycled = []
            for p in pages:
                c = self._refs[p] - 1
                if c:
                    self._refs[p] = c
                else:
                    del self._refs[p]
                    recycled.append(p)
            self._free.extend(sorted(recycled, reverse=True))
        self._m_frees.inc(len(recycled))
        if isinstance(table_or_pages, PageTable):
            table_or_pages.pages = []

    def stats(self) -> dict:
        with self._lock:
            free = len(self._free)
            shared = sum(1 for c in self._refs.values() if c > 1)
        return {"num_pages": self.num_pages, "page_size": self.page_size,
                "free_pages": free,
                "used_pages": self.num_pages - free,
                "occupancy": round(1 - free / self.num_pages, 4),
                "shared_pages": shared,
                "alloc_count": self.alloc_count,
                "free_count": self.free_count,
                "alloc_failures": self.alloc_failures}


def defrag_plan(pool: PagePool, tables: list[PageTable],
                extra_pages=()) -> dict[int, int]:
    """Mapping old_page -> new_page that compacts all live pages into the
    lowest indices (stable: table order, then page order, first holder
    wins for a shared page). The caller applies it to the device pools
    (serving/model.py `apply_defrag`) and this function rewrites tables,
    refcounts, and the free list. `extra_pages` names live pages held
    outside any table (the prefix cache's runs) — the caller must remap
    its own holders with the returned mapping.

    Safe only while the engine step is quiesced (the scheduler calls it
    between steps)."""
    order: list[int] = []
    seen: set[int] = set()
    for p in itertools.chain((p for t in tables for p in t.pages),
                             extra_pages):
        if p not in seen:
            seen.add(p)
            order.append(p)
    mapping = {old: new for new, old in enumerate(order)}
    for t in tables:
        t.pages = [mapping[p] for p in t.pages]
    with pool._lock:
        if seen != set(pool._refs):
            missing = sorted(set(pool._refs) - seen)
            raise ValueError(
                f"defrag plan covers {len(seen)} pages but the pool has "
                f"{len(pool._refs)} allocated (unaccounted: "
                f"{missing[:8]}) — pass every holder's pages")
        pool._refs = {mapping[p]: c for p, c in pool._refs.items()}
        pool._free = list(range(pool.num_pages - 1, len(order) - 1, -1))
    return mapping
