"""Continuous batcher: request lifecycle + slot scheduling policy.

The serving engine decodes a FIXED-SHAPE slot batch every step (so there
is exactly one compiled decode program per (slots, pages) bucket); this
module is the policy layer that decides, between steps, which requests
occupy those slots:

  * admission — FIFO from the queue into free slots, gated by the page
    pool: a request is admitted only when its WORST-CASE page demand
    (prompt + max_new_tokens) is allocatable, so an admitted request can
    never run out of pages mid-decode (no mid-flight OOM, no deadlock);
  * prefill-then-decode — a newly admitted request is prefilled once
    (its prompt KV written to its pages, first token sampled), then
    joins the in-flight decode batch;
  * eviction — EOS or max_new_tokens completes a request; a missed
    deadline preempts it (partial output returned, ALL its pages freed
    back to the pool that step);
  * backpressure — the bounded queue rejects submits past `max_queue`.

Pure host logic over kv_cache.PagePool — no jax imports — so the policy
is unit-testable without a model (tests/test_serving.py).
"""
from __future__ import annotations

import itertools
import threading
import time
import weakref
from collections import deque

import numpy as np

from ..observability import flight as _flight, registry as _obs
from .kv_cache import PagePool

__all__ = ["Request", "Scheduler", "QueueFull"]

# lifecycle counters on the process-wide registry, labeled per scheduler
# instance; Scheduler.stats() keys are unchanged — they now READ these
# (always=True: legacy surface must keep counting under the telemetry
# kill switch)
_ADMITTED = _obs.counter(
    "paddle_tpu_serving_admitted_total",
    "requests admitted into a slot", ["inst"], always=True)
_COMPLETED = _obs.counter(
    "paddle_tpu_serving_completed_total",
    "requests finished with status done", ["inst"], always=True)
_PREEMPTED = _obs.counter(
    "paddle_tpu_serving_preempted_total",
    "running requests preempted by a deadline", ["inst"], always=True)
_REJECTED = _obs.counter(
    "paddle_tpu_serving_rejected_total",
    "submits rejected by queue backpressure", ["inst"], always=True)
_EVICTIONS = _obs.counter(
    "paddle_tpu_serving_evictions_total",
    "requests leaving the slot table / queue, by reason",
    ["inst", "reason"])

_sched_ids = itertools.count()


def _drop_sched_series(inst: str):
    for m in (_ADMITTED, _COMPLETED, _PREEMPTED, _REJECTED, _EVICTIONS):
        m.remove_matching(inst=inst)


class QueueFull(RuntimeError):
    """Backpressure: the engine's admission queue is at capacity."""


_req_ids = itertools.count(1)


class Request:
    """One generation request, queued -> running -> finished.

    status: queued | running | done | deadline | error | cancelled.
    `deadline` is an absolute time.monotonic() stamp (None = no bound).
    """

    def __init__(self, prompt, max_new_tokens: int, deadline: float | None
                 = None, eos_id: int | None = None):
        self.id = next(_req_ids)
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.deadline = deadline
        self.eos_id = eos_id
        self.trace_id: str | None = None  # set by Engine.submit
        self.generated: list[int] = []
        self.status = "queued"
        self.error: str | None = None
        self.table = None            # PageTable while admitted
        self.slot: int | None = None
        self.submitted_at = time.monotonic()
        self.started_at: float | None = None
        self.finished_at: float | None = None
        self._done = threading.Event()

    # -- results -------------------------------------------------------
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def result(self, timeout: float | None = None) -> np.ndarray:
        """Generated tokens (possibly partial on deadline preemption).
        Raises on error status; TimeoutError if not finished in time."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.id} not finished")
        if self.status == "error":
            raise RuntimeError(self.error or "request failed")
        return np.asarray(self.generated, np.int32)

    @property
    def total_tokens(self) -> int:
        return int(self.prompt.size) + self.max_new_tokens

    @property
    def position(self) -> int:
        """Position of the LAST generated token (its KV is written by the
        next decode step)."""
        return int(self.prompt.size) + len(self.generated) - 1

    def latency(self) -> float | None:
        if self.finished_at is None:
            return None
        return self.finished_at - self.submitted_at


class Scheduler:
    """Slot table + queue; the engine calls the methods between steps."""

    def __init__(self, pool: PagePool, num_slots: int,
                 max_seq_len: int, max_queue: int = 256,
                 now=time.monotonic, inst: str | None = None):
        self.pool = pool
        self.num_slots = num_slots
        self.max_seq_len = max_seq_len
        self.max_queue = max_queue
        self.now = now
        self.slots: list[Request | None] = [None] * num_slots
        self.queue: deque[Request] = deque()
        self._lock = threading.Lock()
        # counters (engine /stats) — registry-backed, labeled per
        # instance (`inst` lets the Engine align the label with its own)
        self.inst = inst if inst is not None else f"s{next(_sched_ids)}"
        self._m_admitted = _ADMITTED.labels(inst=self.inst)
        self._m_completed = _COMPLETED.labels(inst=self.inst)
        self._m_preempted = _PREEMPTED.labels(inst=self.inst)
        self._m_rejected = _REJECTED.labels(inst=self.inst)
        # a dead scheduler's series leave the exposition
        weakref.finalize(self, _drop_sched_series, self.inst)

    # legacy counter attributes (PR-2 stats surface) now read the
    # registry series
    @property
    def admitted(self) -> int:
        return int(self._m_admitted.value)

    @property
    def completed(self) -> int:
        return int(self._m_completed.value)

    @property
    def preemptions(self) -> int:
        return int(self._m_preempted.value)

    @property
    def rejected(self) -> int:
        return int(self._m_rejected.value)

    # -- queue side (frontend threads) ---------------------------------
    def submit(self, req: Request) -> Request:
        if req.total_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt+max_new_tokens = {req.total_tokens} exceeds "
                f"max_seq_len {self.max_seq_len}")
        with self._lock:
            if len(self.queue) >= self.max_queue:
                self._m_rejected.inc()
                _flight.record("serving", "reject",
                               trace_id=req.trace_id, inst=self.inst,
                               request=req.id, reason="queue_full",
                               queue_depth=len(self.queue))
                raise QueueFull(
                    f"queue at capacity ({self.max_queue}); retry later")
            self.queue.append(req)
        return req

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self.queue)

    def active_requests(self) -> list[Request]:
        return [r for r in self.slots if r is not None]

    @property
    def idle(self) -> bool:
        return self.queue_depth == 0 and not self.active_requests()

    # -- step side (scheduler thread) ----------------------------------
    def expire_deadlines(self) -> list[Request]:
        """Finish every queued or running request whose deadline passed;
        running ones are PREEMPTED: their pages all go back to the pool
        now, their partial output stands."""
        t = self.now()
        hit: list[Request] = []
        with self._lock:
            kept = deque()
            for r in self.queue:
                if r.deadline is not None and t > r.deadline:
                    hit.append(r)
                else:
                    kept.append(r)
            self.queue = kept
        for i, r in enumerate(self.slots):
            if r is not None and r.deadline is not None and t > r.deadline:
                self.slots[i] = None
                self._m_preempted.inc()
                hit.append(r)
        for r in hit:
            self._finish(r, "deadline")
        return hit

    def admit(self) -> list[Request]:
        """FIFO-admit queued requests into free slots while the pool can
        cover their worst case; returns the newly admitted requests (the
        engine prefills them). Head-of-line blocking is intentional —
        FIFO fairness over utilization."""
        out: list[Request] = []
        for i in range(self.num_slots):
            if self.slots[i] is not None:
                continue
            with self._lock:
                if not self.queue:
                    break
                head = self.queue[0]
                table = self.pool.alloc_table(head.total_tokens)
                if table is None:
                    # the scheduler DECIDED to block admission: the
                    # reason belongs in the flight record, it is what a
                    # postmortem reader needs to explain a deep queue
                    _flight.record("serving", "admit_blocked",
                                   trace_id=head.trace_id,
                                   inst=self.inst, request=head.id,
                                   reason="pool_full",
                                   need_tokens=head.total_tokens)
                    break            # pool full: wait for evictions
                self.queue.popleft()
                # slot assignment inside the SAME critical section as
                # the dequeue: a postmortem snapshot reading queue +
                # slots under this lock must never catch a request in
                # neither place
                head.table = table
                head.slot = i
                head.status = "running"
                head.started_at = self.now()
                self.slots[i] = head
            self._m_admitted.inc()
            _flight.record("serving", "admit", trace_id=head.trace_id,
                           inst=self.inst, request=head.id, slot=i,
                           pages=len(table.pages))
            out.append(head)
        return out

    def record_token(self, req: Request, token: int) -> bool:
        """Append a sampled token; returns True when the request is now
        finished (EOS or max_new_tokens) and has been evicted."""
        req.generated.append(int(token))
        req.table.length = req.position + 1
        if (req.eos_id is not None and token == req.eos_id) \
                or len(req.generated) >= req.max_new_tokens:
            self.evict(req, "done")
            return True
        return False

    def cancel(self, req: Request) -> bool:
        """Abandon a queued or running request (its pages return to the
        pool; partial output stands). False if already finished. The
        caller must hold the engine step lock so this never races a
        decode step."""
        with self._lock:
            try:
                self.queue.remove(req)
            except ValueError:
                pass
        if req.done():
            return False
        self.evict(req, "cancelled")
        return True

    def evict(self, req: Request, status: str):
        if req.slot is not None and self.slots[req.slot] is req:
            self.slots[req.slot] = None
        self._finish(req, status)
        if status == "done":
            self._m_completed.inc()

    def _finish(self, req: Request, status: str):
        if req.table is not None:
            self.pool.free(req.table)
            req.table = None
        req.status = status
        req.finished_at = self.now()
        _EVICTIONS.labels(inst=self.inst, reason=status).inc()
        _flight.record("serving", "evict", trace_id=req.trace_id,
                       inst=self.inst, request=req.id, reason=status,
                       generated=len(req.generated))
        req._done.set()

    def stats(self) -> dict:
        return {"queue_depth": self.queue_depth,
                "active_slots": len(self.active_requests()),
                "num_slots": self.num_slots,
                "admitted": self.admitted,
                "completed": self.completed,
                "preemptions": self.preemptions,
                "rejected": self.rejected}
