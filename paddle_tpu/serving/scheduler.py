"""Continuous batcher: request lifecycle + slot scheduling policy.

The serving engine decodes a FIXED-SHAPE slot batch every step (so there
is exactly one compiled decode program per (slots, pages) bucket); this
module is the policy layer that decides, between steps, which requests
occupy those slots:

  * admission — priority-ordered from the queue into free slots, gated
    by the page pool: a request is admitted only when its WORST-CASE
    page demand (prompt + max_new_tokens) is allocatable, so an admitted
    request can never run out of pages mid-decode (no mid-flight OOM,
    no deadlock). Requests carry a priority TIER (0 = highest); within
    a tier, order is FIFO with head-of-line blocking, and a waiting
    request's effective tier rises one step per `aging_s` seconds so a
    sustained high-tier flood can never starve the low tiers;
  * quotas — per-tenant token buckets charge each ENQUEUED submit its
    worst-case token demand (a submit that bounces off a full queue is
    not charged); an over-quota tenant is rejected at submit
    (`QuotaExceeded`, a `QueueFull` subclass so frontends reply
    "rejected", not a transport error);
  * shedding — when the queue is at capacity, a submit sheds the
    lowest-effective-priority queued request instead of rejecting a
    HIGHER-priority newcomer (status "shed"); equal-or-lower newcomers
    are rejected as before (backpressure semantics unchanged);
  * prefill-then-decode — a newly admitted request is prefilled once
    (its prompt KV written to its pages, first token sampled), then
    joins the in-flight decode batch;
  * eviction — EOS or max_new_tokens completes a request; a missed
    deadline preempts it (partial output returned, ALL its pages freed
    back to the pool that step). A deadline that lapses while the
    request is still QUEUED counts separately (`expired_in_queue`):
    admission-control tuning must distinguish "never ran" from
    "ran out of time mid-decode".

Pure host logic over kv_cache.PagePool — no jax imports — so the policy
is unit-testable without a model (tests/test_serving.py,
tests/test_slo_harness.py).
"""
from __future__ import annotations

import itertools
import threading
import time
import weakref
from collections import deque

import numpy as np

from ..observability import (flight as _flight, meter as _meter,
                             registry as _obs)
from .kv_cache import PagePool, PageTable, pages_needed

__all__ = ["Request", "Scheduler", "QueueFull", "QuotaExceeded",
           "TokenBucket"]

# lifecycle counters on the process-wide registry, labeled per scheduler
# instance; Scheduler.stats() keys are unchanged — they now READ these
# (always=True: legacy surface must keep counting under the telemetry
# kill switch)
_ADMITTED = _obs.counter(
    "paddle_tpu_serving_admitted_total",
    "requests admitted into a slot", ["inst"], always=True)
_COMPLETED = _obs.counter(
    "paddle_tpu_serving_completed_total",
    "requests finished with status done", ["inst"], always=True)
_PREEMPTED = _obs.counter(
    "paddle_tpu_serving_preempted_total",
    "running requests preempted by a deadline", ["inst"], always=True)
_REJECTED = _obs.counter(
    "paddle_tpu_serving_rejected_total",
    "submits rejected by queue backpressure", ["inst"], always=True)
_EVICTIONS = _obs.counter(
    "paddle_tpu_serving_evictions_total",
    "requests leaving the slot table / queue, by reason",
    ["inst", "reason"])
_EXPIRED_QUEUE = _obs.counter(
    "paddle_tpu_serving_expired_in_queue_total",
    "queued requests whose deadline lapsed before they ever ran "
    "(distinct from running-request preemptions)", ["inst"],
    always=True)
_SHED = _obs.counter(
    "paddle_tpu_serving_shed_total",
    "queued requests shed to make room for a higher-priority submit",
    ["inst"], always=True)
_QUOTA_REJECTED = _obs.counter(
    "paddle_tpu_serving_quota_rejected_total",
    "submits rejected by a tenant token-bucket quota", ["inst"],
    always=True)

_sched_ids = itertools.count()


def _drop_sched_series(inst: str):
    for m in (_ADMITTED, _COMPLETED, _PREEMPTED, _REJECTED, _EVICTIONS,
              _EXPIRED_QUEUE, _SHED, _QUOTA_REJECTED):
        m.remove_matching(inst=inst)


class QueueFull(RuntimeError):
    """Backpressure: the engine's admission queue is at capacity."""


class QuotaExceeded(QueueFull):
    """The tenant's token bucket cannot cover this request right now.
    Subclasses QueueFull so every existing backpressure handler (the
    frontend's "rejected" reply, client retry policies) treats it as
    load shedding, never a transport error."""


class TokenBucket:
    """Per-tenant admission quota: `rate` tokens/sec refill up to
    `burst`. Charged the request's WORST-CASE token demand at submit
    (prompt + max_new_tokens) — the same worst-case currency the page
    pool admits on. Clock injectable for deterministic tests."""

    __slots__ = ("rate", "burst", "_tokens", "_t", "_now")

    def __init__(self, rate: float, burst: float | None = None,
                 now=time.monotonic):
        if rate <= 0:
            raise ValueError("rate must be positive")
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else rate)
        self._tokens = self.burst
        self._now = now
        self._t = now()

    def available(self) -> float:
        t = self._now()
        self._tokens = min(self.burst,
                           self._tokens + (t - self._t) * self.rate)
        self._t = t
        return self._tokens

    def take(self, n: float) -> bool:
        if self.available() < n:
            return False
        self._tokens -= n
        return True


_req_ids = itertools.count(1)


class Request:
    """One generation request, queued -> running -> finished.

    status: queued | running | done | deadline | error | cancelled |
    shed. `deadline` is an absolute time.monotonic() stamp (None = no
    bound). `priority` is a tier (0 = highest; default 1); `tenant`
    names the quota bucket the request is charged against.
    """

    def __init__(self, prompt, max_new_tokens: int, deadline: float | None
                 = None, eos_id: int | None = None, priority: int = 1,
                 tenant: str = "default", temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0,
                 seed: int | None = None):
        self.id = next(_req_ids)
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.deadline = deadline
        self.eos_id = eos_id
        self.priority = max(0, int(priority))
        self.tenant = str(tenant)
        # stochastic decode (serving/sampling.py): temperature 0 =
        # greedy; seed None = keyed by the request identity (engine)
        self.temperature = float(temperature)
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0")
        self.top_k = max(0, int(top_k))
        self.top_p = float(top_p)
        if not 0 < self.top_p <= 1:
            raise ValueError("top_p must be in (0, 1]")
        self.seed = None if seed is None else int(seed)
        # shared-prefix admission (serving/prefix_cache.py): the match
        # this request was admitted onto, and — for a full-prompt
        # bootstrap — the pending (src, dst) copy-on-write pair whose
        # src ref is pinned until the engine's device copy
        self.prefix_match = None
        self.prefix_cow: tuple[int, int] | None = None
        self.trace_id: str | None = None  # set by Engine.submit
        self.generated: list[int] = []
        self.status = "queued"
        self.error: str | None = None
        self.table = None            # PageTable while admitted
        self.slot: int | None = None
        self.submitted_at = time.monotonic()
        self.started_at: float | None = None
        self.finished_at: float | None = None
        # stamped on the SCHEDULER's clock (injectable in tests):
        # _queued_at anchors priority aging; first/last_token_at are the
        # SLO surface (TTFT, inter-token latency) the load generator
        # reads (serving/loadgen.py)
        self._queued_at: float | None = None
        self.first_token_at: float | None = None
        self.last_token_at: float | None = None
        self._finished = False       # set once, under the scheduler lock
        self._done = threading.Event()
        # token-progress condition for streaming consumers: notified on
        # every recorded token and on finish. A leaf lock — holders
        # never take the scheduler or engine step lock under it.
        self._progress = threading.Condition()

    # -- results -------------------------------------------------------
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def result(self, timeout: float | None = None) -> np.ndarray:
        """Generated tokens (possibly partial on deadline preemption).
        Raises on error status; TimeoutError if not finished in time."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.id} not finished")
        if self.status == "error":
            raise RuntimeError(self.error or "request failed")
        return np.asarray(self.generated, np.int32)

    def _notify_progress(self):
        with self._progress:
            self._progress.notify_all()

    def next_tokens(self, start: int, timeout: float | None = None) \
            -> tuple[list[int], bool]:
        """Block until tokens beyond index `start` exist or the request
        finished; returns (new_tokens, done). The streaming frontends
        poll this from their handler threads — `generated` is only ever
        appended, so the slice is safe to read concurrently (a token
        appended between wakeup and slice just arrives early)."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        with self._progress:
            while len(self.generated) <= start \
                    and not self._done.is_set():
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    break
                self._progress.wait(remaining)
        return list(self.generated[start:]), self._done.is_set()

    @property
    def total_tokens(self) -> int:
        return int(self.prompt.size) + self.max_new_tokens

    @property
    def position(self) -> int:
        """Position of the LAST generated token (its KV is written by the
        next decode step)."""
        return int(self.prompt.size) + len(self.generated) - 1

    def latency(self) -> float | None:
        if self.finished_at is None:
            return None
        return self.finished_at - self.submitted_at

    def ttft(self) -> float | None:
        """Time to first token (submit -> first sampled token)."""
        if self.first_token_at is None or self._queued_at is None:
            return None
        return self.first_token_at - self._queued_at

    def inter_token(self) -> float | None:
        """Mean inter-token latency over this request's decode."""
        if (self.first_token_at is None or self.last_token_at is None
                or len(self.generated) < 2):
            return None
        return (self.last_token_at - self.first_token_at) \
            / (len(self.generated) - 1)


class Scheduler:
    """Slot table + queue; the engine calls the methods between steps."""

    def __init__(self, pool: PagePool, num_slots: int,
                 max_seq_len: int, max_queue: int = 256,
                 now=time.monotonic, inst: str | None = None,
                 aging_s: float = 30.0):
        self.pool = pool
        self.num_slots = num_slots
        self.max_seq_len = max_seq_len
        self.max_queue = max_queue
        self.now = now
        # a queued request's effective tier rises one step per aging_s
        # seconds waited, so a sustained high-tier flood can never
        # starve the low tiers (<=0 disables aging)
        self.aging_s = aging_s
        self.slots: list[Request | None] = [None] * num_slots
        self.queue: deque[Request] = deque()
        self.quotas: dict[str, TokenBucket] = {}
        # shared-prefix admission: installed by the Engine when
        # PADDLE_TPU_PREFIX_CACHE_PAGES > 0 (serving/prefix_cache.py)
        self.prefix_cache = None
        # graceful drain: True = admit nothing new, finish what's here
        # (the router stops routing to a draining replica; docs/SERVING.md)
        self.draining = False
        self._lock = threading.Lock()
        # counters (engine /stats) — registry-backed, labeled per
        # instance (`inst` lets the Engine align the label with its own)
        self.inst = inst if inst is not None else f"s{next(_sched_ids)}"
        self._m_admitted = _ADMITTED.labels(inst=self.inst)
        self._m_completed = _COMPLETED.labels(inst=self.inst)
        self._m_preempted = _PREEMPTED.labels(inst=self.inst)
        self._m_rejected = _REJECTED.labels(inst=self.inst)
        self._m_expired_queue = _EXPIRED_QUEUE.labels(inst=self.inst)
        self._m_shed = _SHED.labels(inst=self.inst)
        self._m_quota_rejected = _QUOTA_REJECTED.labels(inst=self.inst)
        # a dead scheduler's series leave the exposition
        weakref.finalize(self, _drop_sched_series, self.inst)

    # legacy counter attributes (PR-2 stats surface) now read the
    # registry series
    @property
    def admitted(self) -> int:
        return int(self._m_admitted.value)

    @property
    def completed(self) -> int:
        return int(self._m_completed.value)

    @property
    def preemptions(self) -> int:
        return int(self._m_preempted.value)

    @property
    def rejected(self) -> int:
        return int(self._m_rejected.value)

    @property
    def expired_in_queue(self) -> int:
        return int(self._m_expired_queue.value)

    @property
    def shed(self) -> int:
        return int(self._m_shed.value)

    @property
    def quota_rejected(self) -> int:
        return int(self._m_quota_rejected.value)

    # -- admission policy ----------------------------------------------
    def set_tenant_quota(self, tenant: str, tokens_per_sec: float,
                         burst: float | None = None):
        """Install (or replace) a token-bucket quota for `tenant`; each
        submit is charged its worst-case token demand. Tenants without
        a bucket are unthrottled."""
        self.quotas[str(tenant)] = TokenBucket(
            tokens_per_sec, burst, now=self.now)

    def effective_priority(self, req: Request, t: float | None = None) \
            -> int:
        """The request's tier after aging: one step toward 0 per
        `aging_s` seconds waited in the queue."""
        if self.aging_s <= 0 or req._queued_at is None:
            return req.priority
        t = self.now() if t is None else t
        return max(0, req.priority
                   - int((t - req._queued_at) // self.aging_s))

    # -- queue side (frontend threads) ---------------------------------
    def submit(self, req: Request) -> Request:
        if req.total_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt+max_new_tokens = {req.total_tokens} exceeds "
                f"max_seq_len {self.max_seq_len}")
        victim: Request | None = None
        with self._lock:
            if self.draining:
                # drain semantics: every in-flight/queued request
                # finishes, nothing new is admitted — the standard
                # backpressure reply ("rejected") tells well-behaved
                # clients and the router to go elsewhere
                self._m_rejected.inc()
                _meter.METER.note_outcome(req.tenant, req.priority,
                                          "rejected")
                _flight.record("serving", "reject",
                               trace_id=req.trace_id, inst=self.inst,
                               request=req.id, reason="draining")
                raise QueueFull("draining: not admitting new requests")
            t = self.now()
            req._queued_at = t
            bucket = self.quotas.get(req.tenant)
            # quota is CHECKED here but only CHARGED once the request
            # is actually enqueued (below): a submit that bounces off a
            # full queue must not drain the tenant's bucket, or retries
            # against backpressure turn into phantom quota rejections
            if bucket is not None \
                    and bucket.available() < req.total_tokens:
                self._m_quota_rejected.inc()
                _meter.METER.note_outcome(req.tenant, req.priority,
                                          "quota")
                _flight.record("serving", "reject",
                               trace_id=req.trace_id, inst=self.inst,
                               request=req.id, reason="quota",
                               tenant=req.tenant,
                               need_tokens=req.total_tokens)
                raise QuotaExceeded(
                    f"tenant {req.tenant!r} over quota "
                    f"({req.total_tokens} tokens); retry later")
            if len(self.queue) >= self.max_queue:
                # load-shed by priority: a saturated queue drops its
                # lowest-effective-priority entry for a strictly
                # higher-priority newcomer; otherwise the newcomer is
                # rejected (plain backpressure, unchanged semantics)
                worst = max(self.queue,
                            key=lambda r: (self.effective_priority(r, t),
                                           r.id), default=None)
                if worst is not None \
                        and self.effective_priority(worst, t) \
                        > self.effective_priority(req, t):
                    self.queue.remove(worst)
                    victim = worst
                else:
                    self._m_rejected.inc()
                    _meter.METER.note_outcome(req.tenant, req.priority,
                                              "rejected")
                    _flight.record("serving", "reject",
                                   trace_id=req.trace_id, inst=self.inst,
                                   request=req.id, reason="queue_full",
                                   queue_depth=len(self.queue))
                    raise QueueFull(
                        f"queue at capacity ({self.max_queue}); "
                        f"retry later")
            if bucket is not None:
                # cannot fail: available() was checked under this same
                # lock and no other submit ran since
                bucket.take(req.total_tokens)
            self.queue.append(req)
        if victim is not None:
            self._m_shed.inc()
            _flight.record("serving", "shed", trace_id=victim.trace_id,
                           inst=self.inst, request=victim.id,
                           tier=victim.priority, tenant=victim.tenant,
                           for_request=req.id, for_tier=req.priority)
            self._finish(victim, "shed")
        return req

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self.queue)

    def active_requests(self) -> list[Request]:
        return [r for r in self.slots if r is not None]

    @property
    def idle(self) -> bool:
        return self.queue_depth == 0 and not self.active_requests()

    # -- step side (scheduler thread) ----------------------------------
    def expire_deadlines(self) -> list[Request]:
        """Finish every queued or running request whose deadline passed;
        running ones are PREEMPTED: their pages all go back to the pool
        now, their partial output stands. Queued ones count under the
        distinct `expired_in_queue` key — they never held a slot, and
        admission-control tuning must tell the two apart."""
        t = self.now()
        expired_queued: list[Request] = []
        hit: list[Request] = []
        with self._lock:
            kept = deque()
            for r in self.queue:
                if r.deadline is not None and t > r.deadline:
                    expired_queued.append(r)
                else:
                    kept.append(r)
            self.queue = kept
        for r in expired_queued:
            self._m_expired_queue.inc()
            self._finish(r, "deadline", reason="expired_in_queue")
            hit.append(r)
        for i, r in enumerate(self.slots):
            if r is not None and r.deadline is not None and t > r.deadline:
                self.slots[i] = None
                self._m_preempted.inc()
                self._finish(r, "deadline")
                hit.append(r)
        return hit

    def _pick_head(self, t: float) -> Request | None:
        """The queue's admission head: best (aged) tier, then FIFO.
        Head-of-line blocking applies to THIS request — a pool-blocked
        head is never bypassed by a smaller lower-priority request
        (fairness over utilization, as in the original FIFO)."""
        return min(self.queue,
                   key=lambda r: (self.effective_priority(r, t), r.id),
                   default=None)

    def _alloc_for(self, req: Request):
        """The request's PageTable: a prefix-cache hit charges only the
        unshared tail (+1 COW page when the whole prompt matched — the
        bootstrap decode rewrites the last prompt position); a miss (or
        no cache) pays the full worst case, as always. Lookup refs are
        either installed in the table (retired with it) or released
        here when the tail allocation fails; a pool-blocked allocation
        retries once after shedding cold cache-only pages, so the cache
        can never starve live admissions."""
        ps = self.pool.page_size
        cache = self.prefix_cache
        match = cache.lookup(req.prompt) if cache is not None else None
        total = pages_needed(req.total_tokens, ps)
        matched = 0 if match is None else len(match.pages)
        need = total - matched + (1 if match is not None and match.full
                                  else 0)
        pages = self.pool.alloc(need)
        if pages is None and cache is not None and cache.reclaim(need):
            pages = self.pool.alloc(need)
        if pages is None:
            if match is not None:
                self.pool.free(match.pages)   # release the lookup refs
            return None
        table = PageTable(ps)
        if match is None:
            table.pages = pages
        elif match.full:
            table.pages = match.pages[:-1] + [pages[0]] + pages[1:]
            req.prefix_cow = (match.pages[-1], pages[0])
            req.prefix_match = match
        else:
            table.pages = match.pages + pages
            req.prefix_match = match
        return table

    def admit(self) -> list[Request]:
        """Admit queued requests into free slots in effective-priority
        order (tier after aging, FIFO within a tier) while the pool can
        cover their worst case; returns the newly admitted requests
        (the engine prefills them)."""
        out: list[Request] = []
        for i in range(self.num_slots):
            if self.slots[i] is not None:
                continue
            with self._lock:
                if not self.queue:
                    break
                head = self._pick_head(self.now())
                table = self._alloc_for(head)
                if table is None:
                    # the scheduler DECIDED to block admission: the
                    # reason belongs in the flight record, it is what a
                    # postmortem reader needs to explain a deep queue
                    _flight.record("serving", "admit_blocked",
                                   trace_id=head.trace_id,
                                   inst=self.inst, request=head.id,
                                   reason="pool_full",
                                   need_tokens=head.total_tokens)
                    break            # pool full: wait for evictions
                self.queue.remove(head)
                # slot assignment inside the SAME critical section as
                # the dequeue: a postmortem snapshot reading queue +
                # slots under this lock must never catch a request in
                # neither place
                head.table = table
                head.slot = i
                head.status = "running"
                head.started_at = self.now()
                self.slots[i] = head
            self._m_admitted.inc()
            _flight.record("serving", "admit", trace_id=head.trace_id,
                           inst=self.inst, request=head.id, slot=i,
                           pages=len(table.pages),
                           cached_pages=0 if head.prefix_match is None
                           else len(head.prefix_match.pages),
                           tier=head.priority, tenant=head.tenant)
            out.append(head)
        return out

    def record_token(self, req: Request, token: int) -> bool:
        """Append a sampled token; returns True when the request is now
        finished (EOS or max_new_tokens) and has been evicted."""
        req.generated.append(int(token))
        req.last_token_at = self.now()
        if req.first_token_at is None:
            req.first_token_at = req.last_token_at
        req.table.length = req.position + 1
        if (req.eos_id is not None and token == req.eos_id) \
                or len(req.generated) >= req.max_new_tokens:
            self.evict(req, "done")
            return True
        req._notify_progress()       # streaming consumers wake per token
        return False

    def cancel(self, req: Request) -> bool:
        """Abandon a queued or running request (its pages return to the
        pool; partial output stands). False if already finished. The
        caller must hold the engine step lock so this never races a
        decode step."""
        with self._lock:
            try:
                self.queue.remove(req)
            except ValueError:
                pass
        if req.done():
            return False
        # evict is idempotent: a concurrent shed that wins the race
        # makes this a no-op and cancel reports False
        return self.evict(req, "cancelled")

    def evict(self, req: Request, status: str) -> bool:
        if req.slot is not None and self.slots[req.slot] is req:
            self.slots[req.slot] = None
        finished = self._finish(req, status)
        if finished and status == "done":
            self._m_completed.inc()
        return finished

    def _finish(self, req: Request, status: str,
                reason: str | None = None) -> bool:
        """`status` is the request's public lifecycle state; `reason`
        (default: the status) is the finer-grained eviction label —
        e.g. a queued deadline lapse finishes with status "deadline"
        but reason "expired_in_queue". Idempotent: the shed path runs
        on the submitting thread OUTSIDE the engine step lock, so it
        can race a concurrent cancel — first caller wins, the loser
        is a no-op (returns False)."""
        with self._lock:
            if req._finished:
                return False
            req._finished = True
        now = self.now()
        if req.prefix_cow is not None:
            # bootstrap admission that died before the engine's COW
            # copy: drop the pinned lookup ref on the source page
            self.pool.free([req.prefix_cow[0]])
            req.prefix_cow = None
        pages = 0
        if req.table is not None:
            if status == "done" and self.prefix_cache is not None:
                # retirement insert: publish prompt+generated pages so
                # a follow-up turn reuses this conversation's KV. The
                # LAST generated token's KV is never written (decode
                # writes token t's KV while generating t+1), hence the
                # total-1 page ceiling.
                total = int(req.prompt.size) + len(req.generated)
                n = min((total - 1) // self.pool.page_size,
                        len(req.table.pages))
                if n > 0:
                    toks = np.concatenate(
                        [req.prompt,
                         np.asarray(req.generated, np.int32)])
                    self.prefix_cache.insert(
                        toks[:n * self.pool.page_size],
                        req.table.pages[:n])
            pages = len(req.table.pages)   # before free() recycles them
            self.pool.free(req.table)
            req.table = None
        req.status = status
        req.finished_at = now
        # per-tenant accounting: what this request consumed reaching its
        # terminal state — queue wait, generated tokens, and the HBM it
        # held (pages × slot residency)
        queue_s = 0.0
        if req._queued_at is not None:
            queue_s = max(0.0, (req.started_at or now) - req._queued_at)
        kv_page_s = 0.0
        if req.started_at is not None:
            kv_page_s = pages * max(0.0, now - req.started_at)
        _meter.METER.note_outcome(req.tenant, req.priority,
                                  reason or status,
                                  tokens_out=len(req.generated),
                                  queue_s=queue_s, kv_page_s=kv_page_s)
        _EVICTIONS.labels(inst=self.inst,
                          reason=reason or status).inc()
        _flight.record("serving", "evict", trace_id=req.trace_id,
                       inst=self.inst, request=req.id,
                       reason=reason or status,
                       generated=len(req.generated))
        req._done.set()
        req._notify_progress()
        return True

    def drain(self):
        """Stop admitting (submit raises QueueFull); queued + running
        requests finish normally. One-way for this scheduler's life —
        a drained replica is retired or respawned, never un-drained."""
        with self._lock:
            self.draining = True
        _flight.record("serving", "drain", inst=self.inst,
                       queue_depth=len(self.queue))

    def stats(self) -> dict:
        return {"queue_depth": self.queue_depth,
                "active_slots": len(self.active_requests()),
                "num_slots": self.num_slots,
                "draining": self.draining,
                "admitted": self.admitted,
                "completed": self.completed,
                "preemptions": self.preemptions,
                "rejected": self.rejected,
                "expired_in_queue": self.expired_in_queue,
                "shed": self.shed,
                "quota_rejected": self.quota_rejected}
