"""Replayable stochastic decode: Philox-keyed temperature/top-k/top-p.

The sampler lives INSIDE the jitted decode body (engine.py closes over
`sample_tokens`), with every sampling parameter a slot-wide traced array
— so stochastic decode keeps the one-compile-per-(slots,pages)-bucket
contract, and a greedy request (temperature 0) still gets the literal
`argmax` it always did, bit-for-bit.

Randomness is the counter-based Philox4x32-10 generator implemented
directly in uint32 lane math (no uint64 — runs with jax x64 disabled),
keyed by the request's 64-bit seed and COUNTED by the decode step:

    uniform = philox(key=(seed_lo, seed_hi), counter=(step, 0, 0, 0))

One uniform per (seed, step) feeds an inverse-CDF draw over the
temperature-scaled, top-k/top-p-filtered distribution. Because the
stream is a pure function of (seed, step) — no RNG state anywhere — a
replayed request emits the identical token sequence: transport retries,
router failover to a survivor replica (the router pins the same wire
request id, so the same derived seed), and same-seed loadgen reruns all
reproduce token-for-token (docs/SERVING.md replay contract; the chaos
drill in tests/test_router.py pins it).

`philox_uniform_host` is the numpy mirror of the device stream — the
unit tests pin the two against each other so the device implementation
can never drift silently.
"""
from __future__ import annotations

import hashlib

import numpy as np

__all__ = ["SamplingParams", "sample_tokens", "seed_to_key",
           "derive_seed", "philox_uniform_host"]

# Philox4x32 round/bump constants (Salmon et al., SC'11)
_M0 = 0xD2511F53
_M1 = 0xCD9E8D57
_W0 = 0x9E3779B9
_W1 = 0xBB67AE85


class SamplingParams:
    """Validated wire/request sampling knobs. temperature == 0 means
    greedy (top_k/top_p ignored); seed None means "derive from the
    request id" (frontend.py), which is exactly what makes replays
    byte-identical without the client ever choosing a seed."""

    __slots__ = ("temperature", "top_k", "top_p", "seed")

    def __init__(self, temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: int | None = None):
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = None if seed is None else int(seed)
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0")
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0 (0 = disabled)")
        if not 0 < self.top_p <= 1:
            raise ValueError("top_p must be in (0, 1]")

    @classmethod
    def from_request(cls, req: dict) -> "SamplingParams":
        return cls(temperature=req.get("temperature", 0.0),
                   top_k=req.get("top_k", 0),
                   top_p=req.get("top_p", 1.0),
                   seed=req.get("seed"))

    def to_request(self, out: dict) -> dict:
        """Write non-default knobs into a wire request dict."""
        if self.temperature > 0:
            out["temperature"] = self.temperature
        if self.top_k > 0:
            out["top_k"] = self.top_k
        if self.top_p < 1.0:
            out["top_p"] = self.top_p
        if self.seed is not None:
            out["seed"] = self.seed
        return out


def derive_seed(request_id) -> int:
    """Stable 64-bit seed from a request identity. The router relays
    the ORIGINAL wire request id on failover (exactly-once relay), so
    every replica derives the same seed for the same logical request."""
    h = hashlib.blake2b(str(request_id).encode(), digest_size=8)
    return int.from_bytes(h.digest(), "big")


def seed_to_key(seed: int) -> np.ndarray:
    """64-bit seed -> uint32[2] Philox key (lo, hi)."""
    s = int(seed) & 0xFFFFFFFFFFFFFFFF
    return np.array([s & 0xFFFFFFFF, s >> 32], np.uint32)


def _mulhilo(xp, a, b):
    """Full 32x32->64 product in uint32 lanes: (hi, lo)."""
    m16 = xp.uint32(0xFFFF)
    al, ah = a & m16, a >> xp.uint32(16)
    bl, bh = b & m16, b >> xp.uint32(16)
    lo = (a * b).astype(xp.uint32)       # wraps mod 2^32
    t = ah * bl + ((al * bl) >> xp.uint32(16))
    t2 = al * bh + (t & m16)
    hi = ah * bh + (t >> xp.uint32(16)) + (t2 >> xp.uint32(16))
    return hi, lo


def _philox4(xp, k0, k1, c0, c1, c2, c3):
    """Ten Philox4x32 rounds; all args uint32 arrays (broadcastable)."""
    for _ in range(10):
        hi0, lo0 = _mulhilo(xp, xp.uint32(_M0), c0)
        hi1, lo1 = _mulhilo(xp, xp.uint32(_M1), c2)
        c0, c1, c2, c3 = hi1 ^ c1 ^ k0, lo1, hi0 ^ c3 ^ k1, lo0
        k0 = k0 + xp.uint32(_W0)
        k1 = k1 + xp.uint32(_W1)
    return c0


def _uniform(xp, seeds, steps):
    """One float32 uniform in [0, 1) per lane from key=(seed lo, hi),
    counter=(step, 0, 0, 0). seeds [..., 2] uint32, steps [...] int."""
    step = steps.astype(xp.uint32)
    zero = xp.zeros_like(step)
    x = _philox4(xp, seeds[..., 0], seeds[..., 1], step, zero, zero,
                 zero)
    # top 24 bits -> [0, 1): exact in float32
    return (x >> xp.uint32(8)).astype(xp.float32) \
        * xp.float32(1.0 / (1 << 24))


def philox_uniform_host(seed: int, step: int) -> float:
    """Numpy mirror of the device stream (tests pin device == host)."""
    key = seed_to_key(seed)
    with np.errstate(over="ignore"):
        u = _uniform(np, key.reshape(1, 2),
                     np.asarray([step], np.int64))
    return float(u[0])


def sample_tokens(logits, temps, topks, topps, seeds, steps):
    """One token per slot, inside the jitted decode body.

    logits [S, V] f32; temps/topps [S] f32; topks/steps [S] i32;
    seeds [S, 2] u32. Returns [S] i32.

    temperature 0 -> plain argmax (the pre-existing greedy path,
    selected per slot so greedy and sampled requests share one decode
    program). temperature > 0: scale, keep the top-k logits and the
    top-p nucleus (the crossing token included), then one inverse-CDF
    draw with the slot's (seed, step) uniform.
    """
    import jax
    import jax.numpy as jnp

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    V = logits.shape[-1]
    scaled = logits / jnp.where(temps > 0, temps, 1.0)[:, None]
    order = jnp.argsort(-scaled, axis=-1)            # descending, stable
    sl = jnp.take_along_axis(scaled, order, axis=-1)
    probs = jax.nn.softmax(sl, axis=-1)
    k_eff = jnp.where(topks > 0, jnp.clip(topks, 1, V), V)
    rank = jnp.arange(V, dtype=jnp.int32)[None, :]
    csum = jnp.cumsum(probs, axis=-1)
    # nucleus: keep while the mass BEFORE a token is < top_p, which
    # always includes the crossing token (and rank 0)
    keep = (rank < k_eff[:, None]) \
        & ((csum - probs) < topps[:, None])
    w = jnp.where(keep, probs, 0.0)
    cdf = jnp.cumsum(w, axis=-1)
    u = _uniform(jnp, seeds, steps)
    target = u * cdf[:, -1]
    pick = jnp.sum((cdf <= target[:, None]).astype(jnp.int32), axis=-1)
    pick = jnp.clip(pick, 0, V - 1)   # u*total rounding up to total
    sampled = jnp.take_along_axis(order, pick[:, None],
                                  axis=-1)[:, 0].astype(jnp.int32)
    return jnp.where(temps > 0, sampled, greedy)
