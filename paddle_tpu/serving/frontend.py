"""Network front-end for the serving engine.

Reuses the PR-1 fault-tolerant PS wire format
(distributed/fleet/runtime/rpc.py: data-only frames, CRC, optional
PADDLE_PS_SECRET HMAC handshake, client retry with stable request ids,
server-side dedup) — so a retried `generate` that raced a connection
drop is served from the dedup cache instead of decoding twice.

Ops:
  {"op": "generate", "prompt": <int ndarray>, "max_new_tokens": n,
   "deadline": seconds|None, "timeout": seconds,
   "priority": tier (0 = highest, default 1), "tenant": str,
   "temperature": f (0 = greedy), "top_k": n, "top_p": f,
   "seed": int|absent, "stream": bool}
      -> {"status": "done"|"deadline"|"timeout"|"rejected"|"shed"|
                    "error",
          "tokens": <int32 ndarray>, ...}
    With "stream": true the server pushes F_STREAM frames
    {"tokens": <int32 ndarray>, "index": i} as tokens are decoded,
    then the normal final reply (whose "tokens" is the AUTHORITATIVE
    full list — stream frames are progress, the final frame is the
    dedup-cached result a retry sees). Streaming is what makes TTFT
    observable on the wire and lets a router detect a replica wedged
    mid-generation by the inter-frame gap (docs/SERVING.md).
    Backpressure AND tenant-quota rejections reply status="rejected";
    a queued request shed for a higher-priority submit replies
    status="shed" (docs/SERVING.md admission control).
    Blocks the connection's handler thread until the request finishes
    (the engine keeps batching others meanwhile). Backpressure surfaces
    as status="rejected" — a well-formed reply, not a transport error,
    so the client's retry loop does not hammer a saturated server. A
    handler timeout CANCELS the request (slot+pages freed, partial
    tokens returned) before replying, because the reply is dedup-cached
    and a still-running request would decode tokens no retry could
    ever fetch.
  {"op": "stats"} -> engine.stats()   (queue depth, p50/p99, tokens/s,
    pool occupancy, preemptions, compile counters)
  {"op": "metrics"} -> Prometheus text over the process-wide telemetry
    registry (docs/OBSERVABILITY.md) — the serving scrape point
  {"op": "debug_dump", "write": bool} -> a full postmortem bundle
    (metrics + trace ring + flight rings + in-flight requests,
    docs/DEBUGGING.md), optionally persisted into the server's own
    PADDLE_TPU_DEBUG_DIR (never a wire-chosen path)
  {"op": "drain"} -> {"draining": true, ...}  Graceful removal: stop
    admitting (submits reply "rejected"), finish everything queued or
    running; `ping`/`stats` report draining=true so a router routes
    around this replica. {"wait": true, "timeout": s} blocks until the
    queue ran dry (reply carries "idle").
  {"op": "ping"}  -> {"ok": true, "draining": bool, "queue_depth": n,
    "active_slots": n, "occupancy": f, "model_version": v,
    "tokens_per_s_per_chip": f, "mfu": f}  — the router's health/load
    probe (cheap: no latency sorting); model_version is the published
    version the engine serves (docs/ONLINE_LEARNING.md); the rate/MFU
    keys are the perf plane's live per-chip view (docs/OBSERVABILITY.md)
  {"op": "adopt_version", "version": v} -> {"adopted": v, ...}
    Zero-downtime hot swap to published version v from the replica's
    CONFIGURED publish root (publish_root= / PADDLE_TPU_PUBLISH_DIR —
    the wire never chooses a path): two-phase warm start, in-flight
    generations finish on the old weights, new prefills see v.

In-process use (tests, co-located workers) needs none of this — call
`Engine.submit` / `Engine.generate` directly.
"""
from __future__ import annotations

import socketserver
import threading
import time

import numpy as np

from ..distributed.fleet.runtime.rpc import (RpcClient, RpcServerState,
                                             serve_connection)
from ..observability import (debug as _debug, registry as _obs,
                             tracing as _tracing)
from .sampling import SamplingParams, derive_seed
from .scheduler import QueueFull

__all__ = ["ServingServer", "ServingClient"]


class ServingServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    READ_OPS = frozenset({"stats", "ping", "metrics", "debug_dump"})

    def __init__(self, engine, endpoint: str = "127.0.0.1:0",
                 secret: str | None = None,
                 default_timeout: float = 120.0,
                 publish_root: str | None = None):
        import os
        self.engine = engine
        self.default_timeout = default_timeout
        # the publish root adopt_version loads from is SERVER
        # configuration (arg or PADDLE_TPU_PUBLISH_DIR), never a
        # wire-chosen path — same rule as debug_dump's destination
        self.publish_root = publish_root if publish_root is not None \
            else (os.environ.get("PADDLE_TPU_PUBLISH_DIR") or None)
        # expose_req_id: the wire request id seeds stochastic sampling
        # when the client sent none — a transport retry AND a router
        # failover both relay the ORIGINAL id, so a replayed request
        # derives the same seed and emits the identical token sequence
        # (serving/sampling.py replay contract)
        self._rpc = RpcServerState(read_ops=self.READ_OPS, secret=secret,
                                   expose_req_id=True)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                serve_connection(self.request, outer._dispatch,
                                 outer._rpc)

        host, port = endpoint.rsplit(":", 1)
        super().__init__((host, int(port)), Handler)
        self.endpoint = f"{host}:{self.server_address[1]}"
        self._thread: threading.Thread | None = None
        self._conns: set = set()     # live handler sockets (kill())

    # connection tracking so kill() can sever live streams the way a
    # process death would (chaos drills; docs/SERVING.md)
    def process_request(self, request, client_address):
        self._conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        self._conns.discard(request)
        super().shutdown_request(request)

    def start(self):
        self.engine.start()
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True,
                                        name="serving-frontend")
        self._thread.start()
        return self

    def stop(self):
        self.shutdown()
        self.server_close()
        self.engine.stop()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def kill(self):
        """Crash, don't drain (chaos drills): close the listener AND
        every live connection — in-flight streamed replies die
        mid-frame, exactly what a replica process death looks like to
        the router — and halt the serve thread. The engine is left to
        the caller (a real kill takes it down with the process)."""
        import socket as _socket
        self.shutdown()
        self.server_close()
        for s in list(self._conns):
            try:
                s.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        self._conns.clear()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _dispatch(self, req: dict):
        op = req.get("op")
        rid = req.pop("_req_id", None) if isinstance(req, dict) \
            else None
        if op == "ping":
            # the router's combined health + load probe: queue depth and
            # occupancy WITHOUT engine.stats()'s latency sort, so a
            # sub-second ping cadence costs nothing measurable
            # (perf_rates is two deque copies, same class of cheap)
            sched = self.engine.scheduler
            rates = self.engine.perf_rates() \
                if hasattr(self.engine, "perf_rates") else {}
            return {"ok": True, "draining": bool(sched.draining),
                    "queue_depth": sched.queue_depth,
                    "active_slots": len(sched.active_requests()),
                    "occupancy": float(self.engine.pool.occupancy),
                    "model_version":
                        int(getattr(self.engine, "model_version", 0)),
                    "tokens_per_s_per_chip":
                        rates.get("tokens_per_s_per_chip", 0.0),
                    "mfu": rates.get("mfu", 0.0)}
        if op == "adopt_version":
            # online-learning hot swap (PR 12): two-phase warm start
            # from the SERVER-configured publish root — the wire names
            # only the version number, never a path. The router's
            # staggered rollout drives this verb replica by replica.
            if not self.publish_root:
                raise ValueError(
                    "no publish root configured on this replica "
                    "(publish_root= or PADDLE_TPU_PUBLISH_DIR)")
            version = int(req["version"])
            self.engine.warm_start(self.publish_root, step=version,
                                   version=version)
            return {"adopted": version,
                    "model_version": int(self.engine.model_version)}
        if op == "drain":
            self.engine.drain()
            idle = None
            if req.get("wait"):
                deadline = time.monotonic() \
                    + float(req.get("timeout") or self.default_timeout)
                while not self.engine.scheduler.idle \
                        and time.monotonic() < deadline:
                    time.sleep(0.01)
                idle = self.engine.scheduler.idle
            rep = {"draining": True,
                   "queue_depth": self.engine.scheduler.queue_depth}
            if idle is not None:
                rep["idle"] = bool(idle)
            return rep
        if op == "stats":
            return self.engine.stats()
        if op == "metrics":
            # Prometheus exposition over the whole process registry —
            # scrape point for the serving tier (docs/OBSERVABILITY.md)
            return _obs.prometheus_text()
        if op == "debug_dump":
            # full postmortem bundle on demand (docs/DEBUGGING.md):
            # metrics + trace ring + flight rings + in-flight request
            # table, persisted to the server-side PADDLE_TPU_DEBUG_DIR
            # (never a wire-chosen path) and returned over the wire
            return _debug.dump_verb(req)
        if op == "generate":
            prompt = np.asarray(req["prompt"], np.int32)
            # serve_connection already opened a span rooted at the wire
            # trace id; this child span marks the frontend tier and the
            # engine.submit inside it stamps the id onto the request
            with _tracing.span("frontend.generate",
                               prompt_len=int(prompt.size)) as sp:
                try:
                    sp_params = SamplingParams.from_request(req)
                    seed = sp_params.seed
                    if seed is None and sp_params.temperature > 0 \
                            and rid:
                        # no client seed: key the Philox stream by the
                        # STABLE wire id (retries/failovers relay it)
                        seed = derive_seed(rid)
                    h = self.engine.submit(
                        prompt, int(req.get("max_new_tokens", 16)),
                        deadline=req.get("deadline"),
                        priority=int(req.get("priority", 1)),
                        tenant=str(req.get("tenant", "default")),
                        temperature=sp_params.temperature,
                        top_k=sp_params.top_k, top_p=sp_params.top_p,
                        seed=seed)
                except QueueFull as e:
                    sp.attrs["status"] = "rejected"
                    return {"status": "rejected", "error": str(e)}
                except ValueError as e:
                    sp.attrs["status"] = "error"
                    return {"status": "error", "error": str(e)}
                if req.get("stream"):
                    # generator reply: serve_connection pushes each
                    # yielded frame as F_STREAM, then the returned dict
                    # as the final (dedup-cached) reply
                    sp.attrs["status"] = "stream"
                    return self._stream_result(req, h)
                out = self._await_result(req, h)
                sp.attrs["status"] = out.get("status")
                return out

        raise ValueError(f"unknown op {op!r}")

    def _await_result(self, req: dict, h):
        timeout = float(req.get("timeout") or self.default_timeout)
        if not h.wait(timeout):
            return self._timeout_reply(h, timeout)
        return self._finished_reply(h)

    def _timeout_reply(self, h, timeout: float):
        # the reply gets dedup-cached, so the request must not
        # keep decoding tokens nobody can ever retrieve: cancel
        # it (frees slot+pages) and return the partial output.
        # cancel() can lose the race to completion — fall
        # through to the finished result in that case.
        if self.engine.cancel(h):
            return {"status": "timeout",
                    "tokens": np.asarray(h.generated, np.int32),
                    "error": f"not finished within {timeout}s; "
                             "request cancelled",
                    "trace_id": h.trace_id}
        return self._finished_reply(h)

    def _finished_reply(self, h):
        # trace_id rides every reply so callers (loadgen exemplars,
        # operators) can pull the assembled cross-process trace from
        # the telemetry collector by id
        if h.status == "error":
            return {"status": "error", "error": h.error or "failed",
                    "trace_id": h.trace_id}
        return {"status": h.status,
                "tokens": np.asarray(h.generated, np.int32),
                "prompt_len": int(h.prompt.size),
                "latency_ms": round((h.latency() or 0.0) * 1e3, 3),
                "trace_id": h.trace_id}

    def _stream_result(self, req: dict, h):
        """Push tokens as they decode, finish with the normal reply.
        The final frame's "tokens" is the authoritative full list —
        stream frames are incremental progress (TTFT/ITL on the wire,
        mid-generation stall detection for the router). The span opens
        at first next(), not at dispatch — a returned generator
        outlives the dispatch call, and the span must cover the
        stream's real duration and final status."""
        with _tracing.span("frontend.stream", request=h.id,
                           prompt_len=int(h.prompt.size)) as sp:
            out = yield from self._stream_body(req, h)
            sp.attrs["status"] = out.get("status")
            return out

    def _stream_body(self, req: dict, h):
        timeout = float(req.get("timeout") or self.default_timeout)
        deadline = time.monotonic() + timeout
        sent = 0
        try:
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return self._timeout_reply(h, timeout)
                toks, done = h.next_tokens(sent, timeout=remaining)
                if toks:
                    yield {"tokens": np.asarray(toks, np.int32),
                           "index": sent}
                    sent += len(toks)
                if done:
                    return self._finished_reply(h)
        finally:
            # GeneratorExit: the client connection died mid-stream —
            # nobody can ever fetch this request's reply (it is NOT in
            # the dedup cache yet), so stop burning decode steps on it
            if not h.done():
                self.engine.cancel(h)


class ServingClient:
    """Thin client over RpcClient (retry/deadline/dedup semantics).

    Thread-safe and truly concurrent since the multiplexed transport
    (PR 11): calls and streamed generates from many threads interleave
    over the pooled channels (PADDLE_TPU_RPC_POOL_SIZE sockets,
    replies matched by request id), so one shared ServingClient no
    longer serializes callers — a long streamed generate does not
    head-of-line block a concurrent ping."""

    def __init__(self, endpoint: str, secret: str | None = None,
                 timeout: float | None = None):
        self._rpc = RpcClient(endpoint, secret=secret,
                              timeout=timeout if timeout is not None
                              else 150.0)

    def ping(self) -> bool:
        rep = self._rpc.call({"op": "ping"})
        return bool(rep.get("ok")) if isinstance(rep, dict) \
            else bool(rep)

    def ping_info(self) -> dict:
        """Full health/load probe: draining flag, queue depth, active
        slots, page occupancy (what the router's least-loaded dispatch
        and health state machine read)."""
        rep = self._rpc.call({"op": "ping"})
        return rep if isinstance(rep, dict) else {"ok": bool(rep)}

    def drain(self, wait: bool = False,
              timeout: float | None = None) -> dict:
        """Graceful removal: stop admitting, finish the queue.
        ``wait=True`` blocks until the server ran dry (reply carries
        "idle")."""
        req = {"op": "drain", "wait": bool(wait)}
        if timeout is not None:
            req["timeout"] = float(timeout)
        wire_t = (timeout or 120.0) + 30.0
        return self._rpc.call(req, timeout=wire_t, deadline=wire_t + 30)

    def stats(self) -> dict:
        return self._rpc.call({"op": "stats"})

    def adopt_version(self, version: int,
                      timeout: float = 120.0) -> dict:
        """Hot-swap the replica to published ``version`` (loaded from
        ITS configured publish root). Mutating + dedup-cached: a
        retried adopt replays the recorded reply, never a second
        device upload."""
        return self._rpc.call({"op": "adopt_version",
                               "version": int(version)},
                              timeout=timeout, deadline=timeout + 30)

    def metrics(self) -> str:
        """Prometheus text from the serving process's registry."""
        return self._rpc.call({"op": "metrics"})

    def debug_dump(self, write: bool = True) -> dict:
        """Pull a full postmortem bundle from a (healthy or wedged)
        server: metrics, trace ring, flight rings, env, in-flight
        requests. ``write=True`` also persists it server-side into the
        server's own PADDLE_TPU_DEBUG_DIR (the destination is never
        wire-controlled; docs/DEBUGGING.md)."""
        return self._rpc.call({"op": "debug_dump",
                               "write": bool(write)})

    def generate(self, prompt, max_new_tokens: int = 16,
                 deadline: float | None = None,
                 timeout: float = 120.0, priority: int = 1,
                 tenant: str = "default", session: str | None = None,
                 stream: bool = False, on_token=None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: int | None = None) -> dict:
        """One generation round-trip. ``stream=True`` asks the server
        to push tokens as they decode; ``on_token(tokens, index)`` is
        called per pushed frame on this thread and delivers every token
        EXACTLY ONCE in order (a mid-stream transport retry re-streams
        from index 0 — the client forwards only the unseen tail, the
        same dedup the router's failover relay applies). The returned
        final reply's "tokens" is the authoritative full list (a
        dedup-hit retry replays no frames — on_token may see nothing).
        ``session`` is the router's affinity key (ignored by a bare
        ServingServer)."""
        req = {"op": "generate",
               "prompt": np.asarray(prompt, np.int32),
               "max_new_tokens": int(max_new_tokens),
               "deadline": deadline, "timeout": timeout,
               "priority": int(priority), "tenant": str(tenant)}
        # only non-default sampling knobs go on the wire (validated
        # here so a bad temperature fails client-side, not mid-stream)
        SamplingParams(temperature, top_k, top_p, seed).to_request(req)
        if session is not None:
            req["session"] = str(session)
        if not stream:
            return self._rpc.call(req, timeout=timeout + 30.0,
                                  deadline=timeout + 60.0)
        req["stream"] = True
        seen = 0

        def _on(frame):
            nonlocal seen
            if on_token is None or not isinstance(frame, dict) \
                    or frame.get("tokens") is None:
                return
            toks = [int(t) for t in
                    np.asarray(frame["tokens"]).ravel()]
            new = int(frame.get("index", 0)) + len(toks) - seen
            if new > 0:
                on_token(toks[len(toks) - new:], seen)
                seen += new

        # streamed: the per-attempt timeout bounds the INTER-FRAME gap,
        # the deadline bounds the whole call
        return self._rpc.call(req, timeout=timeout + 30.0,
                              deadline=timeout + 60.0, on_stream=_on)

    def close(self):
        self._rpc.close()
