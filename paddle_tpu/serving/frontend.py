"""Network front-end for the serving engine.

Reuses the PR-1 fault-tolerant PS wire format
(distributed/fleet/runtime/rpc.py: data-only frames, CRC, optional
PADDLE_PS_SECRET HMAC handshake, client retry with stable request ids,
server-side dedup) — so a retried `generate` that raced a connection
drop is served from the dedup cache instead of decoding twice.

Ops:
  {"op": "generate", "prompt": <int ndarray>, "max_new_tokens": n,
   "deadline": seconds|None, "timeout": seconds,
   "priority": tier (0 = highest, default 1), "tenant": str}
      -> {"status": "done"|"deadline"|"timeout"|"rejected"|"shed"|
                    "error",
          "tokens": <int32 ndarray>, ...}
    Backpressure AND tenant-quota rejections reply status="rejected";
    a queued request shed for a higher-priority submit replies
    status="shed" (docs/SERVING.md admission control).
    Blocks the connection's handler thread until the request finishes
    (the engine keeps batching others meanwhile). Backpressure surfaces
    as status="rejected" — a well-formed reply, not a transport error,
    so the client's retry loop does not hammer a saturated server. A
    handler timeout CANCELS the request (slot+pages freed, partial
    tokens returned) before replying, because the reply is dedup-cached
    and a still-running request would decode tokens no retry could
    ever fetch.
  {"op": "stats"} -> engine.stats()   (queue depth, p50/p99, tokens/s,
    pool occupancy, preemptions, compile counters)
  {"op": "metrics"} -> Prometheus text over the process-wide telemetry
    registry (docs/OBSERVABILITY.md) — the serving scrape point
  {"op": "debug_dump", "write": bool} -> a full postmortem bundle
    (metrics + trace ring + flight rings + in-flight requests,
    docs/DEBUGGING.md), optionally persisted into the server's own
    PADDLE_TPU_DEBUG_DIR (never a wire-chosen path)
  {"op": "ping"}  -> True

In-process use (tests, co-located workers) needs none of this — call
`Engine.submit` / `Engine.generate` directly.
"""
from __future__ import annotations

import socketserver
import threading

import numpy as np

from ..distributed.fleet.runtime.rpc import (RpcClient, RpcServerState,
                                             serve_connection)
from ..observability import (debug as _debug, registry as _obs,
                             tracing as _tracing)
from .scheduler import QueueFull

__all__ = ["ServingServer", "ServingClient"]


class ServingServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    READ_OPS = frozenset({"stats", "ping", "metrics", "debug_dump"})

    def __init__(self, engine, endpoint: str = "127.0.0.1:0",
                 secret: str | None = None,
                 default_timeout: float = 120.0):
        self.engine = engine
        self.default_timeout = default_timeout
        self._rpc = RpcServerState(read_ops=self.READ_OPS, secret=secret)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                serve_connection(self.request, outer._dispatch,
                                 outer._rpc)

        host, port = endpoint.rsplit(":", 1)
        super().__init__((host, int(port)), Handler)
        self.endpoint = f"{host}:{self.server_address[1]}"
        self._thread: threading.Thread | None = None

    def start(self):
        self.engine.start()
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True,
                                        name="serving-frontend")
        self._thread.start()
        return self

    def stop(self):
        self.shutdown()
        self.server_close()
        self.engine.stop()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _dispatch(self, req: dict):
        op = req.get("op")
        if op == "ping":
            return True
        if op == "stats":
            return self.engine.stats()
        if op == "metrics":
            # Prometheus exposition over the whole process registry —
            # scrape point for the serving tier (docs/OBSERVABILITY.md)
            return _obs.prometheus_text()
        if op == "debug_dump":
            # full postmortem bundle on demand (docs/DEBUGGING.md):
            # metrics + trace ring + flight rings + in-flight request
            # table, persisted to the server-side PADDLE_TPU_DEBUG_DIR
            # (never a wire-chosen path) and returned over the wire
            return _debug.dump_verb(req)
        if op == "generate":
            prompt = np.asarray(req["prompt"], np.int32)
            # serve_connection already opened a span rooted at the wire
            # trace id; this child span marks the frontend tier and the
            # engine.submit inside it stamps the id onto the request
            with _tracing.span("frontend.generate",
                               prompt_len=int(prompt.size)) as sp:
                try:
                    h = self.engine.submit(
                        prompt, int(req.get("max_new_tokens", 16)),
                        deadline=req.get("deadline"),
                        priority=int(req.get("priority", 1)),
                        tenant=str(req.get("tenant", "default")))
                except QueueFull as e:
                    sp.attrs["status"] = "rejected"
                    return {"status": "rejected", "error": str(e)}
                except ValueError as e:
                    sp.attrs["status"] = "error"
                    return {"status": "error", "error": str(e)}
                out = self._await_result(req, h)
                sp.attrs["status"] = out.get("status")
                return out

        raise ValueError(f"unknown op {op!r}")

    def _await_result(self, req: dict, h):
        timeout = float(req.get("timeout") or self.default_timeout)
        if not h.wait(timeout):
            # the reply gets dedup-cached, so the request must not
            # keep decoding tokens nobody can ever retrieve: cancel
            # it (frees slot+pages) and return the partial output.
            # cancel() can lose the race to completion — fall
            # through to the finished result in that case.
            if self.engine.cancel(h):
                return {"status": "timeout",
                        "tokens": np.asarray(h.generated, np.int32),
                        "error": f"not finished within {timeout}s; "
                                 "request cancelled"}
        if h.status == "error":
            return {"status": "error", "error": h.error or "failed"}
        return {"status": h.status,
                "tokens": np.asarray(h.generated, np.int32),
                "prompt_len": int(h.prompt.size),
                "latency_ms": round((h.latency() or 0.0) * 1e3, 3)}


class ServingClient:
    """Thin client over RpcClient (retry/deadline/dedup semantics)."""

    def __init__(self, endpoint: str, secret: str | None = None,
                 timeout: float | None = None):
        self._rpc = RpcClient(endpoint, secret=secret,
                              timeout=timeout if timeout is not None
                              else 150.0)

    def ping(self) -> bool:
        return bool(self._rpc.call({"op": "ping"}))

    def stats(self) -> dict:
        return self._rpc.call({"op": "stats"})

    def metrics(self) -> str:
        """Prometheus text from the serving process's registry."""
        return self._rpc.call({"op": "metrics"})

    def debug_dump(self, write: bool = True) -> dict:
        """Pull a full postmortem bundle from a (healthy or wedged)
        server: metrics, trace ring, flight rings, env, in-flight
        requests. ``write=True`` also persists it server-side into the
        server's own PADDLE_TPU_DEBUG_DIR (the destination is never
        wire-controlled; docs/DEBUGGING.md)."""
        return self._rpc.call({"op": "debug_dump",
                               "write": bool(write)})

    def generate(self, prompt, max_new_tokens: int = 16,
                 deadline: float | None = None,
                 timeout: float = 120.0, priority: int = 1,
                 tenant: str = "default") -> dict:
        return self._rpc.call(
            {"op": "generate", "prompt": np.asarray(prompt, np.int32),
             "max_new_tokens": int(max_new_tokens),
             "deadline": deadline, "timeout": timeout,
             "priority": int(priority), "tenant": str(tenant)},
            timeout=timeout + 30.0, deadline=timeout + 60.0)

    def close(self):
        self._rpc.close()
