"""Serving engine: continuous-batching decode loop over a paged cache.

One Engine = one model + one preallocated page pool + one fixed-shape
slot batch. Each scheduler iteration (`step()`):

  1. expire deadlines (queued + running; preempted requests free ALL
     their pages back to the pool immediately);
  2. admit queued requests into free slots (capacity-gated FIFO), run
     one jitted PREFILL per admission (prompt KV -> pages, first token);
  3. run ONE jitted DECODE over the whole slot batch (inactive slots
     ride along pointed at the trash page) and record each slot's token,
     evicting on EOS / max_new_tokens.

Compilation contract: decode is one program per (slots, pages) bucket —
an Engine has exactly one such bucket, so one compile for its lifetime;
prefill compiles once per prompt-length bucket (page-aligned power-of-
two padding). `stats()["compiles"]` counts actual traces (the counter
increments inside the traced function, which only runs at trace time) —
tests assert at-most-one per bucket.

Threading: `submit()` may be called from any number of frontend threads
(bounded queue = backpressure); the step loop runs either on the
caller's thread (`run_until_idle`, deterministic tests) or on the
engine's own scheduler thread (`start()`).
"""
from __future__ import annotations

import itertools
import math
import os
import threading
import time
import weakref
from collections import defaultdict, deque

import numpy as np

from ..distributed.fleet.runtime import fault_injection as _fi
from ..observability import (debug as _debug, flight as _flight,
                             meter as _meter, perf as _perf,
                             registry as _obs, tracing as _tracing,
                             watchdog as _watchdog)
from .kv_cache import PagePool, defrag_plan
from .prefix_cache import PrefixCache
from .sampling import sample_tokens, seed_to_key
from .scheduler import QueueFull, Request, Scheduler

__all__ = ["Engine", "QueueFull"]

# engine telemetry (labeled per engine instance; the scheduler/pool
# series share the same label value). Hot-path writes are counter incs
# and histogram observes around the jitted calls — host-side
# microseconds against millisecond steps (<2% bar held by the
# metrics_overhead microbench).
_REQS = _obs.counter(
    "paddle_tpu_serving_requests_total",
    "requests submitted to the engine", ["engine"])
_TOKENS = _obs.counter(
    "paddle_tpu_serving_tokens_total",
    "tokens generated (prefill first tokens + decode)", ["engine"],
    always=True)  # backs stats()["tokens_generated"]
_STEPS = _obs.counter(
    "paddle_tpu_serving_steps_total",
    "decode scheduler iterations that ran the slot batch", ["engine"],
    always=True)  # backs stats()["steps"]
_COMPILES = _obs.counter(
    "paddle_tpu_serving_compiles_total",
    "XLA trace events per program bucket (trace-time side effect)",
    ["engine", "bucket"])
_DECODE_H = _obs.histogram(
    "paddle_tpu_serving_decode_step_seconds",
    "wall time of one jitted decode over the slot batch", ["engine"])
_PREFILL_H = _obs.histogram(
    "paddle_tpu_serving_prefill_seconds",
    "wall time of one jitted prefill (admission)", ["engine"])
_LATENCY_H = _obs.histogram(
    "paddle_tpu_serving_request_latency_seconds",
    "submit-to-finish latency per request", ["engine"])
_QUEUE_DEPTH = _obs.gauge(
    "paddle_tpu_serving_queue_depth",
    "requests waiting for admission (live)", ["engine"])
_OCCUPANCY = _obs.gauge(
    "paddle_tpu_serving_page_occupancy",
    "fraction of KV pages in use (live)", ["engine"])
_SAMPLING_REQS = _obs.counter(
    "paddle_tpu_sampling_requests_total",
    "requests submitted with temperature > 0", ["engine"])
_SAMPLING_TOKENS = _obs.counter(
    "paddle_tpu_sampling_tokens_total",
    "tokens drawn from the Philox sampler (temperature > 0)",
    ["engine"])

_engine_ids = itertools.count()


def _drop_engine_series(eid: str):
    for m in (_REQS, _TOKENS, _STEPS, _COMPILES, _DECODE_H, _PREFILL_H,
              _LATENCY_H, _QUEUE_DEPTH, _OCCUPANCY, _SAMPLING_REQS,
              _SAMPLING_TOKENS):
        m.remove_matching(engine=eid)


def _bucket_len(n: int, page_size: int) -> int:
    """Smallest page-aligned power-of-two-pages length >= n."""
    pages = max(1, math.ceil(n / page_size))
    return page_size * (1 << (pages - 1).bit_length())


def _req_summary(req: Request, where: str) -> dict:
    """One request's postmortem line (JSON-safe, lock-free reads)."""
    return {"id": req.id, "where": where, "status": req.status,
            "trace_id": req.trace_id,
            "prompt_len": int(req.prompt.size),
            "generated": len(req.generated),
            "max_new_tokens": req.max_new_tokens, "slot": req.slot,
            "tier": req.priority, "tenant": req.tenant,
            "age_s": round(time.monotonic() - req.submitted_at, 3),
            "error": req.error}


class Engine:
    def __init__(self, model, num_slots: int = 8, num_pages: int = 64,
                 page_size: int = 16, max_seq_len: int | None = None,
                 eos_id: int | None = None, max_queue: int = 256,
                 prefix_cache_pages: int | None = None):
        import jax

        self.model = model
        self.eos_id = eos_id
        self.page_size = page_size
        self.num_pages = num_pages
        # the hard sequence ceiling is min(pool capacity, requested cap,
        # MODEL position limit) — without the model term a request could
        # decode past wpe and jnp.take would clip instead of erroring,
        # returning garbage tokens with status "done"
        model_cap = getattr(model, "max_positions", None)
        cap = min(max_seq_len or num_pages * page_size,
                  num_pages * page_size,
                  model_cap if model_cap else num_pages * page_size)
        # floor to a page multiple: prefill buckets are page-aligned and
        # must never pad past the model's position table
        if cap < page_size:
            raise ValueError(
                f"page_size {page_size} exceeds the sequence ceiling "
                f"{cap} (model/pool/max_seq_len)")
        self.max_seq_len = (cap // page_size) * page_size
        self.max_pages_per_req = max(
            1, min(num_pages, self.max_seq_len // page_size))
        self.num_slots = num_slots
        self.engine_id = f"e{next(_engine_ids)}"
        self.pool = PagePool(num_pages, page_size, inst=self.engine_id)
        self.scheduler = Scheduler(self.pool, num_slots, self.max_seq_len,
                                   max_queue=max_queue,
                                   inst=self.engine_id)
        self.trash_page = num_pages      # model pools carry P+1 pages
        self.cache = model.init_cache(num_pages, page_size)
        # shared-prefix KV reuse (serving/prefix_cache.py): 0 pages =
        # disabled (the default — an idle engine then provably holds no
        # pages, the PR-2 invariant tests pin that)
        if prefix_cache_pages is None:
            prefix_cache_pages = int(os.environ.get(
                "PADDLE_TPU_PREFIX_CACHE_PAGES", "0") or 0)
        self.prefix_cache = None
        if prefix_cache_pages > 0:
            self.prefix_cache = PrefixCache(
                self.pool, budget_pages=min(prefix_cache_pages,
                                            num_pages),
                inst=self.engine_id)
            self.scheduler.prefix_cache = self.prefix_cache

        self._compiles: dict[str, int] = defaultdict(int)
        self._latencies: deque[float] = deque(maxlen=4096)
        self._tok_window: deque[tuple[float, int]] = deque(maxlen=512)
        # registry series for this engine (stats() reads these back)
        eid = self.engine_id
        self._m_reqs = _REQS.labels(engine=eid)
        self._m_tokens = _TOKENS.labels(engine=eid)
        self._m_steps = _STEPS.labels(engine=eid)
        self._m_decode_h = _DECODE_H.labels(engine=eid)
        self._m_prefill_h = _PREFILL_H.labels(engine=eid)
        self._m_latency_h = _LATENCY_H.labels(engine=eid)
        self._m_sampling_reqs = _SAMPLING_REQS.labels(engine=eid)
        self._m_sampling_tokens = _SAMPLING_TOKENS.labels(engine=eid)
        # live gauges read through a weakref so the registry never pins
        # a dead engine (tests build hundreds per process)
        wr = weakref.ref(self)
        _QUEUE_DEPTH.labels(engine=eid).set_function(
            lambda: (lambda e: e.scheduler.queue_depth if e else 0.0)(
                wr()))
        _OCCUPANCY.labels(engine=eid).set_function(
            lambda: (lambda e: e.pool.occupancy if e else 0.0)(wr()))
        # a dead engine's series (incl. the weakref gauges, which would
        # otherwise report 0.0 forever) leave the exposition
        weakref.finalize(self, _drop_engine_series, eid)
        # postmortem wiring: a progress token (the engine must keep
        # producing tokens OR retiring requests while the scheduler is
        # non-idle — a wedged jitted call inside step() is exactly what
        # the watchdog exists to catch) and an in-flight-request
        # provider for debug bundles. Both probe through the weakref so
        # a dead engine unregisters itself; neither takes the step lock
        # (a wedged step HOLDS it). Decode steps alone are NOT the
        # probe: a healthy stream of requests that all finish at
        # prefill (max_new_tokens=1) or all fail/expire never runs a
        # decode step, so _wd_progress also advances on every token and
        # every request retirement.
        self._wd_progress = 0
        self._recent: deque[dict] = deque(maxlen=32)
        wd_name = f"serving.engine.{eid}"
        _watchdog.WATCHDOG.watch(
            wd_name,
            probe=lambda: (lambda e: None if e is None
                           else e._wd_progress)(wr()),
            idle=lambda: (lambda e: True if e is None
                          else e.scheduler.idle)(wr()))
        weakref.finalize(self, _watchdog.WATCHDOG.unwatch, wd_name)
        _debug.register_requests_provider(
            wd_name,
            lambda: (lambda e: None if e is None
                     else e._debug_requests())(wr()))
        weakref.finalize(self, _debug.unregister_requests_provider,
                         wd_name)
        self._lock = threading.Lock()    # step loop exclusivity
        self._stats_lock = threading.Lock()  # deque append vs snapshot
        # published-version identity (PR 12): stamped by warm_start
        # under the step lock, so ping/stats can never report a version
        # whose weights aren't the ones decoding. 0 = cold weights
        # (never warm-started from a published version)
        self.model_version = 0
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

        # donation halves cache HBM on device backends; CPU jit would
        # only warn about it
        donate = self._donate = jax.default_backend() != "cpu"
        S, M = num_slots, self.max_pages_per_req
        compiles = self._compiles

        def note_compile(bucket: str):
            # Python side effect inside the traced fn: runs once per
            # actual XLA trace, so this counts COMPILES, not steps
            compiles[bucket] += 1
            _COMPILES.labels(engine=eid, bucket=bucket).inc()
            _flight.record("serving", "compile", engine=eid,
                           bucket=bucket)

        # sampling params ride every program as slot-wide TRACED arrays
        # (sampling.py): a greedy slot (temperature 0) still takes the
        # literal argmax path inside sample_tokens, and no sampling
        # value can ever force a recompile — the one-compile-per-bucket
        # contract is pinned with sampling enabled
        def prefill(params, cache, tokens, true_len, page_row,
                    temps, topks, topps, seeds, steps):
            note_compile(f"prefill[{tokens.shape[0]}]")  # trace-time
            cache, logits = model.prefill(params, cache, tokens,
                                          true_len, page_row)
            tok = sample_tokens(logits[None, :], temps, topks, topps,
                                seeds, steps)
            return cache, tok[0]

        def prefill_tail(params, cache, tokens, start, true_len,
                         page_row, temps, topks, topps, seeds, steps):
            note_compile(f"prefill_tail[{tokens.shape[0]}]")
            cache, logits = model.prefill_tail(params, cache, tokens,
                                               start, true_len,
                                               page_row)
            tok = sample_tokens(logits[None, :], temps, topks, topps,
                                seeds, steps)
            return cache, tok[0]

        def decode(params, cache, tokens, positions, tables,
                   temps, topks, topps, seeds, steps):
            note_compile(f"decode[slots={S},pages={M}]")  # trace-time
            cache, logits = model.decode(params, cache, tokens,
                                         positions, tables)
            return cache, sample_tokens(logits, temps, topks, topps,
                                        seeds, steps)

        kw = {"donate_argnums": (1,)} if donate else {}
        self._prefill = jax.jit(prefill, **kw)
        self._prefill_tail = jax.jit(prefill_tail, **kw)
        self._decode = jax.jit(decode, **kw)

        # perf plane: per-bucket FLOP costs land in _register_perf_cost
        # on each bucket's first (compiling) call; a bounded window of
        # (time, flops) pairs backs the live MFU gauge the same way
        # _tok_window backs tokens_per_sec
        self.num_chips = 1               # single-chip engine today
        self._flops_window: deque[tuple[float, float]] = deque(maxlen=512)
        self._bucket_flops: dict[str, float] = {}
        self._perf_sampler = _perf.StepSampler(f"engine:{eid}")
        self._perf_name = f"engine:{eid}"
        _perf.mfu_gauge(self._perf_name).set_function(
            lambda: (lambda e: e.perf_rates()["mfu"] if e else 0.0)(wr()))
        _perf.kv_cache_gauge(eid).set_function(
            lambda: (lambda e: e._kv_cache_bytes() if e else 0.0)(wr()))
        _perf.register_provider(self._perf_name,
                                _perf.weak_provider(self, "perf_rates"))
        weakref.finalize(self, _perf.drop_instance, self._perf_name, eid)

    # -- submission (any thread) ---------------------------------------
    def submit(self, prompt, max_new_tokens: int = 16,
               deadline: float | None = None,
               eos_id: int | None = None, priority: int = 1,
               tenant: str = "default", temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0,
               seed: int | None = None) -> Request:
        """Enqueue a request. `deadline` is RELATIVE seconds from now;
        raises QueueFull (backpressure) when the queue is at capacity
        and QuotaExceeded (a QueueFull) when `tenant` is over its
        token-bucket quota. `priority` is the admission tier
        (0 = highest; see scheduler.Scheduler). `temperature` 0 is
        greedy; > 0 samples via the replayable (seed, step) Philox
        stream (serving/sampling.py) — `seed` defaults to the request
        id, so an identical resubmission with an explicit seed (or the
        same wire id through the frontend) replays token-for-token."""
        req = Request(prompt, max_new_tokens,
                      deadline=None if deadline is None
                      else time.monotonic() + deadline,
                      eos_id=eos_id if eos_id is not None else self.eos_id,
                      priority=priority, tenant=tenant,
                      temperature=temperature, top_k=top_k, top_p=top_p,
                      seed=seed)
        if req.temperature > 0:
            self._m_sampling_reqs.inc()
        # carry the caller's trace context (e.g. the frontend handler's
        # wire trace id) onto the request — minting a fresh id for
        # in-process callers, so EVERY request's flight timeline is
        # keyed by a trace id even without a wire hop
        req.trace_id = _tracing.TRACER.current_trace_id() \
            or _tracing.new_trace_id()
        # offered load is metered even if the scheduler rejects below —
        # billing sees what the tenant *sent*, not what was admitted
        _meter.METER.note_submitted(req.tenant, req.priority,
                                    int(req.prompt.size))
        self.scheduler.submit(req)
        self._m_reqs.inc()
        _flight.record("serving", "submit", trace_id=req.trace_id,
                       engine=self.engine_id, request=req.id,
                       prompt_len=int(req.prompt.size),
                       max_new_tokens=req.max_new_tokens,
                       tier=req.priority, tenant=req.tenant)
        self._wake.set()
        return req

    def generate(self, prompt, max_new_tokens: int = 16,
                 deadline: float | None = None,
                 timeout: float | None = 120.0, priority: int = 1,
                 tenant: str = "default", temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0,
                 seed: int | None = None) -> np.ndarray:
        """Blocking convenience: submit + wait (requires the scheduler
        thread running, or another thread driving step())."""
        return self.submit(prompt, max_new_tokens, deadline=deadline,
                           priority=priority, tenant=tenant,
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, seed=seed).result(timeout)

    # -- checkpoint warm-start ------------------------------------------
    def warm_start(self, root: str, step: int | None = None,
                   version: int | None = None):
        """Swap in weights from a committed checkpoint manifest
        (paddle_tpu.checkpoint) without rebuilding the engine: shapes/
        dtypes must match the current model (the jitted programs and
        page pools are layout-anchored and stay valid).

        Two-phase so the swap is zero-downtime: the checkpoint read
        AND the host->device upload run off the step lock (decode
        keeps batching on the old weights through both), then the FLIP
        takes the lock for a single reference swap — weights change
        between steps, never inside one, and never with disk I/O or a
        device transfer under the step lock (the lock-blocking-call
        analysis rule pins the disk half). Models served here provide
        read_checkpoint/adopt_checkpoint (GPTDecodeModel does).

        ``version`` stamps the published-version identity the flip
        installs (online-learning hot swap): in-flight generations
        finish on the old weights' tokens-so-far, and every request
        prefilled after the flip — plus ping/stats — reports the new
        version. Defaults to ``step`` so a plain checkpoint warm start
        is still identifiable."""
        prepared = self.model.read_checkpoint(root, step=step)
        with self._lock:
            self.model.adopt_checkpoint(prepared)
            v = version if version is not None else step
            if v is not None:
                self.model_version = int(v)
        return self

    @classmethod
    def from_checkpoint(cls, root: str, step: int | None = None,
                        attn_impl: str | None = None,
                        **engine_kw) -> "Engine":
        """Build an Engine whose model (config + weights) comes from a
        checkpoint manifest — the serving cold-start path that skips
        re-initialising and re-uploading weights from scratch."""
        from .model import GPTDecodeModel
        model = GPTDecodeModel.from_checkpoint(root, step=step,
                                               attn_impl=attn_impl)
        return cls(model, **engine_kw)

    # -- step loop -----------------------------------------------------
    def _row(self, req: Request | None) -> list[int]:
        if req is None:
            return [self.trash_page] * self.max_pages_per_req
        return req.table.padded(self.max_pages_per_req,
                                fill=self.trash_page)

    def _req_sampling(self, req: Request):
        """Shape-[1] traced sampling args for the prefill programs."""
        seed = req.seed if req.seed is not None else req.id
        return (np.asarray([req.temperature], np.float32),
                np.asarray([req.top_k], np.int32),
                np.asarray([req.top_p], np.float32),
                seed_to_key(seed).reshape(1, 2),
                np.asarray([len(req.generated)], np.int32))

    def _apply_cow(self, req: Request):
        """Full-prompt bootstrap admission: copy the last matched page
        (the decode step will rewrite the last prompt position's KV
        there) into the request's private page, then drop the lookup
        ref the scheduler kept pinned for exactly this copy."""
        src, dst = req.prefix_cow
        self.cache = self.model.copy_pages(self.cache, [src], [dst])
        req.prefix_cow = None
        self.pool.free([src])
        if self.prefix_cache is not None:
            self.prefix_cache.note_cow()
        _flight.record("serving", "prefix_cow", trace_id=req.trace_id,
                       engine=self.engine_id, request=req.id,
                       src=src, dst=dst)

    def _cache_insert_prompt(self, req: Request):
        """Publish the freshly prefilled prompt's full pages (existing
        cached prefixes dedupe inside insert)."""
        if self.prefix_cache is None:
            return
        n = int(req.prompt.size) // self.page_size
        if n:
            self.prefix_cache.insert(req.prompt[:n * self.page_size],
                                     req.table.pages[:n])

    def _run_prefill(self, req: Request):
        import jax.numpy as jnp
        if req.prefix_cow is not None:
            self._apply_cow(req)
        m = req.prefix_match
        if m is not None and m.full:
            # bootstrap: the WHOLE prompt was cached — no prefill at
            # all. The request enters the decode batch with no
            # generated tokens; the next decode step feeds the last
            # prompt token at position prompt_len-1 (re-deriving that
            # position's KV into the COW page, bit-identical in the
            # parity regime) and samples the first token there.
            _flight.record("serving", "prefill_skipped",
                           trace_id=req.trace_id, engine=self.engine_id,
                           request=req.id,
                           cached_tokens=m.tokens)
            return
        start = m.tokens if m is not None else 0
        tail = req.prompt[start:] if start else req.prompt
        T = _bucket_len(tail.size, self.page_size)
        T = min(T, self.max_pages_per_req * self.page_size - start)
        toks = np.zeros((T,), np.int32)
        toks[:tail.size] = tail
        row = jnp.asarray(self._row(req), dtype=jnp.int32)
        samp = self._req_sampling(req)
        if start:
            bucket = f"prefill_tail[{T}]"
            fn = self._prefill_tail
            targs = (self.model.params, self.cache, jnp.asarray(toks),
                     np.int32(start), np.int32(tail.size), row, *samp)
        else:
            bucket = f"prefill[{T}]"
            fn = self._prefill
            targs = (self.model.params, self.cache, jnp.asarray(toks),
                     np.int32(tail.size), row, *samp)
        # read BEFORE the cost registration: lower() traces the fn and
        # seeds the jit cache, so the note_compile side effect fires
        # there, not on the timed first call
        pre_compiles = self._compiles.get(bucket, 0)
        if bucket not in self._compiles:
            # first call of this bucket pays the compile anyway; the
            # abstract lowering for cost analysis rides the same path
            self._register_perf_cost(bucket, fn, targs, T, start + T)
        t0 = time.perf_counter()
        with _tracing.span("engine.prefill", trace_id=req.trace_id,
                           engine=self.engine_id, request=req.id,
                           prompt_len=int(req.prompt.size), bucket=T,
                           cached_tokens=start):
            self.cache, tok = fn(*targs)
            tok = int(tok)
        dt = time.perf_counter() - t0
        self._m_prefill_h.observe(dt)
        if self._compiles.get(bucket, 0) > pre_compiles:
            _perf.note_compile_seconds("engine.prefill", dt)
        self._note_flops(self._bucket_flops.get(bucket))
        _flight.record("serving", "prefill", trace_id=req.trace_id,
                       engine=self.engine_id, request=req.id,
                       bucket=T, seconds=round(dt, 6))
        self._cache_insert_prompt(req)
        self._note_tokens(1)
        if req.temperature > 0:
            self._m_sampling_tokens.inc()
        if self.scheduler.record_token(req, tok):
            self._note_done(req)

    def step(self) -> bool:
        """One scheduler iteration; returns True if any work was done."""
        import jax.numpy as jnp
        with self._lock:
            for r in self.scheduler.expire_deadlines():
                self._note_done(r)
            for req in self.scheduler.admit():
                try:
                    self._run_prefill(req)
                except Exception as e:
                    # a poison request fails ALONE: evict it with its
                    # pages, keep the engine serving everyone else
                    req.error = f"prefill failed: {type(e).__name__}: {e}"
                    self.scheduler.evict(req, "error")
                    self._note_done(req)
                    self._recover_cache("failed prefill")
            active = [(i, r) for i, r in enumerate(self.scheduler.slots)
                      if r is not None]
            if not active:
                return bool(self.scheduler.queue_depth)
            sample = self._perf_sampler.tick()
            t_host0 = time.perf_counter()
            S = self.num_slots
            tokens = np.zeros((S,), np.int32)
            positions = np.zeros((S,), np.int32)
            tables = np.full((S, self.max_pages_per_req), self.trash_page,
                             np.int32)
            temps = np.zeros((S,), np.float32)
            topks = np.zeros((S,), np.int32)
            topps = np.ones((S,), np.float32)
            seeds = np.zeros((S, 2), np.uint32)
            steps = np.zeros((S,), np.int32)
            sampled_n = 0
            for i, r in active:
                # a bootstrap admission (whole prompt cached, prefill
                # skipped) reaches its first decode with NOTHING
                # generated: feed the last prompt token at position
                # prompt_len-1, exactly where prefill would have left it
                tokens[i] = r.generated[-1] if r.generated \
                    else int(r.prompt[-1])
                positions[i] = r.position
                tables[i] = self._row(r)
                temps[i] = r.temperature
                topks[i] = r.top_k
                topps[i] = r.top_p
                seeds[i] = seed_to_key(r.seed if r.seed is not None
                                       else r.id)
                steps[i] = len(r.generated)
                if r.temperature > 0:
                    sampled_n += 1
            # hang injection (chaos drills): PADDLE_PS_FAULT_STALL with
            # PADDLE_PS_FAULT_STALL_POINT=serving_decode wedges the
            # step thread here — inside the step lock, exactly like a
            # hung jitted decode — which is what the stall watchdog
            # must catch while requests keep queueing
            _fi.injector().maybe_stall("serving_decode")
            bucket = f"decode[slots={S},pages={self.max_pages_per_req}]"
            targs = (self.model.params, self.cache, jnp.asarray(tokens),
                     jnp.asarray(positions), jnp.asarray(tables),
                     jnp.asarray(temps), jnp.asarray(topks),
                     jnp.asarray(topps), jnp.asarray(seeds),
                     jnp.asarray(steps))
            # as in _run_prefill: read before lower() runs the trace
            pre_compiles = self._compiles.get(bucket, 0)
            if bucket not in self._compiles:
                self._register_perf_cost(bucket, self._decode, targs,
                                         S, self.max_seq_len)
            try:
                t0 = time.perf_counter()
                with _tracing.span("engine.decode",
                                   engine=self.engine_id,
                                   active=len(active)):
                    self.cache, device_toks = self._decode(*targs)
                    if sample:
                        # fenced phase boundaries: dispatch ends when
                        # the async jit call returns, device when the
                        # result is ready, transfer when it is host-side
                        import jax
                        t1 = time.perf_counter()
                        jax.block_until_ready(device_toks)
                        t2 = time.perf_counter()
                        next_toks = np.asarray(device_toks)
                        t3 = time.perf_counter()
                    else:
                        next_toks = np.asarray(device_toks)
                dt = time.perf_counter() - t0
                self._m_decode_h.observe(dt)
            except Exception as e:
                # a decode-step failure poisons the whole slot batch (the
                # cache buffer may be donated/invalid): fail the in-flight
                # requests with their pages freed rather than wedging them
                for _i, r in active:
                    r.error = f"decode failed: {type(e).__name__}: {e}"
                    self.scheduler.evict(r, "error")
                    self._note_done(r)
                self._recover_cache("failed decode")
                raise
            if self._compiles.get(bucket, 0) > pre_compiles:
                _perf.note_compile_seconds("engine.decode", dt)
            elif sample:
                # host = batch building (token/position/table arrays);
                # dispatch = the async jit call returning; device = the
                # block_until_ready fence; transfer = device->host copy
                _perf.record_breakdown(self._perf_name, {
                    "host": t0 - t_host0,
                    "dispatch": t1 - t0,
                    "device": t2 - t1,
                    "transfer": t3 - t2,
                })
            self._note_tokens(len(active))
            if sampled_n:
                self._m_sampling_tokens.inc(sampled_n)
            self._note_flops(self._bucket_flops.get(bucket))
            self._m_steps.inc()
            _flight.record("serving", "step", engine=self.engine_id,
                           active=len(active))
            for i, r in active:
                if self.scheduler.record_token(r, int(next_toks[i])):
                    self._note_done(r)
            return True

    def _recover_cache(self, why: str):
        """After a failed jitted call on a DONATING backend the cache
        buffer may already be consumed — rebuild it and fail whatever
        in-flight KV it held (CPU never donates: old cache stays valid,
        surviving requests keep decoding)."""
        if not self._donate:
            return
        for r in list(self.scheduler.active_requests()):
            r.error = f"kv cache lost to a {why} (donated buffer)"
            self.scheduler.evict(r, "error")
            self._note_done(r)
        self.cache = self.model.init_cache(self.num_pages, self.page_size)

    def drain(self) -> "Engine":
        """Graceful removal from a serving fleet: stop admitting new
        requests (submit raises QueueFull("draining")), let everything
        queued or running finish. `stats()["draining"]` and the
        frontend's `ping` report it so a router stops routing here;
        `run_until_idle`/the scheduler thread empty the queue, then the
        process can exit with nothing lost."""
        self.scheduler.drain()
        self._wake.set()
        return self

    @property
    def draining(self) -> bool:
        return self.scheduler.draining

    def cancel(self, req: Request) -> bool:
        """Abandon a request (frontend timeout, client gone): dequeue or
        preempt it, freeing its pages. False if it already finished."""
        with self._lock:
            return self.scheduler.cancel(req)

    def run_until_idle(self, max_steps: int = 100000):
        for _ in range(max_steps):
            self.step()
            if self.scheduler.idle:
                return
        raise RuntimeError(f"not idle after {max_steps} steps")

    def defrag(self):
        """Compact live pages to the low end of the pool (between steps).
        Shared pages move once; every holder — tables, the prefix
        cache's runs, and any pending COW source — is rewritten through
        the same mapping."""
        with self._lock:
            active = list(self.scheduler.active_requests())
            tables = [r.table for r in active]
            extra = self.prefix_cache.pages() if self.prefix_cache \
                else ()
            mapping = defrag_plan(self.pool, tables, extra_pages=extra)
            if self.prefix_cache is not None:
                self.prefix_cache.remap(mapping)
            for r in active:
                if r.prefix_cow is not None:
                    src, dst = r.prefix_cow
                    r.prefix_cow = (mapping.get(src, src),
                                    mapping.get(dst, dst))
            self.cache = self.model.apply_defrag(self.cache, mapping)
            return mapping

    # -- background thread ---------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    worked = self.step()
                except Exception:
                    # step() already failed the affected requests; the
                    # serving thread must survive a poison step or every
                    # later request wedges against a dead engine
                    import traceback
                    traceback.print_exc()
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                    continue
                if not worked and self.scheduler.idle:
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="serving-engine")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- perf plane ----------------------------------------------------
    def _register_perf_cost(self, bucket: str, jitfn, targs,
                            tokens: int, ctx: int):
        """First call of a compile bucket: register its XLA FLOPs/bytes
        under (serving:<eid>, bucket), analytic matmul FLOPs as the
        fallback when the backend reports no cost analysis."""
        analytic = _perf.analytic_gpt_flops(
            getattr(self.model, "cfg", None), tokens, ctx) or None
        fl = _perf.register_jit_cost(f"serving:{self.engine_id}", bucket,
                                     jitfn, *targs,
                                     analytic_flops=analytic)
        if fl:
            self._bucket_flops[bucket] = fl

    def _note_flops(self, flops: float | None):
        if flops:
            with self._stats_lock:
                self._flops_window.append((time.monotonic(), flops))

    def _kv_cache_bytes(self) -> float:
        # the cache is whatever pytree the model keeps (dict of layers
        # here); tree_leaves reaches the buffers regardless of shape
        import jax
        return float(sum(getattr(leaf, "nbytes", 0)
                         for leaf in jax.tree_util.tree_leaves(self.cache)))

    def perf_rates(self) -> dict:
        """Cheap live rates for ping/stats and the perf snapshot: no
        latency sort, two deque copies under the stats lock."""
        with self._stats_lock:
            w = list(self._tok_window)
            fw = list(self._flops_window)
        tps = 0.0
        if len(w) >= 2 and w[-1][0] > w[0][0]:
            tps = sum(n for _, n in w[1:]) / (w[-1][0] - w[0][0])
        mfu = 0.0
        if len(fw) >= 2 and fw[-1][0] > fw[0][0]:
            flops_per_s = sum(f for _, f in fw[1:]) / (fw[-1][0] - fw[0][0])
            mfu = _perf.mfu(flops_per_s, 1.0)
        return {"tokens_per_sec": round(tps, 2),
                "tokens_per_s_per_chip": round(tps / self.num_chips, 2),
                "mfu": round(mfu, 5)}

    # -- stats ---------------------------------------------------------
    def _note_tokens(self, n: int):
        self._wd_progress += 1
        self._m_tokens.inc(n)
        with self._stats_lock:
            self._tok_window.append((time.monotonic(), n))

    def _req_flops(self, req: Request) -> float:
        """Metering-grade FLOPs estimate for one finished request from
        the compiled-cost registry: its prefill bucket's cost plus a
        per-token share of the decode bucket (a decode step's cost
        amortizes over the slot batch it ran with)."""
        if req.started_at is None:
            return 0.0          # never admitted — nothing executed
        T = _bucket_len(int(req.prompt.size), self.page_size)
        T = min(T, self.max_pages_per_req * self.page_size)
        total = self._bucket_flops.get(f"prefill[{T}]", 0.0)
        decode_toks = max(0, len(req.generated) - 1)
        if decode_toks:
            shares = []
            for bucket, fl in self._bucket_flops.items():
                if bucket.startswith("decode[slots="):
                    s = bucket[len("decode[slots="):].split(",", 1)[0]
                    try:
                        shares.append(fl / max(1, int(s)))
                    except ValueError:
                        pass
            if shares:
                total += decode_toks * (sum(shares) / len(shares))
        return total

    def _note_done(self, req: Request):
        self._wd_progress += 1
        lat = req.latency()
        if lat is not None:
            self._m_latency_h.observe(lat)
            with self._stats_lock:
                self._latencies.append(lat)
        _meter.METER.note_flops(req.tenant, req.priority,
                                self._req_flops(req))
        with self._stats_lock:
            self._recent.append(_req_summary(req, "finished"))

    # -- postmortem view (debug bundles / debug_dump verb) --------------
    def _debug_requests(self) -> dict:
        """JSON-safe in-flight table for postmortem bundles. Reads only
        the scheduler's queue lock (never the step lock — a wedged
        decode step holds that one, and this runs while it is stuck).
        Queue AND slots are read under that one lock, matching admit's
        dequeue+assign critical section, so no live request can fall
        between the two lists."""
        with self.scheduler._lock:
            queued = list(self.scheduler.queue)
            slotted = [(i, r) for i, r
                       in enumerate(self.scheduler.slots)
                       if r is not None]
        inflight = [_req_summary(r, "queued") for r in queued]
        inflight += [_req_summary(r, f"slot{i}") for i, r in slotted]
        with self._stats_lock:
            recent = list(self._recent)
        return {"engine": self.engine_id,
                "num_slots": self.num_slots,
                "queue_depth": len(queued),
                "inflight": inflight, "recent": recent}

    def stats(self) -> dict:
        """/stats counters: queue depth, latency percentiles, tokens/sec,
        page-pool occupancy, preemptions, compiles per bucket."""
        with self._stats_lock:  # the step thread appends concurrently
            lats = sorted(self._latencies)
            w = list(self._tok_window)
        total = int(self._m_tokens.value)

        def pct(p):
            if not lats:
                return None
            return round(lats[min(len(lats) - 1,
                                  int(p / 100 * len(lats)))] * 1e3, 3)

        tps = 0.0
        if len(w) >= 2 and w[-1][0] > w[0][0]:
            tps = sum(n for _, n in w[1:]) / (w[-1][0] - w[0][0])
        rates = self.perf_rates()
        return {**self.scheduler.stats(),
                "pool": self.pool.stats(),
                "prefix_cache": self.prefix_cache.stats()
                if self.prefix_cache is not None else None,
                "model_version": self.model_version,
                "steps": int(self._m_steps.value),
                "tokens_generated": total,
                "tokens_per_sec": round(tps, 2),
                "tokens_per_s_per_chip": rates["tokens_per_s_per_chip"],
                "mfu": rates["mfu"],
                "latency_ms_p50": pct(50), "latency_ms_p99": pct(99),
                "completed_seen": len(lats),
                "compiles": dict(self._compiles)}
