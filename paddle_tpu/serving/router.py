"""Replicated serving: fault-tolerant router + replica supervision.

One `Router` fronts N `ServingServer` replicas over the PR-1 wire
format — the same `ServingClient` that talks to a single replica talks
to the router unchanged. What the router adds (docs/SERVING.md):

  * least-loaded dispatch — each replica's live queue depth / active
    slots / page occupancy (from its enriched `ping`) plus the
    router's own in-flight reservation picks the emptiest replica;
  * session affinity — requests carrying a `session` key stick to one
    replica (their KV/prefix locality), remapped only when that
    replica stops being routable;
  * per-replica backpressure — a replica at its in-flight cap is not
    offered new work; when every routable replica is saturated the
    router itself replies "rejected" (well-formed backpressure, never
    a transport error);
  * health state machine — healthy -> suspect -> dead via ping
    timeouts, consecutive transport errors and MID-STREAM token
    stalls (the streamed forward's inter-frame timeout catches a
    replica whose frontend answers pings while its decode step is
    wedged); draining replicas (operator `drain_replica`, or the
    replica reporting it) stop receiving new work and retire instead
    of respawning;
  * failover — an in-flight `generate` that dies with its replica is
    replayed on a survivor with the SAME wire request id, so dedup
    semantics hold on every replica it may ever reach and the client
    sees exactly one authoritative final reply. Greedy decode is
    deterministic, so the survivor's tokens extend the tokens already
    streamed upstream (the relay forwards only the unseen tail);
  * staggered rollout (PR 12) — with a publish root configured, the
    `rollout` op hot-swaps the fleet to a published model version one
    replica at a time (the rest keep serving), health-gating each
    swap with a post-swap probe and rolling the flipped replicas AND
    the registry back to the pinned version on failure;
    `publish_watch=True` subscribes to the registry so every
    publication rolls out automatically (docs/ONLINE_LEARNING.md);
  * elastic respawn — a dead replica with a respawn hook (subprocess
    via launch.py --serving_replicas, or `InProcessReplica` here) is rebuilt
    from its engine checkpoint (`Engine.from_checkpoint`); the router
    re-admits it after `ready_pings` healthy probes and ramps its
    in-flight cap from 1 (slow start) so a failover thundering herd
    cannot slam an empty, cold page pool — the warm-start
    re-admission path.

Observability: `paddle_tpu_router_*` metrics, `serving`-tier flight
events (`router_state`/`router_failover`/`router_respawn`), and one
watchdog health token per replica (`serving.router.<id>.<replica>`)
that fires when a replica stays suspect/dead past the deadline.

Env knobs (constructor kwargs win; docs/ENV_KNOBS.md):
  PADDLE_TPU_ROUTER_PING_INTERVAL    health-probe cadence (s, 0.5)
  PADDLE_TPU_ROUTER_PING_TIMEOUT     per-probe timeout (s, 2.0)
  PADDLE_TPU_ROUTER_SUSPECT_AFTER    consecutive failures -> suspect (1)
  PADDLE_TPU_ROUTER_DEAD_AFTER       consecutive failures -> dead (3)
  PADDLE_TPU_ROUTER_TOKEN_STALL      inter-frame stall bound (s, 30)
  PADDLE_TPU_ROUTER_SUSPECT_HOLD     stall-suspicion hold (s, 5) — ping
                                     successes inside the hold do NOT
                                     clear suspicion (a wedged decode
                                     pings green)
  PADDLE_TPU_ROUTER_FAILOVER_RETRIES extra replicas tried per request (2)
  PADDLE_TPU_ROUTER_MAX_INFLIGHT     per-replica in-flight cap (32)
  PADDLE_TPU_ROUTER_READY_PINGS      healthy probes before re-admitting
                                     a respawned replica (1)
  PADDLE_TPU_ROUTER_RESPAWN_COOLDOWN seconds between respawn attempts (2)
"""
from __future__ import annotations

import hashlib
import itertools
import os
import socket
import socketserver
import threading
import time
import weakref
from collections import OrderedDict

import numpy as np

from ..distributed.fleet.runtime.rpc import (PSRemoteError, RpcClient,
                                             RpcServerState, WireError,
                                             _env_float as _env_f,
                                             serve_connection)
from ..observability import (debug as _debug, flight as _flight,
                             meter as _meter, registry as _obs,
                             tracing as _tracing, watchdog as _watchdog)
from ..observability.collector import (TEL_READ_OPS, TelemetryCollector,
                                       telemetry_dispatch)

__all__ = ["ReplicaSpec", "Replica", "Router", "InProcessReplica"]

# replica state machine (gauge value in parentheses)
HEALTHY = "healthy"          # (0) routable
SUSPECT = "suspect"          # (1) errors/stalls; no NEW dispatch
DEAD = "dead"                # (2) past the error threshold; respawnable
RESPAWNING = "respawning"    # (3) respawn hook ran; awaiting ready pings
DRAINING = "draining"        # (4) finishing its queue; no new dispatch
RETIRED = "retired"          # (5) drained replica gone — never respawned
_STATE_VALUE = {HEALTHY: 0, SUSPECT: 1, DEAD: 2, RESPAWNING: 3,
                DRAINING: 4, RETIRED: 5}

_R_REQS = _obs.counter(
    "paddle_tpu_router_requests_total",
    "generate requests answered by the router, by final outcome",
    ["router", "outcome"], always=True)
_R_DISPATCH = _obs.counter(
    "paddle_tpu_router_dispatch_total",
    "forward attempts per replica (includes failover replays)",
    ["router", "replica"])
_R_FAILOVERS = _obs.counter(
    "paddle_tpu_router_failovers_total",
    "in-flight forwards replayed on another replica, by reason",
    ["router", "reason"], always=True)
_R_STATE = _obs.gauge(
    "paddle_tpu_router_replica_state",
    "replica health state (0 healthy, 1 suspect, 2 dead, 3 respawning, "
    "4 draining, 5 retired)", ["router", "replica"])
_R_RESPAWNS = _obs.counter(
    "paddle_tpu_router_respawns_total",
    "respawn attempts per replica", ["router", "replica"], always=True)
_R_STALLS = _obs.counter(
    "paddle_tpu_router_stream_stalls_total",
    "mid-generation inter-frame stalls detected on streamed forwards",
    ["router", "replica"], always=True)
_R_INFLIGHT = _obs.gauge(
    "paddle_tpu_router_inflight",
    "generate forwards currently in flight per replica (live)",
    ["router", "replica"])

_router_ids = itertools.count()


def _drop_router_series(rid: str):
    for m in (_R_REQS, _R_DISPATCH, _R_FAILOVERS, _R_STATE, _R_RESPAWNS,
              _R_STALLS, _R_INFLIGHT):
        m.remove_matching(router=rid)


class ReplicaSpec:
    """One replica the router fronts: a name, its current endpoint, and
    (optionally) how to rebuild it when it dies. ``respawn()`` returns
    the replacement's endpoint (or None = unchanged) — typically a
    wrapper around `Engine.from_checkpoint` + a fresh `ServingServer`
    (in-process: `InProcessReplica.spec()`; across processes: the
    launch.py respawn idiom / tests/fixtures/serving_replica.py)."""

    def __init__(self, name: str, endpoint: str, respawn=None,
                 max_inflight: int | None = None):
        self.name = str(name)
        self.endpoint = str(endpoint)
        self.respawn = respawn
        self.max_inflight = max_inflight


class Replica:
    """Router-side view of one replica. All mutable fields are guarded
    by the ROUTER's lock (one lock, no ordering hazards); the client
    pool has its own leaf lock (pop/append only, no I/O under it)."""

    def __init__(self, spec: ReplicaSpec, max_inflight: int):
        self.spec = spec
        self.name = spec.name
        self.endpoint = spec.endpoint
        # born UNCONFIRMED: routable only after a healthy probe — a
        # configured-but-not-yet-started replica must not swallow the
        # first requests' failover budget or inflate healthy_replicas
        self.state = RESPAWNING
        self.cold = False            # was dead: slow-start on readmit
        self.consecutive_errors = 0
        # mid-stream stalls, counted SEPARATELY: a wedged decode step
        # answers pings, so only a successful forward (decode proven
        # alive) or a respawn may reset this — green pings cannot.
        # Without it a permanently wedged replica flaps
        # suspect->healthy forever and never reaches dead/respawn.
        self.stall_errors = 0
        self.ready = 0               # healthy probes since dead/respawn
        self.inflight = 0            # router-side reservation
        self.max_inflight = spec.max_inflight or max_inflight
        self.slow_cap = self.max_inflight
        self.last_info: dict = {}    # last enriched-ping payload
        self.last_pick = 0           # dispatch seq of the last pick
        self.epoch = 0               # bumped per respawn: stale-failure guard
        self.suspect_until = 0.0     # stall-hold horizon
        self.respawn_inflight = False
        self.probe_inflight = False
        self.last_respawn = -1e9
        # ONE multiplexed client per replica: generates, pings, and
        # drain verbs interleave over its pooled channels — a streamed
        # generate no longer monopolizes a connection, and the health
        # probe shares the wire it is probing (PR 11)
        self._cli: RpcClient | None = None
        self._cli_lock = threading.Lock()

    @property
    def routable(self) -> bool:
        return self.state == HEALTHY

    @property
    def capacity(self) -> int:
        return min(self.max_inflight, self.slow_cap)

    def has_capacity(self) -> bool:
        return self.inflight < self.capacity

    def load_key(self) -> tuple:
        # least-loaded, then least page pressure, then least-recently-
        # picked — the last term breaks exact ties round-robin so an
        # idle fleet spreads instead of hammering the first replica
        # (and a freshly respawned replica actually receives work)
        info = self.last_info
        return (self.inflight + int(info.get("queue_depth", 0))
                + int(info.get("active_slots", 0)),
                float(info.get("occupancy", 0.0)),
                self.last_pick)

    def reset_channel(self):
        """Close the shared mux client (respawn/endpoint change)."""
        with self._cli_lock:
            cli, self._cli = self._cli, None
        if cli is not None:
            cli.close()


class Router(socketserver.ThreadingTCPServer):
    """Wire-compatible front for N serving replicas (module docstring).

    Ops: everything `ServingServer` speaks — `generate` (streamed or
    one-shot) is forwarded with failover, `ping`/`stats`/`metrics`/
    `debug_dump` answer locally — plus `drain_replica` for graceful
    removal. The router's own RpcServerState dedups `generate` by the
    client's request id, and that SAME id pins every downstream
    forward, so a retry, a failover replay, and their combination all
    resolve to exactly one applied generation per client call."""

    allow_reuse_address = True
    daemon_threads = True

    READ_OPS = frozenset({"stats", "ping", "metrics", "debug_dump"}
                         | TEL_READ_OPS)

    def __init__(self, endpoint: str = "127.0.0.1:0", replicas=(),
                 secret: str | None = None,
                 telemetry_host: bool | None = None,
                 default_timeout: float = 120.0,
                 ping_interval: float | None = None,
                 ping_timeout: float | None = None,
                 suspect_after: int | None = None,
                 dead_after: int | None = None,
                 token_stall: float | None = None,
                 suspect_hold: float | None = None,
                 failover_retries: int | None = None,
                 max_inflight: int | None = None,
                 ready_pings: int | None = None,
                 respawn_cooldown: float | None = None,
                 publish_root: str | None = None,
                 publish_watch: bool = False):
        self.router_id = f"r{next(_router_ids)}"
        self.secret = secret
        self.default_timeout = default_timeout
        self.ping_interval = ping_interval if ping_interval is not None \
            else _env_f("PADDLE_TPU_ROUTER_PING_INTERVAL", 0.5)
        self.ping_timeout = ping_timeout if ping_timeout is not None \
            else _env_f("PADDLE_TPU_ROUTER_PING_TIMEOUT", 2.0)
        self.suspect_after = suspect_after if suspect_after is not None \
            else int(_env_f("PADDLE_TPU_ROUTER_SUSPECT_AFTER", 1))
        self.dead_after = dead_after if dead_after is not None \
            else int(_env_f("PADDLE_TPU_ROUTER_DEAD_AFTER", 3))
        self.token_stall = token_stall if token_stall is not None \
            else _env_f("PADDLE_TPU_ROUTER_TOKEN_STALL", 30.0)
        self.suspect_hold = suspect_hold if suspect_hold is not None \
            else _env_f("PADDLE_TPU_ROUTER_SUSPECT_HOLD", 5.0)
        self.failover_retries = failover_retries \
            if failover_retries is not None \
            else int(_env_f("PADDLE_TPU_ROUTER_FAILOVER_RETRIES", 2))
        self.max_inflight = max_inflight if max_inflight is not None \
            else int(_env_f("PADDLE_TPU_ROUTER_MAX_INFLIGHT", 32))
        self.ready_pings = ready_pings if ready_pings is not None \
            else int(_env_f("PADDLE_TPU_ROUTER_READY_PINGS", 1))
        self.respawn_cooldown = respawn_cooldown \
            if respawn_cooldown is not None \
            else _env_f("PADDLE_TPU_ROUTER_RESPAWN_COOLDOWN", 2.0)

        # online-learning rollout (PR 12): with a publish root the
        # router coordinates staggered fleet hot swaps ("rollout" op —
        # one replica at a time, health-gated, automatic rollback to
        # the pinned version); publish_watch additionally subscribes
        # to the registry so every publication rolls out by itself
        self.publish_root = publish_root if publish_root is not None \
            else (os.environ.get("PADDLE_TPU_PUBLISH_DIR") or None)
        self._pub_registry = None
        self._pub_sub = None
        self._rollout_lock = threading.Lock()
        self.rollouts = 0
        self.rollout_rollbacks = 0
        if self.publish_root:
            from ..publish import VersionRegistry
            self._pub_registry = VersionRegistry(self.publish_root)
            if publish_watch:
                from ..publish import VersionSubscriber
                self._pub_sub = VersionSubscriber(
                    self.publish_root, registry=self._pub_registry,
                    swap_fn=lambda v, rec: self.rollout_version(v),
                    kinds=("gpt-decode",))

        self._replicas: dict[str, Replica] = {}
        self._pick_seq = itertools.count(1)
        self._sessions: OrderedDict[str, str] = OrderedDict()
        self._session_cap = 4096
        # prefix-affinity (PR 19): sessionless requests sharing a
        # prompt prefix prefer the replica that served it last, so the
        # replica's radix prefix cache keeps hitting. A HINT only —
        # capacity/spill/failover rules are unchanged, and a miss just
        # falls through to least-loaded.
        self._prefix_affinity: OrderedDict[str, str] = OrderedDict()
        self._prefix_cap = 4096
        self._lock = threading.Lock()
        self._stop_ev = threading.Event()
        self._bg_threads: list[threading.Thread] = []
        # telemetry hosting (the debug_dump-verb pattern): the router
        # can carry the fleet collector on its own dispatch so small
        # deployments need no extra process (PADDLE_TPU_TELEMETRY_HOST=1
        # or telemetry_host=True); agents then point
        # PADDLE_TPU_TELEMETRY_COLLECTOR at the router endpoint
        if telemetry_host is None:
            telemetry_host = os.environ.get(
                "PADDLE_TPU_TELEMETRY_HOST", "") == "1"
        self.collector = TelemetryCollector() if telemetry_host else None
        self._rpc = RpcServerState(read_ops=self.READ_OPS, secret=secret,
                                   expose_req_id=True)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                serve_connection(self.request, outer._dispatch,
                                 outer._rpc)

        host, port = endpoint.rsplit(":", 1)
        super().__init__((host, int(port)), Handler)
        self.endpoint = f"{host}:{self.server_address[1]}"
        weakref.finalize(self, _drop_router_series, self.router_id)
        for spec in replicas:
            self.add_replica(spec)

    # -- fleet membership ----------------------------------------------
    def add_replica(self, spec: ReplicaSpec) -> Replica:
        r = Replica(spec, self.max_inflight)
        with self._lock:
            if r.name in self._replicas:
                raise ValueError(f"duplicate replica name {r.name!r}")
            self._replicas[r.name] = r
        _R_STATE.labels(router=self.router_id,
                        replica=r.name).set(_STATE_VALUE[r.state])
        _R_INFLIGHT.labels(router=self.router_id, replica=r.name).set(0)
        # one watchdog health token per replica: fires when the replica
        # stays suspect/dead/respawning past the deadline (the fleet's
        # capacity is silently down a replica). Probes through a
        # weakref so a dead router unregisters itself.
        wr = weakref.ref(self)
        name = r.name

        def _healthy():
            router = wr()
            if router is None:
                return None          # unregisters the token
            rep = router._replicas.get(name)
            return rep is not None and rep.state in (HEALTHY, DRAINING,
                                                     RETIRED)

        tok = f"serving.router.{self.router_id}.{name}"
        _watchdog.WATCHDOG.watch_healthy(tok, _healthy)
        weakref.finalize(self, _watchdog.WATCHDOG.unwatch, tok)
        return r

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "Router":
        self._stop_ev.clear()
        # one synchronous probe round BEFORE accepting requests: every
        # configured replica is confirmed (or counted against) once, so
        # the first client request never races the health machinery
        first = []
        for r in list(self._replicas.values()):
            with self._lock:
                if r.probe_inflight or r.state == RETIRED:
                    continue
                r.probe_inflight = True
            t = threading.Thread(target=self._probe_once, args=(r,),
                                 daemon=True)
            t.start()
            first.append(t)
        for t in first:
            t.join(timeout=self.ping_timeout + 1.0)
        serve = threading.Thread(target=self.serve_forever, daemon=True,
                                 name=f"router-{self.router_id}-serve")
        health = threading.Thread(target=self._health_loop, daemon=True,
                                  name=f"router-{self.router_id}-health")
        self._bg_threads = [serve, health]
        serve.start()
        health.start()
        if self._pub_sub is not None:
            self._pub_sub.start()
        return self

    def stop(self):
        self._stop_ev.set()
        if self._pub_sub is not None:
            self._pub_sub.stop()
        if self._bg_threads:         # shutdown() blocks unless
            self.shutdown()          # serve_forever is running
        self.server_close()
        for t in self._bg_threads:
            t.join(timeout=10)
        self._bg_threads = []
        with self._lock:
            replicas = list(self._replicas.values())
        for r in replicas:
            r.reset_channel()
            _watchdog.WATCHDOG.unwatch(
                f"serving.router.{self.router_id}.{r.name}")

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- health state machine -------------------------------------------
    def _set_state(self, r: Replica, new: str):
        """Caller holds self._lock."""
        old = r.state
        if old == new:
            return
        r.state = new
        _R_STATE.labels(router=self.router_id,
                        replica=r.name).set(_STATE_VALUE[new])
        _flight.record("serving", "router_state", router=self.router_id,
                       replica=r.name, old=old, new=new,
                       consecutive=r.consecutive_errors)

    def _note_alive(self, r: Replica, info: dict):
        with self._lock:
            r.last_info = dict(info) if isinstance(info, dict) else {}
            now = time.monotonic()
            if now < r.suspect_until:
                # a wedged decode step answers pings: inside the stall
                # hold a green ping does NOT clear suspicion — only the
                # hold expiring (and then surviving dispatch) does
                return
            r.consecutive_errors = 0
            if r.last_info.get("draining") and r.state in (HEALTHY,
                                                           SUSPECT):
                self._set_state(r, DRAINING)
                return
            if r.state in (DEAD, RESPAWNING):
                r.ready += 1
                if r.ready >= self.ready_pings:
                    r.ready = 0
                    r.stall_errors = 0   # fresh incarnation
                    if r.cold:
                        # warm-start re-admission after a DEATH: the
                        # replacement engine has an EMPTY page pool and
                        # zero compiled-state warmth — ramp its
                        # in-flight cap from 1 so the backlog cannot
                        # slam it (doubles per completed forward).
                        # First-ever confirmation of a configured
                        # replica skips the ramp (it may be a warm,
                        # long-running server the router just joined).
                        r.slow_cap = 1
                        r.cold = False
                    self._set_state(r, HEALTHY)
            elif r.state == SUSPECT:
                self._set_state(r, HEALTHY)

    def _note_failure(self, r: Replica, reason: str,
                      epoch: int | None = None):
        respawn = None
        with self._lock:
            if epoch is not None and epoch != r.epoch:
                return               # talked to a pre-respawn incarnation
            r.consecutive_errors += 1
            r.ready = 0
            if reason == "stall":
                # ping replies stay green while decode is wedged: hold
                # the suspicion so the next probe can't flip it back,
                # and count stalls on a ledger pings cannot reset — a
                # permanently wedged replica must still reach DEAD
                r.stall_errors += 1
                r.suspect_until = time.monotonic() + self.suspect_hold
            if r.consecutive_errors >= self.dead_after \
                    or r.stall_errors >= self.dead_after:
                if r.state in (DRAINING, RETIRED):
                    # a drained replica going dark is it EXITING — that
                    # is the drain completing, never a fault to respawn
                    self._set_state(r, RETIRED)
                else:
                    r.cold = True
                    self._set_state(r, DEAD)
                    respawn = self._arm_respawn(r)
            elif r.consecutive_errors >= self.suspect_after \
                    and r.state == HEALTHY:
                self._set_state(r, SUSPECT)
        if respawn is not None:
            respawn.start()

    def _arm_respawn(self, r: Replica) -> threading.Thread | None:
        """Caller holds self._lock; returns the (unstarted) respawn
        thread so the spec's hook never runs under the lock."""
        if r.spec.respawn is None or r.respawn_inflight:
            return None
        if time.monotonic() - r.last_respawn < self.respawn_cooldown:
            return None
        r.respawn_inflight = True
        r.last_respawn = time.monotonic()
        return threading.Thread(target=self._do_respawn, args=(r,),
                                daemon=True,
                                name=f"router-respawn-{r.name}")

    def _do_respawn(self, r: Replica):
        _R_RESPAWNS.labels(router=self.router_id, replica=r.name).inc()
        _flight.record("serving", "router_respawn",
                       router=self.router_id, replica=r.name,
                       endpoint=r.endpoint)
        try:
            new_ep = r.spec.respawn()
        except Exception as e:
            _flight.record("serving", "router_respawn_failed",
                           router=self.router_id, replica=r.name,
                           error=f"{type(e).__name__}: {e}")
            with self._lock:
                r.respawn_inflight = False
            return
        with self._lock:
            if new_ep:
                r.endpoint = str(new_ep)
            r.epoch += 1             # in-flight failures to the old
            r.consecutive_errors = 0  # incarnation are stale now
            r.stall_errors = 0
            r.suspect_until = 0.0
            r.ready = 0
            r.respawn_inflight = False
            self._set_state(r, RESPAWNING)
        r.reset_channel()

    def _probe(self, r: Replica):
        cli = self._client(r)
        epoch = r.epoch
        try:
            # fail-fast per-call override on the SHARED channel: the
            # probe rides the same wire the generates use, so a green
            # ping vouches for the path requests actually take
            info = cli.call({"op": "ping"}, timeout=self.ping_timeout,
                            deadline=self.ping_timeout * 2,
                            max_retries=0)
        except Exception:
            self._note_failure(r, "ping", epoch=epoch)
        else:
            self._note_alive(r, info)

    def _probe_once(self, r: Replica):
        try:
            self._probe(r)
        finally:
            with self._lock:
                r.probe_inflight = False

    def _health_loop(self):
        # each probe rides its own short-lived thread: a dead replica
        # blocks ITS probe for ping_timeout, never the others' cadence
        # — failure detection must not slow down exactly when several
        # replicas are sick. probe_inflight keeps probes of one
        # replica serial (one probe verdict per replica at a time,
        # even though the shared mux channel could carry many).
        while not self._stop_ev.wait(self.ping_interval):
            for r in list(self._replicas.values()):
                if self._stop_ev.is_set():
                    return
                with self._lock:
                    if r.probe_inflight or r.state == RETIRED:
                        continue
                    r.probe_inflight = True
                threading.Thread(
                    target=self._probe_once, args=(r,), daemon=True,
                    name=f"router-{self.router_id}-probe-{r.name}"
                ).start()

    # -- dispatch -------------------------------------------------------
    @staticmethod
    def _prefix_key(prompt) -> str:
        """Stable hash of the prompt's leading tokens (the shared
        system-prompt region). 64 tokens comfortably covers the page-
        aligned prefixes the replica-side radix cache can actually
        reuse without the router knowing any replica's page size."""
        head = np.ascontiguousarray(np.asarray(prompt).ravel()[:64],
                                    dtype=np.int64)
        return hashlib.blake2b(head.tobytes(), digest_size=8).hexdigest()

    def _pick(self, session: str | None, exclude: set,
              prefix: str | None = None) -> Replica | None:
        """Reserve the least-loaded routable replica (None = nothing
        routable with capacity). Pure in-memory under the router lock.
        Sticky preferences, strongest first: an established session,
        then the prompt-prefix affinity hint — both only when the
        preferred replica is routable with capacity, never overriding
        spill or failover exclusion."""
        with self._lock:
            owner = None
            if session is not None:
                name = self._sessions.get(session)
                owner = self._replicas.get(name) if name else None
                if owner is not None and owner.routable \
                        and owner.name not in exclude \
                        and owner.has_capacity():
                    self._sessions.move_to_end(session)
                    owner.inflight += 1
                    owner.last_pick = next(self._pick_seq)
                    _R_INFLIGHT.labels(router=self.router_id,
                                       replica=owner.name
                                       ).set(owner.inflight)
                    return owner
            if session is None and prefix is not None:
                name = self._prefix_affinity.get(prefix)
                pref = self._replicas.get(name) if name else None
                if pref is not None and pref.routable \
                        and pref.name not in exclude \
                        and pref.has_capacity():
                    self._prefix_affinity.move_to_end(prefix)
                    pref.inflight += 1
                    pref.last_pick = next(self._pick_seq)
                    _R_INFLIGHT.labels(router=self.router_id,
                                       replica=pref.name
                                       ).set(pref.inflight)
                    return pref
            cands = [r for r in self._replicas.values()
                     if r.routable and r.name not in exclude
                     and r.has_capacity()]
            if not cands:
                return None
            r = min(cands, key=Replica.load_key)
            if session is None and prefix is not None:
                # remember where this prefix landed (dead/at-capacity
                # preferred replicas get overwritten here, so the hint
                # self-heals after failover)
                self._prefix_affinity[prefix] = r.name
                self._prefix_affinity.move_to_end(prefix)
                while len(self._prefix_affinity) > self._prefix_cap:
                    self._prefix_affinity.popitem(last=False)
            if session is not None and (owner is None
                                        or not owner.routable):
                # remap the session only when its replica stopped
                # being ROUTABLE — a transient at-capacity spike (or a
                # one-attempt exclusion) spills THIS request sideways
                # without forfeiting the session's KV/prefix locality
                self._sessions[session] = r.name
                self._sessions.move_to_end(session)
                while len(self._sessions) > self._session_cap:
                    self._sessions.popitem(last=False)
            r.inflight += 1
            r.last_pick = next(self._pick_seq)
            _R_INFLIGHT.labels(router=self.router_id,
                               replica=r.name).set(r.inflight)
            return r

    def _release(self, r: Replica, ok: bool):
        with self._lock:
            r.inflight = max(0, r.inflight - 1)
            _R_INFLIGHT.labels(router=self.router_id,
                               replica=r.name).set(r.inflight)
            if ok:
                # a completed forward is PROOF the decode path moves:
                # the one signal allowed to clear the stall ledger
                r.stall_errors = 0
                if r.slow_cap < r.max_inflight:
                    r.slow_cap = min(r.max_inflight, r.slow_cap * 2)

    def _client(self, r: Replica) -> RpcClient:
        """The replica's one multiplexed client, rebuilt lazily when
        the endpoint moved (respawn). Construction is lazy-connecting,
        so nothing blocks under the lock."""
        with r._cli_lock:
            cli = r._cli
            if cli is None or cli.endpoint != r.endpoint:
                old = cli
                cli = r._cli = RpcClient(
                    r.endpoint, secret=self.secret,
                    timeout=self.default_timeout,
                    deadline=self.default_timeout * 2,
                    max_retries=0)
            else:
                old = None
        if old is not None:
            old.close()
        return cli

    def _forward_req(self, req: dict) -> dict:
        fwd = {"op": "generate", "prompt": req["prompt"],
               "max_new_tokens": int(req.get("max_new_tokens", 16)),
               "deadline": req.get("deadline"),
               "timeout": req.get("timeout"),
               "priority": int(req.get("priority", 1)),
               "tenant": str(req.get("tenant", "default")),
               # ALWAYS stream downstream, whatever the client asked:
               # the inter-frame gap is the router's only mid-generation
               # stall signal, and TTFT becomes wire-observable
               "stream": True}
        # sampling knobs relay verbatim — the replica (not the router)
        # resolves a missing seed from the wire request id, and the
        # router pins that id across failover, so a relayed retry on a
        # survivor replica replays the identical token stream
        for key in ("temperature", "top_k", "top_p", "seed"):
            if key in req:
                fwd[key] = req[key]
        return fwd

    def _relay(self, req: dict, rid: int | None):
        """Generator: forward one generate with failover, yielding
        relayed token frames (consumed internally when the client did
        not ask for a stream). Returns the final reply dict. The
        tracing span opens HERE (first next()), not in _dispatch — a
        returned generator outlives the dispatch call, and the span
        must cover the actual relay work."""
        with _tracing.span("router.generate",
                           prompt_len=int(req["prompt"].size)) as sp:
            final = yield from self._relay_inner(req, rid)
            sp.attrs["status"] = final.get("status", "?") \
                if isinstance(final, dict) else "?"
            return final

    def _relay_inner(self, req: dict, rid: int | None):
        fwd = self._forward_req(req)
        tenant = fwd["tenant"]
        stream_up = bool(req.get("stream"))
        session = req.get("session")
        first_t = float(req.get("timeout") or self.default_timeout) + 5.0
        sent = 0                     # tokens already relayed upstream
        tried: set[str] = set()
        last_err: str | None = None
        pfx = self._prefix_key(req["prompt"]) if session is None else None
        for _attempt in range(self.failover_retries + 1):
            r = self._pick(session, tried, prefix=pfx)
            if r is None:
                break
            tried.add(r.name)
            epoch = r.epoch
            _R_DISPATCH.labels(router=self.router_id,
                               replica=r.name).inc()
            cli = self._client(r)
            ok = None   # True = channel fine, False = transport fault,
            #             None = abandoned (upstream died mid-relay)
            try:
                gen = cli.call_stream(fwd, req_id=rid, timeout=first_t,
                                      stream_timeout=self.token_stall)
                final = None
                try:
                    while final is None:
                        try:
                            frame = next(gen)
                        except StopIteration as stop:
                            final = stop.value \
                                if stop.value is not None else {}
                            break
                        toks = frame.get("tokens") \
                            if isinstance(frame, dict) else None
                        if toks is None:
                            continue
                        toks = [int(t) for t in
                                np.asarray(toks).ravel()]
                        idx = int(frame.get("index", 0))
                        # failover replay restarts from index 0 with
                        # identical (greedy-deterministic) tokens:
                        # relay only the unseen tail
                        new = idx + len(toks) - sent
                        if new > 0:
                            tail = toks[len(toks) - new:]
                            if stream_up:
                                yield {"tokens": np.asarray(tail,
                                                            np.int32),
                                       "index": sent}
                            sent += new
                finally:
                    gen.close()
                ok = True
            except PSRemoteError as e:
                # the replica DISPATCHED and failed (application
                # error): deterministic poison would fail everywhere —
                # report it, no failover
                ok = True
                _R_REQS.labels(router=self.router_id,
                               outcome="error").inc()
                _meter.METER.note_routed(tenant, "error")
                return {"status": "error", "error": str(e)}
            except (socket.timeout, WireError, ConnectionError,
                    OSError) as e:
                ok = False
                stalled = isinstance(e, socket.timeout)
                reason = "stall" if stalled else "transport"
                if stalled:
                    _R_STALLS.labels(router=self.router_id,
                                     replica=r.name).inc()
                last_err = f"{type(e).__name__}: {e}"
                _R_FAILOVERS.labels(router=self.router_id,
                                    reason=reason).inc()
                _flight.record("serving", "router_failover",
                               router=self.router_id, replica=r.name,
                               reason=reason, relayed=sent,
                               error=last_err)
                self._note_failure(r, reason, epoch=epoch)
                continue
            finally:
                # runs on EVERY exit — including GeneratorExit when the
                # upstream client dies mid-relay, which must not leak
                # the in-flight reservation (capacity would shrink
                # forever) or grow the slow-start cap. The shared mux
                # client needs no return/close: an abandoned stream
                # sends F_CANCEL and the channel itself stays pooled.
                self._release(r, ok is True)
            status = final.get("status", "?") \
                if isinstance(final, dict) else "?"
            if status == "rejected" \
                    and len(tried) <= self.failover_retries:
                # replica-level backpressure with replicas left to try:
                # spill sideways instead of bouncing the client — also
                # mid-stream (a failover can land on a saturated
                # replica; it applied nothing, and the tail relay
                # resumes cleanly on the next candidate)
                last_err = "replica backpressure"
                _R_FAILOVERS.labels(router=self.router_id,
                                    reason="backpressure").inc()
                continue
            if status == "rejected" and sent:
                break                # partial stream: NOT clean backpressure
            _R_REQS.labels(router=self.router_id, outcome=status).inc()
            _meter.METER.note_routed(tenant, status)
            return final
        # give-up reply. "rejected" means nothing was admitted ANYWHERE
        # (safe to resubmit); once tokens were streamed upstream the
        # request partially executed, so it must surface as an error —
        # a client treating it as clean backpressure would resubmit and
        # double-consume the streamed prefix.
        clean = sent == 0 and (last_err is None
                               or last_err == "replica backpressure")
        outcome = "rejected" if clean else "failed"
        _R_REQS.labels(router=self.router_id, outcome=outcome).inc()
        _meter.METER.note_routed(tenant, outcome)
        detail = "no routable replica with capacity" \
            if last_err is None else last_err
        if sent:
            detail = f"{sent} token(s) already streamed, then: {detail}"
        return {"status": "rejected" if clean else "error",
                "error": f"router: giving up after "
                         f"{len(tried) or 'no'} replica(s): {detail}"}

    # -- server ops ----------------------------------------------------
    def _dispatch(self, req: dict):
        op = req.get("op")
        if op == "ping":
            with self._lock:
                healthy = sum(1 for r in self._replicas.values()
                              if r.routable)
                queued = sum(int(r.last_info.get("queue_depth", 0))
                             + r.inflight
                             for r in self._replicas.values())
            return {"ok": healthy > 0, "router": True,
                    "draining": False, "queue_depth": queued,
                    "healthy_replicas": healthy,
                    "replicas": len(self._replicas)}
        if op == "stats":
            return self.stats()
        if op == "metrics":
            return _obs.prometheus_text()
        if op == "debug_dump":
            return _debug.dump_verb(req)
        if op and (op.startswith("tel_")
                   or op in ("tsdb_query", "alerts", "usage_report")):
            if self.collector is None:
                raise ValueError("telemetry collector not hosted here "
                                 "(set PADDLE_TPU_TELEMETRY_HOST=1)")
            req.pop("_req_id", None)
            return telemetry_dispatch(self.collector, req)
        if op == "drain_replica":
            return self._drain_replica(req)
        if op == "rollout":
            v = req.get("version")
            return self.rollout_version(None if v is None else int(v))
        if op == "generate":
            rid = req.pop("_req_id", None)
            req["prompt"] = np.asarray(req["prompt"], np.int32)
            rely = self._relay(req, rid)
            if req.get("stream"):
                return rely          # serve_connection drains it
            while True:              # consume the relay internally
                try:
                    next(rely)
                except StopIteration as stop:
                    return stop.value if stop.value is not None \
                        else {}
        req.pop("_req_id", None)
        raise ValueError(f"unknown op {op!r}")

    def _drain_replica(self, req: dict) -> dict:
        name = str(req.get("replica", ""))
        with self._lock:
            r = self._replicas.get(name)
            if r is None:
                raise ValueError(f"unknown replica {name!r}")
            self._set_state(r, DRAINING)
        # forward the drain verb so the replica itself stops admitting
        # (direct clients included) and finishes its queue — on the
        # replica's shared mux client, interleaved with whatever
        # in-flight generates it is finishing
        rep = self._client(r).call(
            {"op": "drain", "wait": bool(req.get("wait")),
             "timeout": req.get("timeout")},
            timeout=float(req.get("timeout") or 60) + 30,
            deadline=float(req.get("timeout") or 60) + 60,
            max_retries=1)
        return {"replica": name, "draining": True,
                "idle": rep.get("idle") if isinstance(rep, dict)
                else None}

    # -- staggered fleet rollout (PR 12) --------------------------------
    def _adopt_on(self, r: Replica, version: int) -> dict:
        """One replica's hot swap + health gate: adopt_version on its
        shared mux client, then a post-swap probe that must come back
        ok AND reporting the new version (a swap that 'succeeded' into
        a broken engine fails here). Raises on any failure."""
        cli = self._client(r)
        cli.call({"op": "adopt_version", "version": int(version)},
                 timeout=self.default_timeout,
                 deadline=self.default_timeout * 2, max_retries=0)
        info = cli.call({"op": "ping"}, timeout=self.ping_timeout,
                        deadline=self.ping_timeout * 2, max_retries=0)
        if not (isinstance(info, dict) and info.get("ok")
                and int(info.get("model_version", -1)) == int(version)):
            raise RuntimeError(
                f"post-swap probe on {r.name} reports "
                f"{info.get('model_version') if isinstance(info, dict) else info!r}, "
                f"wanted {version}")
        self._note_alive(r, info)
        return info

    def rollout_version(self, version: int | None = None) -> dict:
        """Staggered fleet hot swap to published ``version`` (default:
        the registry's latest). One replica at a time — the rest keep
        serving the old weights, so fleet capacity never drops by more
        than one replica's worth mid-rollout. Any adopt failure or
        post-swap probe failure aborts the rollout, re-adopts the
        fallback (the registry's pinned version when set, else each
        replica's pre-rollout version) on every replica already
        flipped, and rewinds the registry's latest pointer — the
        automatic-rollback contract (docs/ONLINE_LEARNING.md)."""
        if self._pub_registry is None:
            raise ValueError("rollout needs a publish root "
                             "(publish_root= or PADDLE_TPU_PUBLISH_DIR)")
        failure = None
        with self._rollout_lock:   # one rollout at a time, fleet-wide
            reg = self._pub_registry
            reg.reload(missing_ok=True)
            if version is None:
                version = reg.latest()
            version = int(version)
            if not version:
                return {"adopted": 0, "replicas": [],
                        "error": "nothing published yet"}
            pinned = reg.pinned()
            with self._lock:
                targets = [r for r in self._replicas.values()
                           if r.state in (HEALTHY, SUSPECT)]
            flipped: list[tuple[Replica, int]] = []  # (replica, prior)
            for r in targets:
                prior = int(r.last_info.get("model_version", 0))
                try:
                    self._adopt_on(r, version)
                except Exception as e:
                    err = f"{type(e).__name__}: {e}"
                    _flight.record("serving", "rollout_failed",
                                   router=self.router_id,
                                   replica=r.name, version=version,
                                   error=err)
                    self._restore_flipped(flipped, pinned, version)
                    self.rollout_rollbacks += 1
                    failure = {"adopted": None, "version": version,
                               "failed_on": r.name, "error": err}
                    break
                flipped.append((r, prior))
            else:
                self.rollouts += 1
                _flight.record("serving", "rollout",
                               router=self.router_id, version=version,
                               replicas=[r.name for r, _p in flipped])
                return {"adopted": version,
                        "replicas": [r.name for r, _p in flipped]}
        # rewinding the registry is a durable file commit — done after
        # the rollout lock drops so no rollout ever blocks behind an
        # fsync. The fleet is already restored; a rollout racing this
        # rewind re-reads `latest` and simply re-serves the fallback.
        failure["rolled_back"] = self._rewind_registry(pinned)
        return failure

    def _restore_flipped(self, flipped, pinned: int, bad: int):
        """Abort path, under the rollout lock: restore every
        already-flipped replica (pinned version when set, else its own
        pre-rollout version)."""
        for r, prior in flipped:
            back = pinned or prior
            if not back or back == bad:
                continue             # replica predates publishing
            try:
                self._adopt_on(r, back)
            except Exception:
                # the health loop owns this replica now: it will go
                # suspect/dead and respawn from its checkpoint
                _flight.record("serving", "rollback_failed",
                               router=self.router_id, replica=r.name,
                               version=back)

    def _rewind_registry(self, pinned: int) -> int | None:
        """Rewind the registry's latest pointer so subscribers and
        later rollouts never see the bad version as latest."""
        try:
            rec = self._pub_registry.rollback(pinned or None)
            return int(rec["version"])
        except Exception:
            return None

    def stats(self) -> dict:
        with self._lock:
            reps = {r.name: {"state": r.state,
                             "endpoint": r.endpoint,
                             "inflight": r.inflight,
                             "capacity": r.capacity,
                             "epoch": r.epoch,
                             "consecutive_errors": r.consecutive_errors,
                             "last_info": dict(r.last_info)}
                    for r in self._replicas.values()}
            sessions = len(self._sessions)
        return {"router": self.router_id, "endpoint": self.endpoint,
                "replicas": reps, "sessions": sessions,
                "healthy_replicas": sum(
                    1 for v in reps.values()
                    if v["state"] == HEALTHY)}


class InProcessReplica:
    """A ServingServer + Engine inside this process — the test/bench
    replica (production replicas are separate processes: the launch.py
    --serving_replicas respawn idiom, tests/fixtures/serving_replica.py).

    Builds the engine from a checkpoint root (`Engine.from_checkpoint`)
    so `kill()` + respawn exercises the real warm-start path: the
    replacement re-reads the manifest, starts with an empty page pool,
    and the router's slow-start re-admits it gradually."""

    def __init__(self, ckpt_root: str, name: str = "replica",
                 engine_kw: dict | None = None,
                 endpoint: str = "127.0.0.1:0",
                 publish_root: str | None = None):
        self.ckpt_root = ckpt_root
        self.name = name
        self.engine_kw = dict(engine_kw or {})
        self._endpoint_req = endpoint
        # online-learning: the replica's adopt_version loads from this
        # root (server-side config, like the real subprocess replica's
        # PADDLE_TPU_PUBLISH_DIR env)
        self.publish_root = publish_root
        self.server = None
        self.engine = None

    def start(self) -> str:
        from .engine import Engine
        from .frontend import ServingServer
        self.engine = Engine.from_checkpoint(self.ckpt_root,
                                             **self.engine_kw)
        self.server = ServingServer(self.engine, self._endpoint_req,
                                    publish_root=self.publish_root)
        self.server.start()
        return self.server.endpoint

    @property
    def endpoint(self) -> str:
        return self.server.endpoint if self.server else ""

    def kill(self):
        """Crash, don't drain: sever the listener AND every live
        connection (in-flight streams die mid-frame), stop the decode
        loop. What a process kill looks like from the router's side."""
        srv, eng = self.server, self.engine
        self.server = self.engine = None
        if srv is not None:
            srv.kill()
        if eng is not None:
            eng.stop()

    def stop(self):
        srv, eng = self.server, self.engine
        self.server = self.engine = None
        if srv is not None:
            srv.stop()
        elif eng is not None:
            eng.stop()

    def respawn(self) -> str:
        """The ReplicaSpec.respawn hook: kill whatever is left, rebuild
        from the checkpoint on a fresh port, return the new endpoint."""
        self.kill()
        return self.start()

    def spec(self, **kw) -> ReplicaSpec:
        return ReplicaSpec(self.name, self.endpoint,
                           respawn=self.respawn, **kw)
