"""paddle_tpu.serving — continuous-batching inference with a paged KV
cache (docs/SERVING.md).

The ROADMAP's serving-side subsystem: the single-request ZeroCopy
`Predictor` (paddle_tpu.inference) answers one client; this package
serves MANY — queued requests are continuously batched into a
fixed-shape decode step over a paged KV cache (Ragged Paged Attention,
PAPERS.md), with capacity-based admission, deadlines, preemption,
backpressure and /stats counters.

Quickstart (in-process):

    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.serving import Engine, GPTDecodeModel

    model = GPTDecodeModel(GPTConfig.tiny())
    with Engine(model, num_slots=8, num_pages=64, page_size=16) as eng:
        tokens = eng.generate([1, 2, 3], max_new_tokens=16)

Network mode (PS wire format, see serving/frontend.py):

    from paddle_tpu.serving import ServingServer, ServingClient
    srv = ServingServer(engine).start()          # engine-owned thread
    out = ServingClient(srv.endpoint).generate([1, 2, 3], 16)

Replicated fleet (serving/router.py, docs/SERVING.md): a Router fronts
N replicas with least-loaded dispatch, session affinity, streaming
token frames, exactly-once failover, draining, and elastic respawn
from engine checkpoints — the same ServingClient talks to it.
"""
from .kv_cache import PagePool, PageTable, defrag_plan, pages_needed
from .prefix_cache import PrefixCache, PrefixMatch
from .sampling import SamplingParams, derive_seed
from .scheduler import (QueueFull, QuotaExceeded, Request, Scheduler,
                        TokenBucket)
from .model import GPTDecodeModel
from .engine import Engine
from .frontend import ServingClient, ServingServer
from .loadgen import (Arrival, LoadGenerator, LoadResult, TrafficConfig,
                      slo_report)
from .router import InProcessReplica, Replica, ReplicaSpec, Router

__all__ = [
    "PagePool", "PageTable", "pages_needed", "defrag_plan",
    "PrefixCache", "PrefixMatch", "SamplingParams", "derive_seed",
    "Request", "Scheduler", "QueueFull", "QuotaExceeded", "TokenBucket",
    "GPTDecodeModel", "Engine", "ServingServer", "ServingClient",
    "Arrival", "LoadGenerator", "LoadResult", "TrafficConfig",
    "slo_report",
    "Router", "ReplicaSpec", "Replica", "InProcessReplica",
]
