"""Radix prefix cache: shared-prompt KV reuse over the paged pool.

Millions of requests mostly share a handful of system prompts; with the
paged KV layout (kv_cache.py) the shared prefix's pages are already
position-addressed, so reuse is pure host accounting: a radix tree over
token-id sequences, PAGE-granular (one node = one full page = one
`page_size`-token key), maps a prompt's longest cached page-aligned
prefix to a run of pool pages whose KV is already written.

Contract with the engine/scheduler:

  * `lookup(prompt)` walks the trie and — on a hit — takes one pool ref
    per matched page before returning, so the pages cannot be recycled
    between lookup and admission; the caller either installs them in a
    PageTable (the table's `free` drops the refs at retirement) or
    releases them (`pool.free(match.pages)`) if admission fails.
  * `insert(tokens, pages)` publishes fully-written pages after a
    prefill or at retirement; the cache takes its OWN ref per newly
    cached page. Walks dedupe by token content — the first page cached
    for a prefix wins, later identical runs add no refs.
  * shared pages are READ-ONLY to every holder; the one place decode
    must write into a matched page (the full-prompt bootstrap rewrite of
    the last prompt position) is copy-on-write at ADMISSION — the
    scheduler charges one extra page and the engine copies the page
    device-side before the request ever decodes.
  * eviction is LRU over LEAF nodes whose page refcount is exactly 1
    (cache-only): a page any live request still maps stays; `reclaim`
    lets a pool-blocked admission shed cold cached pages so the cache
    can never deadlock the pool. `budget_pages`
    (PADDLE_TPU_PREFIX_CACHE_PAGES) bounds what the cache holds.

Pure host logic, no jax — unit-testable without a model, like the
scheduler.
"""
from __future__ import annotations

import itertools
import threading
import weakref

import numpy as np

from ..observability import registry as _obs
from .kv_cache import PagePool

__all__ = ["PrefixCache", "PrefixMatch"]

# prefix plane (labeled per cache instance = engine id); the ratchet in
# analysis/rules/invariants.py pins these names
_HITS = _obs.counter(
    "paddle_tpu_prefix_lookup_hits_total",
    "admission lookups that matched >=1 cached page", ["cache"])
_MISSES = _obs.counter(
    "paddle_tpu_prefix_lookup_misses_total",
    "admission lookups that matched nothing", ["cache"])
_TOKENS_SAVED = _obs.counter(
    "paddle_tpu_prefix_prefill_tokens_saved_total",
    "prompt tokens whose prefill was skipped via cached pages",
    ["cache"])
_COW = _obs.counter(
    "paddle_tpu_prefix_cow_copies_total",
    "copy-on-write page copies (full-prompt bootstrap admissions)",
    ["cache"])
_EVICTED = _obs.counter(
    "paddle_tpu_prefix_evicted_pages_total",
    "cached pages evicted (LRU budget + reclaim)", ["cache"])
_CACHED = _obs.gauge(
    "paddle_tpu_prefix_cached_pages",
    "pages currently held by the prefix cache (live)", ["cache"])
_SHARED = _obs.gauge(
    "paddle_tpu_prefix_shared_pages",
    "pool pages with more than one holder (live)", ["cache"])

_cache_ids = itertools.count()


def _drop_cache_series(inst: str):
    for m in (_HITS, _MISSES, _TOKENS_SAVED, _COW, _EVICTED, _CACHED,
              _SHARED):
        m.remove_matching(cache=inst)


class PrefixMatch:
    """One lookup hit: `pages` (refs already taken), `tokens` matched
    (= len(pages) * page_size), `full` = the whole prompt was cached."""

    __slots__ = ("pages", "tokens", "full")

    def __init__(self, pages: list[int], tokens: int, full: bool):
        self.pages = pages
        self.tokens = tokens
        self.full = full


class _Node:
    __slots__ = ("key", "page", "children", "parent", "last_used")

    def __init__(self, key, page, parent):
        self.key = key               # tuple of page_size token ids
        self.page = page             # pool page index backing the key
        self.children: dict[tuple, _Node] = {}
        self.parent = parent
        self.last_used = 0


class PrefixCache:
    """Page-granular radix trie over token ids -> refcounted page runs."""

    def __init__(self, pool: PagePool, budget_pages: int,
                 inst: str | None = None):
        if budget_pages <= 0:
            raise ValueError("budget_pages must be positive")
        self.pool = pool
        self.page_size = pool.page_size
        self.budget_pages = budget_pages
        self._root = _Node((), -1, None)
        self._lock = threading.Lock()
        self._cached = 0             # nodes (= pages) held
        self._clock = itertools.count(1)   # LRU stamps, no wall time
        self.inst = inst if inst is not None else f"pc{next(_cache_ids)}"
        self._m_hits = _HITS.labels(cache=self.inst)
        self._m_misses = _MISSES.labels(cache=self.inst)
        self._m_saved = _TOKENS_SAVED.labels(cache=self.inst)
        self._m_cow = _COW.labels(cache=self.inst)
        self._m_evicted = _EVICTED.labels(cache=self.inst)
        wr = weakref.ref(self)
        _CACHED.labels(cache=self.inst).set_function(
            lambda: (lambda c: float(c._cached) if c else 0.0)(wr()))
        _SHARED.labels(cache=self.inst).set_function(
            lambda: (lambda c: float(c.pool.shared_pages) if c else 0.0)(
                wr()))
        weakref.finalize(self, _drop_cache_series, self.inst)

    # -- lookup (admission path) ---------------------------------------
    def lookup(self, prompt) -> PrefixMatch | None:
        """Longest cached page-aligned prefix of `prompt`, or None.
        Takes one pool ref per matched page BEFORE returning (under the
        cache lock, so no eviction can recycle them in between); the
        caller owns those refs."""
        toks = np.asarray(prompt).reshape(-1)
        ps = self.page_size
        pages: list[int] = []
        with self._lock:
            node = self._root
            for i in range(int(toks.size) // ps):
                key = tuple(int(t) for t in toks[i * ps:(i + 1) * ps])
                child = node.children.get(key)
                if child is None:
                    break
                node = child
                node.last_used = next(self._clock)
                pages.append(node.page)
            if not pages:
                self._m_misses.inc()
                return None
            self.pool.ref(pages)
        self._m_hits.inc()
        self._m_saved.inc(len(pages) * ps)
        return PrefixMatch(list(pages), len(pages) * ps,
                           full=len(pages) * ps == int(toks.size))

    # -- insert (post-prefill / retirement) ----------------------------
    def insert(self, tokens, pages: list[int]) -> int:
        """Publish `pages[i]` as the KV of tokens[i*ps:(i+1)*ps] given
        the preceding pages. Existing nodes win (content-identical by
        construction: the token path determines positions and KV);
        only NEW nodes take a cache ref. Returns pages newly cached."""
        toks = np.asarray(tokens).reshape(-1)
        ps = self.page_size
        if len(pages) * ps > toks.size:
            raise ValueError(
                f"{len(pages)} pages need {len(pages) * ps} tokens, "
                f"got {toks.size}")
        added = 0
        with self._lock:
            node = self._root
            for i, page in enumerate(pages):
                key = tuple(int(t) for t in toks[i * ps:(i + 1) * ps])
                child = node.children.get(key)
                if child is None:
                    child = _Node(key, page, node)
                    node.children[key] = child
                    self.pool.ref([page])
                    self._cached += 1
                    added += 1
                child.last_used = next(self._clock)
                node = child
            self._evict_locked(self.budget_pages)
        return added

    def note_cow(self):
        self._m_cow.inc()

    # -- eviction ------------------------------------------------------
    def _leaves(self):
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            else:
                yield n

    def _evict_locked(self, target: int) -> int:
        """Evict LRU cache-only leaves until at most `target` pages are
        held (a page a live request still refs is never evicted — its
        refcount is > 1). Evicting a leaf can expose its parent as the
        next candidate, so this loops node by node."""
        dropped = 0
        while self._cached > target:
            victim = None
            for n in self._leaves():
                if self.pool.refcount(n.page) != 1:
                    continue
                if victim is None or n.last_used < victim.last_used:
                    victim = n
            if victim is None:
                break                # everything left is in live use
            del victim.parent.children[victim.key]
            self.pool.free([victim.page])
            self._cached -= 1
            dropped += 1
        if dropped:
            self._m_evicted.inc(dropped)
        return dropped

    def reclaim(self, n: int) -> int:
        """Shed up to `n` cold cached pages regardless of budget — the
        scheduler calls this when the pool blocks an admission, so
        cache-held pages can never starve live traffic."""
        with self._lock:
            return self._evict_locked(max(0, self._cached - n))

    # -- defrag --------------------------------------------------------
    def pages(self) -> list[int]:
        with self._lock:
            return [n.page for n in self._walk()]

    def _walk(self):
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            yield n

    def remap(self, mapping: dict[int, int]):
        """Rewrite cached page indices after a defrag (the pool refs
        moved with the pages; only the trie's addresses change)."""
        with self._lock:
            for n in self._walk():
                n.page = mapping.get(n.page, n.page)

    # -- stats ---------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            cached = self._cached
        return {"cached_pages": cached,
                "budget_pages": self.budget_pages,
                "shared_pages": self.pool.shared_pages,
                "hits": int(self._m_hits.value),
                "misses": int(self._m_misses.value),
                "tokens_saved": int(self._m_saved.value),
                "cow_copies": int(self._m_cow.value),
                "evicted_pages": int(self._m_evicted.value)}
