"""Decode-model adapter: GPT functional core over a paged KV cache.

Bridges `models/gpt.py` (stacked-block functional GPT) to the serving
engine's two jitted entry points:

  prefill(params, cache, tokens [T], true_len, page_row [M])
      -> (cache', logits [V])
    Dense causal forward over one padded prompt bucket; per-layer K/V of
    every bucket position is scattered into the request's pages (padding
    positions land in the pool's trash page — see below) and the logits
    of the LAST REAL position come back for the first sampled token.

  decode(params, cache, tokens [S], positions [S], tables [S, M])
      -> (cache', logits [S, V])
    One token for every slot of the fixed-shape slot batch: embed at
    `positions`, per layer append K/V into the position's page, ragged
    paged attention over each slot's own history
    (ops/paged_attention.py), final LN + tied-embedding head.

Trash-page convention: the device pools carry ONE extra page at index
`num_pages` that absorbs every masked write — padded page-table entries
and inactive slots point at it, so the jitted step never needs a
data-dependent "skip this write" branch (writes are unconditional,
garbage lands in the trash page, reads are masked by ctx_len before
softmax). Page tables handed to these functions must therefore be
padded with `fill=num_pages`.

Numerical contract: bit-matches `models.gpt.gpt_forward` greedy decode
when scale factors are exact binary fractions (head_dim a power of two)
— the end-to-end parity test in tests/test_serving.py pins this.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..models.gpt import (GPTConfig, _causal_attention, _ln,
                          decoder_tail, init_gpt_params)
from ..ops.paged_attention import paged_attention_decode

__all__ = ["GPTDecodeModel"]


class GPTDecodeModel:
    """Serving adapter around the functional GPT core.

    The engine owns jit/donation/bucketing; everything here is pure."""

    def __init__(self, cfg: GPTConfig, params=None, seed: int = 0,
                 attn_impl: str | None = None):
        self.cfg = cfg
        self.params = params if params is not None \
            else init_gpt_params(cfg, seed)
        self.params = jax.tree_util.tree_map(jnp.asarray, self.params)
        self.head_dim = cfg.hidden_size // cfg.num_heads
        self.attn_impl = attn_impl  # None = auto (ops/autobench gate)
        # the engine caps admission at this (positions past wpe would
        # silently clip under jnp.take)
        self.max_positions = cfg.max_position_embeddings

    # -- checkpoint warm-start (paddle_tpu.checkpoint) ------------------
    def save_checkpoint(self, root: str, step: int | None = None) -> int:
        """Persist the param pytree through the checkpoint store
        (content-addressed chunks; repeated saves of a mostly-unchanged
        model dedup). Keys are tree paths, structure comes from the
        config at load time — no pickle anywhere."""
        import dataclasses
        from ..checkpoint import CheckpointStore
        leaves, _treedef = jax.tree_util.tree_flatten_with_path(
            self.params)
        arrays = {jax.tree_util.keystr(path): leaf
                  for path, leaf in leaves}
        return CheckpointStore(root).save(
            arrays, step=step,
            meta={"kind": "gpt-decode",
                  "cfg": dataclasses.asdict(self.cfg)})

    @classmethod
    def from_checkpoint(cls, root: str, step: int | None = None,
                        attn_impl: str | None = None,
                        cfg: "GPTConfig | None" = None) \
            -> "GPTDecodeModel":
        """Rebuild a decode model from a committed manifest: the config
        rides the manifest meta (overridable), a template pytree from it
        supplies the structure, and every leaf is restored by tree-path
        key. The serving engine's warm-start entry."""
        from ..checkpoint import CheckpointStore
        from ..models.gpt import GPTConfig
        store = CheckpointStore(root)
        arrays, meta = store.restore(step)
        if cfg is None:
            mcfg = (meta or {}).get("cfg")
            if not mcfg:
                raise ValueError(
                    f"manifest under {root} has no model config — pass "
                    f"cfg= explicitly")
            cfg = GPTConfig(**mcfg)
        model = cls(cfg, attn_impl=attn_impl)
        model.adopt_checkpoint(model._prepare_params(arrays, root))
        return model

    def read_checkpoint(self, root: str, step: int | None = None):
        """Disk + host->device phase of load_checkpoint: fetch the
        arrays AND build the complete replacement pytree
        (device-resident, dtype-cast against the live tree's
        structure) without touching live params. Engine.warm_start
        runs this off the step lock so serving overlaps both the read
        and the upload; the adopt_checkpoint flip is then a pure
        reference swap."""
        from ..checkpoint import CheckpointStore
        arrays, _meta = CheckpointStore(root).restore(step)
        return self._prepare_params(arrays, root)

    def adopt_checkpoint(self, prepared) -> "GPTDecodeModel":
        """Flip phase: adopt a pytree built by read_checkpoint /
        _prepare_params. One reference assignment — O(1) under the
        engine step lock, no disk, no host->device transfer."""
        self.params = prepared
        return self

    def load_checkpoint(self, root: str, step: int | None = None) \
            -> "GPTDecodeModel":
        """Swap this model's weights in place from a committed
        manifest (same structure required) — no throwaway model init,
        which matters when warm-starting a live engine on big
        configs."""
        return self.adopt_checkpoint(self.read_checkpoint(root, step))

    def _prepare_params(self, arrays: dict, root: str):
        """The replacement param pytree from tree-path-keyed arrays,
        using the CURRENT params as structural template (read-only;
        safe concurrent with a live engine decoding on the old
        tree)."""
        template, treedef = jax.tree_util.tree_flatten_with_path(
            self.params)
        leaves = []
        for path, tmpl in template:
            key = jax.tree_util.keystr(path)
            if key not in arrays:
                raise KeyError(f"checkpoint under {root} is missing "
                               f"param {key}")
            leaves.append(jnp.asarray(arrays[key],
                                      dtype=tmpl.dtype))
        return jax.tree_util.tree_unflatten(treedef, leaves)

    # -- cache ---------------------------------------------------------
    def init_cache(self, num_pages: int, page_size: int):
        """[L, num_pages+1, ps, H, d] zero pools (last page = trash)."""
        cfg = self.cfg
        dt = jnp.dtype(cfg.amp_dtype) if cfg.amp_dtype else jnp.float32
        shape = (cfg.num_layers, num_pages + 1, page_size,
                 cfg.num_heads, self.head_dim)
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}

    def apply_defrag(self, cache, mapping: dict[int, int]):
        """Move live pages per defrag_plan's old->new mapping (host-side
        plan, one device gather per pool)."""
        if not mapping:
            return cache
        P = cache["k"].shape[1]
        perm = list(range(P))
        for old, new in mapping.items():
            perm[new] = old
        perm = jnp.asarray(perm, jnp.int32)
        return {"k": cache["k"][:, perm], "v": cache["v"][:, perm]}

    # -- layer math (mirrors models.gpt.gpt_block_fn) -------------------
    def _qkv(self, p, h):
        q = h @ p["wq"] + p["bq"]
        k = h @ p["wk"] + p["bk"]
        v = h @ p["wv"] + p["bv"]
        return q, k, v

    # (the post-attention tail — out-projection + residual + LN2 + FFN —
    # is models.gpt.decoder_tail: one source of truth with training, and
    # the serving decode path reuses the same autobench-gated fused
    # Pallas sub-blocks where they win)

    # -- prefill -------------------------------------------------------
    def prefill(self, params, cache, tokens, true_len, page_row):
        """tokens [T] int32 (padded bucket), true_len scalar int32,
        page_row [M] int32 (fill = trash). Returns (cache, logits [V])."""
        cfg = self.cfg
        H, d = cfg.num_heads, self.head_dim
        T = tokens.shape[0]
        ps = cache["k"].shape[2]
        n_pages = T // ps
        x = jnp.take(params["wte"], tokens, axis=0) \
            + params["wpe"][:T]                               # [T, D]

        def body(carry, xs):
            x, ck, cv = carry
            p, l = xs
            h = _ln(x, p["ln1_s"], p["ln1_b"], cfg.layer_norm_eps)
            q, k, v = self._qkv(p, h)
            kp = k.reshape(n_pages, ps, H, d).astype(ck.dtype)
            vp = v.reshape(n_pages, ps, H, d).astype(cv.dtype)
            ck = ck.at[l, page_row[:n_pages]].set(kp)
            cv = cv.at[l, page_row[:n_pages]].set(vp)
            # ONE source of truth for the dense math: the serving parity
            # contract (prefill == models.gpt forward, bit-for-bit) holds
            # by construction, not by a hand-mirrored copy
            a = _causal_attention(q[None], k[None], v[None], H,
                                  impl="xla")[0]
            x = decoder_tail(p, a, x, cfg)
            return (x, ck, cv), None

        L = cfg.num_layers
        (x, ck, cv), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]),
            (params["blocks"], jnp.arange(L)))
        xlast = jax.lax.dynamic_index_in_dim(x, true_len - 1, 0,
                                             keepdims=False)
        xlast = _ln(xlast, params["lnf_s"], params["lnf_b"],
                    cfg.layer_norm_eps)
        logits = xlast.astype(jnp.float32) \
            @ params["wte"].T.astype(jnp.float32)
        return {"k": ck, "v": cv}, logits

    # -- tail prefill (shared-prefix admission) ------------------------
    def prefill_tail(self, params, cache, tokens, start, true_len,
                     page_row):
        """Prefill ONLY the unmatched tail of a prompt whose first
        `start` tokens (page-aligned) were found in the prefix cache
        with their KV already resident: tokens [T] int32 (padded tail
        bucket), start scalar int32 (page-aligned logical offset),
        true_len scalar int32 (real tail length), page_row [M] int32
        (matched + owned pages, fill = trash). Returns
        (cache, logits [V]) — the logits of the last real tail position.

        Numerics: each tail position is computed exactly like a decode
        step for that position — K/V scattered into its page, then
        ragged paged attention over the request's own history with
        ctx = position + 1 — so the greedy-parity contract the decode
        path pins (bit-match vs the dense forward) carries over to
        shared-prefix admissions unchanged."""
        import jax
        cfg = self.cfg
        H, d = cfg.num_heads, self.head_dim
        T = tokens.shape[0]
        ps = cache["k"].shape[2]
        n_pages = T // ps
        positions = start + jnp.arange(T, dtype=jnp.int32)
        x = jnp.take(params["wte"], tokens, axis=0) \
            + jnp.take(params["wpe"], positions, axis=0)       # [T, D]
        # every tail token shares the request's page row; per-token
        # causal masking rides the ctx lengths, as in decode
        tables = jnp.broadcast_to(page_row[None, :],
                                  (T, page_row.shape[0]))
        ctx = positions + 1
        # the tail's own pages: page_row[start//ps : start//ps + T//ps]
        tail_pages = jax.lax.dynamic_slice_in_dim(
            page_row, start // ps, n_pages)

        def body(carry, xs):
            x, ck, cv = carry
            p, l = xs
            h = _ln(x, p["ln1_s"], p["ln1_b"], cfg.layer_norm_eps)
            q, k, v = self._qkv(p, h)
            kp = k.reshape(n_pages, ps, H, d).astype(ck.dtype)
            vp = v.reshape(n_pages, ps, H, d).astype(cv.dtype)
            ck = ck.at[l, tail_pages].set(kp)
            cv = cv.at[l, tail_pages].set(vp)
            a = paged_attention_decode(
                q.reshape(T, H, d), ck[l], cv[l], tables, ctx,
                scale=1.0 / math.sqrt(d), impl=self.attn_impl)
            x = decoder_tail(p, a.reshape(T, -1), x, cfg)
            return (x, ck, cv), None

        L = cfg.num_layers
        (x, ck, cv), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]),
            (params["blocks"], jnp.arange(L)))
        xlast = jax.lax.dynamic_index_in_dim(x, true_len - 1, 0,
                                             keepdims=False)
        xlast = _ln(xlast, params["lnf_s"], params["lnf_b"],
                    cfg.layer_norm_eps)
        logits = xlast.astype(jnp.float32) \
            @ params["wte"].T.astype(jnp.float32)
        return {"k": ck, "v": cv}, logits

    def copy_pages(self, cache, src, dst):
        """Copy page contents src[i] -> dst[i] across every layer pool —
        the copy-on-write step for a full-prompt bootstrap admission
        (one small device gather/scatter per pool, outside jit)."""
        src = jnp.asarray(src, jnp.int32)
        dst = jnp.asarray(dst, jnp.int32)
        return {"k": cache["k"].at[:, dst].set(cache["k"][:, src]),
                "v": cache["v"].at[:, dst].set(cache["v"][:, src])}

    # -- decode --------------------------------------------------------
    def decode(self, params, cache, tokens, positions, tables):
        """tokens/positions [S] int32, tables [S, M] int32 (fill = trash;
        inactive slots = all-trash rows with position 0). Returns
        (cache, logits [S, V])."""
        cfg = self.cfg
        H, d = cfg.num_heads, self.head_dim
        S = tokens.shape[0]
        ps = cache["k"].shape[2]
        x = jnp.take(params["wte"], tokens, axis=0) \
            + jnp.take(params["wpe"], positions, axis=0)       # [S, D]
        page_of = jnp.take_along_axis(
            tables, (positions // ps)[:, None], axis=1)[:, 0]  # [S]
        off = positions % ps
        ctx = positions + 1

        def body(carry, xs):
            x, ck, cv = carry
            p, l = xs
            h = _ln(x, p["ln1_s"], p["ln1_b"], cfg.layer_norm_eps)
            q, k, v = self._qkv(p, h)
            ck = ck.at[l, page_of, off].set(
                k.reshape(S, H, d).astype(ck.dtype))
            cv = cv.at[l, page_of, off].set(
                v.reshape(S, H, d).astype(cv.dtype))
            a = paged_attention_decode(
                q.reshape(S, H, d), ck[l], cv[l], tables, ctx,
                scale=1.0 / math.sqrt(d), impl=self.attn_impl)
            x = decoder_tail(p, a.reshape(S, -1), x, cfg)
            return (x, ck, cv), None

        L = cfg.num_layers
        (x, ck, cv), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]),
            (params["blocks"], jnp.arange(L)))
        x = _ln(x, params["lnf_s"], params["lnf_b"], cfg.layer_norm_eps)
        logits = x.astype(jnp.float32) \
            @ params["wte"].T.astype(jnp.float32)
        return {"k": ck, "v": cv}, logits
