"""paddle.io — Dataset / DataLoader (reference python/paddle/io/ +
fluid/reader.py:123 + fluid/dataloader/dataloader_iter.py).

TPU-native data pipeline, two regimes:
  * num_workers=0 — a background thread prefetches+collates into a
    bounded queue (double buffering; collation is numpy and releases the
    GIL, so the overlap is real).
  * num_workers>0 — forked worker processes pull index batches from
    per-worker queues, collate, and stream results back over an output
    queue; the parent reorders by batch id (the reference's
    _DataLoaderIterMultiProcess with _order outstanding map).
"""
from __future__ import annotations

import multiprocessing as _mp
import queue as _queue
import threading
import traceback as _tb

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "BatchSampler",
           "Sampler", "SequenceSampler", "RandomSampler", "DataLoader",
           "random_split", "Subset", "WorkerInfo", "get_worker_info"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(np.asarray(t.numpy() if hasattr(t, "numpy") else t)[idx]
                     for t in self.tensors)

    def __len__(self):
        t = self.tensors[0]
        return len(t.numpy() if hasattr(t, "numpy") else t)


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    idx = np.random.permutation(len(dataset))
    parts, off = [], 0
    for n in lengths:
        parts.append(Subset(dataset, idx[off:off + n].tolist()))
        off += n
    return parts


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    arr = np.stack([np.asarray(s) for s in batch])
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return arr


class DataLoader:
    """Batched iterator with background prefetch (reference reader.py:123
    DataLoader + reader/buffered_reader.cc double buffering)."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.prefetch = max(2, prefetch_factor) if use_buffer_reader else 0
        self.num_workers = max(0, int(num_workers))
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif isinstance(dataset, IterableDataset):
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self.batch_sampler is None:
            raise TypeError("IterableDataset has no length")
        return len(self.batch_sampler)

    def _gen_batches(self):
        if self.batch_sampler is None:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        for indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        if self.num_workers > 0:
            yield from _MultiprocessIter(self)
            return
        if not self.prefetch:
            yield from self._gen_batches()
            return
        q: _queue.Queue = _queue.Queue(maxsize=self.prefetch)
        _SENTINEL = object()
        err = []

        def worker():
            try:
                for b in self._gen_batches():
                    q.put(b)
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                q.put(_SENTINEL)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _SENTINEL:
                break
            yield item
        if err:
            raise err[0]


# ---------------------------------------------------------------------------
# multiprocess workers (reference fluid/dataloader/dataloader_iter.py
# _DataLoaderIterMultiProcess + worker.py _worker_loop)
# ---------------------------------------------------------------------------

class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info: WorkerInfo | None = None


def get_worker_info():
    """Inside a DataLoader worker process: (id, num_workers, dataset) —
    what IterableDataset shards on (reference worker.py get_worker_info)."""
    return _worker_info


def _map_worker_loop(dataset, collate_fn, index_queue, out_queue,
                     worker_id, num_workers, init_fn):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset)
    if init_fn is not None:
        init_fn(worker_id)
    while True:
        item = index_queue.get()
        if item is None:
            break
        bid, indices = item
        try:
            out_queue.put((bid, collate_fn([dataset[i] for i in indices]),
                           None))
        except BaseException:
            out_queue.put((bid, None, _tb.format_exc()))


def _iter_worker_loop(dataset, collate_fn, batch_size, drop_last,
                      out_queue, worker_id, num_workers, init_fn):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset)
    if init_fn is not None:
        init_fn(worker_id)
    try:
        batch = []
        for sample in dataset:
            batch.append(sample)
            if len(batch) == batch_size:
                out_queue.put((-1, collate_fn(batch), None))
                batch = []
        if batch and not drop_last:
            out_queue.put((-1, collate_fn(batch), None))
        out_queue.put((-2, worker_id, None))  # worker drained
    except BaseException:
        out_queue.put((-1, None, _tb.format_exc()))


class _MultiprocessIter:
    """Order-preserving fan-out over forked workers. Map-style datasets
    get round-robin index batches and a reorder buffer; iterable datasets
    stream unordered (each worker owns its iterator copy — shard with
    get_worker_info, reference semantics)."""

    def __init__(self, loader: "DataLoader"):
        self.loader = loader
        self.nw = loader.num_workers
        self.timeout = loader.timeout or None
        self._procs: list = []

    def _start_map(self, ctx):
        ld = self.loader
        self.out_q = ctx.Queue()
        self.idx_qs = [ctx.Queue() for _ in range(self.nw)]
        for wid in range(self.nw):
            p = ctx.Process(
                target=_map_worker_loop,
                args=(ld.dataset, ld.collate_fn, self.idx_qs[wid],
                      self.out_q, wid, self.nw, ld.worker_init_fn),
                daemon=True)
            p.start()
            self._procs.append(p)

    def __iter__(self):
        ld = self.loader
        ctx = _mp.get_context("fork")
        try:
            if ld.batch_sampler is None:
                yield from self._run_iterable(ctx)
            else:
                yield from self._run_map(ctx)
        finally:
            self._shutdown()

    def _run_map(self, ctx):
        ld = self.loader
        self._start_map(ctx)
        batches = list(ld.batch_sampler)
        for bid, indices in enumerate(batches):
            self.idx_qs[bid % self.nw].put((bid, indices))
        for q in self.idx_qs:
            q.put(None)
        pending: dict = {}
        next_bid = 0
        got = 0
        while got < len(batches):
            bid, data, err = self._get()
            if err is not None:
                raise RuntimeError(
                    f"DataLoader worker raised:\n{err}")
            pending[bid] = data
            got += 1
            while next_bid in pending:
                yield pending.pop(next_bid)
                next_bid += 1

    def _run_iterable(self, ctx):
        ld = self.loader
        self.out_q = ctx.Queue()
        for wid in range(self.nw):
            p = ctx.Process(
                target=_iter_worker_loop,
                args=(ld.dataset, ld.collate_fn, ld.batch_size,
                      ld.drop_last, self.out_q, wid, self.nw,
                      ld.worker_init_fn),
                daemon=True)
            p.start()
            self._procs.append(p)
        alive = self.nw
        while alive:
            bid, data, err = self._get()
            if err is not None:
                raise RuntimeError(f"DataLoader worker raised:\n{err}")
            if bid == -2:
                alive -= 1
                continue
            yield data

    def _get(self):
        try:
            return self.out_q.get(timeout=self.timeout)
        except _queue.Empty:
            raise RuntimeError(
                f"DataLoader timed out after {self.timeout}s waiting on "
                f"workers (dead worker or too-slow dataset)") from None

    def _shutdown(self):
        for p in self._procs:
            if p.is_alive():
                p.terminate()
        for p in self._procs:
            p.join(timeout=5)
        self._procs.clear()
