"""paddle.io — Dataset / DataLoader (reference python/paddle/io/ +
fluid/reader.py:123).

TPU-native data pipeline: host-side worker threads prefetch+collate batches
into a bounded queue (double buffering), the executor moves them to device
asynchronously. (A C++ shared-memory loader backs `num_workers>0` in a later
round; thread-based prefetch is already overlap-effective because collation
is numpy and releases the GIL.)
"""
from __future__ import annotations

import queue as _queue
import threading

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "BatchSampler",
           "Sampler", "SequenceSampler", "RandomSampler", "DataLoader",
           "random_split", "Subset"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(np.asarray(t.numpy() if hasattr(t, "numpy") else t)[idx]
                     for t in self.tensors)

    def __len__(self):
        t = self.tensors[0]
        return len(t.numpy() if hasattr(t, "numpy") else t)


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    idx = np.random.permutation(len(dataset))
    parts, off = [], 0
    for n in lengths:
        parts.append(Subset(dataset, idx[off:off + n].tolist()))
        off += n
    return parts


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    arr = np.stack([np.asarray(s) for s in batch])
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return arr


class DataLoader:
    """Batched iterator with background prefetch (reference reader.py:123
    DataLoader + reader/buffered_reader.cc double buffering)."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.prefetch = max(2, prefetch_factor) if use_buffer_reader else 0
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif isinstance(dataset, IterableDataset):
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self.batch_sampler is None:
            raise TypeError("IterableDataset has no length")
        return len(self.batch_sampler)

    def _gen_batches(self):
        if self.batch_sampler is None:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        for indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        if not self.prefetch:
            yield from self._gen_batches()
            return
        q: _queue.Queue = _queue.Queue(maxsize=self.prefetch)
        _SENTINEL = object()
        err = []

        def worker():
            try:
                for b in self._gen_batches():
                    q.put(b)
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                q.put(_SENTINEL)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _SENTINEL:
                break
            yield item
        if err:
            raise err[0]
