"""Postmortem debug bundles — one atomic directory of crash evidence.

A bundle freezes every observability surface of one process at one
instant: the metrics registry (Prometheus text AND the JSON dump
format), the trace ring as Chrome ``trace_event`` JSON, the flight
recorder rings, environment/config/version info, and the in-flight
request table of whichever engines registered a provider. Bundles are
written:

  * on watchdog fire (``observability.watchdog``);
  * on unhandled exceptions (``install_crash_hooks`` — ``sys`` and
    ``threading`` excepthooks, chained, gated on
    ``PADDLE_TPU_DEBUG_DIR``);
  * on the SIGTERM dump hook (``observability.__init__`` — the path
    ``launch.py`` uses to stop children);
  * on demand via the ``debug_dump`` verb of the serving frontend and
    every PS server (``dump_verb`` is the shared handler), and
    directly via ``write_bundle()``.

Crash consistency mirrors the PR-4 checkpoint store: files are written
into a hidden temp directory, ``MANIFEST.json`` (CRC32 + size per
file) lands last, and one ``os.rename`` of the directory is the commit
point — a torn bundle is never visible under its final name.
``load_bundle`` re-verifies every CRC.

Layout (under ``PADDLE_TPU_DEBUG_DIR`` / ``launch.py --debug_dir``):

    bundle_<host>_<pid>_<ms>_<seq>/
      MANIFEST.json   reason, host, pid, time, {file: {crc32, bytes}}
      metrics.prom    Prometheus text exposition
      metrics.json    registry JSON dump (aggregatable across ranks)
      trace.json      Chrome trace_event export of the span ring
      flight.json     flight-recorder snapshot (per-tier event rings)
      env.json        PADDLE_*/JAX_*/XLA_* env, argv, versions
      requests.json   per-provider in-flight request tables

``python -m paddle_tpu.observability.registry <dir>`` lists the
bundles of a multi-rank job and merges their ``metrics.json`` into the
job aggregate (``aggregate_with_bundles``).
"""
from __future__ import annotations

import itertools
import json
import os
import platform
import socket
import sys
import threading
import time
import zlib

from . import flight as _flight
from . import registry as _registry
from . import tracing as _tracing

__all__ = ["BundleError", "BUNDLE_PREFIX", "collect", "write_bundle",
           "dump_verb", "load_bundle", "list_bundles",
           "aggregate_with_bundles", "register_requests_provider",
           "unregister_requests_provider", "install_crash_hooks"]

BUNDLE_PREFIX = "bundle_"
BUNDLE_VERSION = 1

_seq = itertools.count()


class BundleError(ValueError):
    """Missing/corrupt bundle file (CRC or manifest mismatch)."""


# ---------------------------------------------------------------------------
# in-flight request providers (the serving engine registers one per
# instance; anything owning request state can too)
# ---------------------------------------------------------------------------

_providers: dict[str, object] = {}
_providers_lock = threading.Lock()


def register_requests_provider(key: str, fn):
    """``fn()`` -> JSON-safe summary of the owner's in-flight work
    (or None once the owner is gone — the provider is then dropped).
    Providers run inside ``collect`` and must never block on the locks
    a wedged tier might hold."""
    with _providers_lock:
        _providers[key] = fn


def unregister_requests_provider(key: str):
    with _providers_lock:
        _providers.pop(key, None)


def _requests_snapshot() -> dict:
    with _providers_lock:
        items = list(_providers.items())
    out, dead = {}, []
    for key, fn in items:
        try:
            v = fn()
        except Exception as e:
            out[key] = {"error": f"{type(e).__name__}: {e}"}
            continue
        if v is None:
            dead.append(key)
        else:
            out[key] = v
    for key in dead:
        unregister_requests_provider(key)
    return out


# ---------------------------------------------------------------------------
# collection
# ---------------------------------------------------------------------------

_ENV_PREFIXES = ("PADDLE_", "JAX_", "XLA_", "FLAGS_", "TPU_",
                 "BENCH_", "TRAINING_ROLE", "POD_IP")
# never let credentials (e.g. PADDLE_PS_SECRET, the HMAC shared
# secret) land in a bundle that gets copied around or returned over
# the wire by the debug_dump verb
_SECRET_MARKERS = ("SECRET", "TOKEN", "PASSWORD", "CREDENTIAL")


def _env_value(key: str, val: str) -> str:
    up = key.upper()
    if any(m in up for m in _SECRET_MARKERS) or up.endswith("_KEY"):
        return "<redacted>"
    return val


def _env_info() -> dict:
    versions: dict[str, object] = {"python": sys.version}
    for mod in ("jax", "jaxlib", "numpy"):
        try:
            versions[mod] = __import__(mod).__version__
        except Exception:
            versions[mod] = None
    return {"argv": list(sys.argv), "cwd": os.getcwd(),
            "platform": platform.platform(),
            "env": {k: _env_value(k, v)
                    for k, v in sorted(os.environ.items())
                    if k.startswith(_ENV_PREFIXES)},
            "versions": versions}


def collect(reason: str = "manual", extra=None) -> dict:
    """Gather every section of a bundle in memory (JSON-safe — this is
    exactly what the ``debug_dump`` verb returns over the wire)."""
    out = {
        "version": BUNDLE_VERSION, "reason": reason,
        "host": socket.gethostname(), "pid": os.getpid(),
        "time": time.time(), "monotonic": time.monotonic(),
        "metrics_text": _registry.prometheus_text(),
        "metrics": _registry.to_dict(),
        "trace": _tracing.TRACER.export_chrome_trace(),
        "flight": _flight.RECORDER.snapshot(),
        "env": _env_info(),
        "requests": _requests_snapshot(),
    }
    if extra is not None:
        out["extra"] = extra
    return out


# ---------------------------------------------------------------------------
# write / read
# ---------------------------------------------------------------------------

def _bundle_files(bundle: dict) -> dict[str, bytes]:
    def j(obj) -> bytes:
        return json.dumps(obj, indent=1, sort_keys=True).encode("utf-8")

    files = {
        "metrics.prom": bundle["metrics_text"].encode("utf-8"),
        "metrics.json": j(bundle["metrics"]),
        "trace.json": j(bundle["trace"]),
        "flight.json": j(bundle["flight"]),
        "env.json": j(bundle["env"]),
        "requests.json": j(bundle["requests"]),
    }
    if bundle.get("extra") is not None:
        # caller-supplied context (e.g. the launcher's crash-loop
        # postmortem naming the flapping rank) must survive to disk
        files["extra.json"] = j(bundle["extra"])
    return files


def write_bundle(dir_: str | None = None, reason: str = "manual",
                 extra=None, bundle: dict | None = None) -> str:
    """Write one atomic bundle directory; returns its path. The
    directory rename is the commit point — a crash mid-write leaves
    only a hidden ``.tmp_*`` turd, never a half bundle."""
    d = dir_ or os.environ.get("PADDLE_TPU_DEBUG_DIR")
    if not d:
        raise ValueError("no bundle directory: pass dir_ or set "
                         "PADDLE_TPU_DEBUG_DIR")
    if bundle is None:
        bundle = collect(reason=reason, extra=extra)
    files = _bundle_files(bundle)
    name = (f"{BUNDLE_PREFIX}{bundle['host']}_{bundle['pid']}_"
            f"{int(bundle['time'] * 1000)}_{next(_seq)}")
    tmp = os.path.join(d, f".tmp_{name}")
    os.makedirs(tmp, exist_ok=True)
    manifest = {"version": BUNDLE_VERSION, "reason": bundle["reason"],
                "host": bundle["host"], "pid": bundle["pid"],
                "time": bundle["time"],
                "files": {fn: {"crc32": zlib.crc32(data),
                               "bytes": len(data)}
                          for fn, data in files.items()}}
    for fn, data in files.items():
        with open(os.path.join(tmp, fn), "wb") as f:
            f.write(data)
    with open(os.path.join(tmp, "MANIFEST.json"), "w",
              encoding="utf-8") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    final = os.path.join(d, name)
    os.rename(tmp, final)
    # bundle writes show up on the fleet dashboard (path + reason)
    # when a telemetry agent is armed; silent no-op otherwise
    from . import agent as _agent
    _agent.publish_event("bundle", reason=bundle["reason"],
                         path=final)
    return final


def dump_verb(req: dict | None = None,
              reason: str = "debug_dump") -> dict:
    """Shared handler behind the serving-frontend and PS `debug_dump`
    verbs: collect a bundle, persist it into the OPERATOR-configured
    PADDLE_TPU_DEBUG_DIR (``req['write']=False`` skips disk), and
    return the in-memory bundle + its path. The destination is
    deliberately NOT wire-controlled — a network peer must never pick
    a server-side filesystem path to write to."""
    req = req or {}
    bundle = collect(reason=reason)
    path = None
    if req.get("write", True):
        d = os.environ.get("PADDLE_TPU_DEBUG_DIR")
        if d:
            try:
                path = write_bundle(d, bundle=bundle)
            except Exception as e:
                bundle["write_error"] = f"{type(e).__name__}: {e}"
    bundle["path"] = path
    return bundle


def load_bundle(path: str, verify: bool = True) -> dict:
    """Read a bundle back; ``verify`` re-checks every CRC32 (raises
    BundleError on mismatch/missing files). JSON files are parsed,
    ``metrics.prom`` comes back as text."""
    mpath = os.path.join(path, "MANIFEST.json")
    try:
        with open(mpath, encoding="utf-8") as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise BundleError(f"unreadable manifest {mpath}: {e}") from None
    files = {}
    for fn, info in manifest.get("files", {}).items():
        fpath = os.path.join(path, fn)
        try:
            with open(fpath, "rb") as f:
                data = f.read()
        except OSError as e:
            raise BundleError(f"missing bundle file {fpath}: {e}") \
                from None
        if verify and zlib.crc32(data) != info.get("crc32"):
            raise BundleError(f"crc mismatch in {fpath}")
        if fn.endswith(".json"):
            files[fn] = json.loads(data.decode("utf-8"))
        else:
            files[fn] = data.decode("utf-8")
    return {"path": path, "manifest": manifest, "files": files}


# ---------------------------------------------------------------------------
# multi-rank listing / aggregation (launch.py --debug_dir)
# ---------------------------------------------------------------------------

def _scan_bundles(dir_: str) -> list[tuple[dict, dict | None]]:
    """One verified read per bundle: (summary, loaded-or-None)."""
    out = []
    try:
        names = sorted(os.listdir(dir_))
    except OSError:
        return out
    for name in names:
        path = os.path.join(dir_, name)
        if not name.startswith(BUNDLE_PREFIX) or not os.path.isdir(path):
            continue
        rec = {"path": path, "name": name, "valid": False}
        loaded = None
        try:
            loaded = load_bundle(path, verify=True)
            m = loaded["manifest"]
            rec.update(reason=m.get("reason"), host=m.get("host"),
                       pid=m.get("pid"), time=m.get("time"),
                       valid=True)
        except BundleError as e:
            rec["error"] = str(e)
        out.append((rec, loaded))
    out.sort(key=lambda rb: rb[0].get("time") or 0)
    return out


def list_bundles(dir_: str) -> list[dict]:
    """Summaries of every committed bundle under ``dir_`` (sorted by
    time): reason/host/pid/time plus a CRC verification verdict."""
    return [rec for rec, _b in _scan_bundles(dir_)]


def aggregate_with_bundles(dir_: str) -> dict:
    """Job-level merge: the per-process ``metrics_*.json`` dumps PLUS
    bundle metrics, aggregated with the registry rules (counters and
    histograms sum, gauges keep newest), and a ``bundles`` listing
    when any exist. Registry snapshots from the SAME process overlap
    (a watchdog-fire bundle, a later SIGTERM bundle, the exit-time
    metrics dump all cover one counter history), so only the NEWEST
    snapshot per (host, pid) contributes — summing them would
    double-count that rank."""
    # (host, pid) -> (time, metrics dump); newest snapshot wins. A
    # dump with no process identity gets a unique key and always
    # contributes.
    newest: dict[tuple, tuple[float, dict]] = {}

    def offer(key, t, dump):
        if key[1] is None:
            key = (key[0], object())
        cur = newest.get(key)
        if cur is None or t >= cur[0]:
            newest[key] = (t, dump)

    try:
        names = sorted(os.listdir(dir_))
    except OSError:
        names = []
    for fn in names:
        if fn.startswith("metrics_") and fn.endswith(".json"):
            try:
                with open(os.path.join(dir_, fn),
                          encoding="utf-8") as f:
                    d = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            offer((d.get("host"), d.get("pid")), d.get("time") or 0, d)
    scanned = _scan_bundles(dir_)
    for rec, loaded in scanned:
        if not rec["valid"] or loaded is None:
            continue
        m = loaded["files"].get("metrics.json")
        if m is not None:
            offer((rec.get("host"), rec.get("pid")),
                  rec.get("time") or 0, m)
    agg = _registry.aggregate_dumps([d for _t, d in newest.values()])
    if scanned:
        agg["bundles"] = [
            {k: rec.get(k) for k in ("name", "reason", "host", "pid",
                                     "time", "valid", "error")
             if k in rec} for rec, _b in scanned]
    return agg


# ---------------------------------------------------------------------------
# crash hooks (unhandled exceptions)
# ---------------------------------------------------------------------------

_hooks_installed = False


def try_write_bundle(reason: str, dir_: str | None = None) -> str | None:
    """Best-effort bundle write: None when no debug dir is configured
    (``dir_`` or ``PADDLE_TPU_DEBUG_DIR``) or the write fails — the
    crash/stall/teardown paths that call this must never be masked by a
    failing dump."""
    if not (dir_ or os.environ.get("PADDLE_TPU_DEBUG_DIR")):
        return None
    try:
        return write_bundle(dir_, reason=reason)
    except Exception:
        return None


def arm_hard_exit(code: int = 143, grace_s: float = 10.0,
                  name: str = "postmortem-hard-exit") -> threading.Thread:
    """Arm a daemon thread that ``os._exit(code)``s after ``grace_s`` —
    bounds the cost of a dump or signal handler that can never finish
    (wedged main thread, a non-reentrant lock held by the interrupted
    frame). Whatever evidence made it to disk stands."""
    def _escalate():
        time.sleep(grace_s)
        os._exit(code)

    t = threading.Thread(target=_escalate, daemon=True, name=name)
    t.start()
    return t


def install_crash_hooks():
    """Chain bundle writes onto sys.excepthook and threading.excepthook
    (idempotent; KeyboardInterrupt/SystemExit excluded). Gated at dump
    time on PADDLE_TPU_DEBUG_DIR so installing is always safe."""
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True

    prev_sys = sys.excepthook

    def _sys_hook(exc_type, exc, tb):
        if isinstance(exc, Exception):
            try_write_bundle(f"excepthook:{exc_type.__name__}")
        prev_sys(exc_type, exc, tb)

    sys.excepthook = _sys_hook

    prev_thread = threading.excepthook

    def _thread_hook(args):
        if isinstance(args.exc_value, Exception):
            try_write_bundle(
                f"thread-excepthook:{args.exc_type.__name__}")
        prev_thread(args)

    threading.excepthook = _thread_hook
