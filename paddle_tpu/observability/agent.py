"""Telemetry agent: per-process streamer to the fleet collector.

One ``TelemetryAgent`` per process taps the local telemetry substrate
(finished spans via ``Tracer.set_sink``, flight events via
``FlightRecorder.set_sink``, discrete events like watchdog stalls via
``publish_event``) into a **bounded drop-oldest queue**, and a single
daemon sender thread batches the queue over the mux RPC wire to a
``TelemetryCollector`` (``observability.collector``) as ``tel_push``
calls. Periodic clock-sync pings (``tel_ping`` RTT midpoints, smallest
RTT wins) ride along so the collector can align this process's
monotonic span clocks onto its own wall clock.

Hard rules, in priority order:

  * **serving is never blocked by telemetry** — the sinks are one
    deque append under a tiny agent lock; ALL socket IO lives on the
    sender thread, which holds no lock any serving path takes;
  * **overload drops oldest, visibly** — the queue is bounded
    (``PADDLE_TPU_TELEMETRY_QUEUE``); overwrites increment
    ``paddle_tpu_telemetry_agent_dropped_total{kind}`` exactly like
    the flight rings' drop accounting;
  * **a dead collector costs one failed send per flush** — sends are
    single-attempt with a short timeout; failures drop the batch
    (counted), back off, and the next flush reconnects (the
    ``pub_watch`` re-subscribe idiom).

Arming: ``PADDLE_TPU_TELEMETRY_COLLECTOR=host:port`` auto-starts the
process agent at ``paddle_tpu.observability`` import (the watchdog
autostart pattern), or call ``arm(endpoint)`` explicitly.
"""
from __future__ import annotations

import os
import socket
import sys
import threading
import time
from collections import deque

from . import flight as _flight
from . import registry as _obs
from . import tracing as _tracing

__all__ = ["TelemetryAgent", "arm", "disarm", "get_agent",
           "publish_event", "maybe_start_from_env"]

_DROPPED = _obs.counter(
    "paddle_tpu_telemetry_agent_dropped_total",
    "telemetry items dropped by the agent (full queue, or a failed "
    "send discarding its batch), by item kind", ["kind"])
_BATCHES = _obs.counter(
    "paddle_tpu_telemetry_agent_batches_total",
    "tel_push batches successfully delivered to the collector")
_SEND_ERRORS = _obs.counter(
    "paddle_tpu_telemetry_agent_send_errors_total",
    "tel_push/tel_ping attempts that failed (collector down or slow)")

# same redaction contract as debug bundles: credential-looking attr
# keys never leave the process
_SECRET_MARKERS = ("SECRET", "TOKEN", "PASSWORD", "CREDENTIAL")


def _redact_attrs(attrs: dict) -> dict:
    out = {}
    for k, v in attrs.items():
        ku = str(k).upper()
        if any(m in ku for m in _SECRET_MARKERS) or ku.endswith("_KEY"):
            out[str(k)] = "<redacted>"
        else:
            out[str(k)] = _flight._safe(v)
    return out


def _span_dict(sp) -> dict:
    d = {"name": sp.name, "trace_id": sp.trace_id,
         "span_id": sp.span_id, "parent_id": sp.parent_id,
         "start": sp.start, "end": sp.end, "tid": sp.tid}
    if sp.attrs:
        d["attrs"] = _redact_attrs(sp.attrs)
    return d


class TelemetryAgent:
    """See module docstring. One instance per process (via ``arm``);
    standalone instances are fine for tests."""

    def __init__(self, endpoint: str, role: str | None = None,
                 queue_max: int | None = None,
                 flush_s: float | None = None,
                 secret: str | None = None,
                 metrics_every: int = 4):
        if queue_max is None:
            queue_max = int(os.environ.get(
                "PADDLE_TPU_TELEMETRY_QUEUE", "4096") or 4096)
        if flush_s is None:
            flush_s = float(os.environ.get(
                "PADDLE_TPU_TELEMETRY_FLUSH", "0.5") or 0.5)
        self.endpoint = endpoint
        if role is None:
            role = os.environ.get("PADDLE_TPU_TELEMETRY_ROLE")
        if not role:
            role = os.path.basename((sys.argv[0] if sys.argv else "")
                                    or "")
            # under `python -m pkg` the agent can arm (via package
            # import) while runpy still has the "-m" placeholder in
            # argv[0] — never report that as a fleet role
            if not role or role in ("-m", "-c", "-"):
                role = "proc"
        self.role = role
        self.flush_s = max(0.05, float(flush_s))
        self._secret = secret if secret is not None \
            else os.environ.get("PADDLE_PS_SECRET") or None
        self._q: deque = deque(maxlen=max(1, int(queue_max)))
        self._qlock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._cli = None
        # wall = monotonic + anchor (this process); collector wall =
        # wall + offset (clock sync). Reported with every push.
        self._anchor = time.time() - time.monotonic()
        self._offset = 0.0
        self._best_rtt: float | None = None
        self._metrics_every = max(1, int(metrics_every))
        self._flushes = 0
        self.batches_sent = 0
        self.send_errors = 0
        self.dropped: dict[str, int] = {}
        self._host = socket.gethostname()
        self._pid = os.getpid()
        self._rpc_client_cls = None

    # -- producers (serving threads; must never block) -----------------
    def _enqueue(self, kind: str, item):
        with self._qlock:
            if len(self._q) == self._q.maxlen:
                old_kind = self._q[0][0]
                self.dropped[old_kind] = self.dropped.get(old_kind, 0) + 1
                _DROPPED.labels(kind=old_kind).inc()
            self._q.append((kind, item))

    def _on_span(self, sp):
        # never stream the agent's own transport spans (rpc.client
        # tel_push/tel_ping, or a hosted collector's rpc.server.tel_*):
        # each flush would mint fresh trace ids for the next flush to
        # ship — telemetry-of-telemetry feedback junk in the collector
        if str((sp.attrs or {}).get("op", "")).startswith("tel_") \
                or sp.name.startswith("rpc.server.tel_"):
            return
        self._enqueue("span", sp)

    def _on_flight(self, ev):
        self._enqueue("flight", ev)

    def publish_event(self, kind: str, **attrs):
        """Discrete fleet event (watchdog stall, bundle written, ...)
        — shows up under the collector's recent-events feed."""
        self._enqueue("event", {"kind": kind, "wall": time.time(),
                                "attrs": _redact_attrs(attrs)})

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "TelemetryAgent":
        if self._thread is not None:
            return self
        # resolve the transport import on the CALLER's thread: a lazy
        # import on the sender thread deadlocks against an in-progress
        # interpreter import of the paddle_tpu package tree (env-armed
        # agents start during `paddle_tpu.observability` import)
        from ..distributed.fleet.runtime.rpc import RpcClient
        self._rpc_client_cls = RpcClient
        _tracing.TRACER.set_sink(self._on_span)
        _flight.RECORDER.set_sink(self._on_flight)
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="telemetry-agent")
        self._thread.start()
        return self

    def stop(self, flush: bool = True):
        if _tracing.TRACER._sink is self._on_span:
            _tracing.TRACER.set_sink(None)
        if _flight.RECORDER._sink is self._on_flight:
            _flight.RECORDER.set_sink(None)
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0 if flush else 0.5)
            self._thread = None
        cli, self._cli = self._cli, None
        if cli is not None:
            try:
                cli.close()
            except Exception:
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- sender thread (the ONLY place sockets are touched) ------------
    def _client(self):
        if self._cli is None:
            cls = self._rpc_client_cls
            if cls is None:       # unstarted agent driven by tests
                from ..distributed.fleet.runtime.rpc import RpcClient \
                    as cls
            self._cli = cls(self.endpoint, secret=self._secret,
                            timeout=2.0, deadline=2.0, max_retries=0)
        return self._cli

    def _drop_conn(self):
        cli, self._cli = self._cli, None
        if cli is not None:
            try:
                cli.close()
            except Exception:
                pass

    def _sync_clock(self):
        t0 = time.time()
        rep = self._client().call({"op": "tel_ping"}, timeout=2.0,
                                  deadline=2.0, max_retries=0)
        t1 = time.time()
        rtt = t1 - t0
        # smallest-RTT exchange wins: its midpoint bounds the skew
        # tightest (allow mild regression so the estimate can track)
        if self._best_rtt is None or rtt <= self._best_rtt * 1.5:
            if self._best_rtt is None or rtt < self._best_rtt:
                self._best_rtt = rtt
            self._offset = float(rep["t_collector"]) - (t0 + t1) / 2.0

    def _drain(self):
        with self._qlock:
            items, self._q = list(self._q), deque(maxlen=self._q.maxlen)
        return items

    def _build_batch(self, items) -> dict:
        spans, flights, events = [], [], []
        for kind, item in items:
            if kind == "span":
                spans.append(_span_dict(item))
            elif kind == "flight":
                flights.append(item.to_dict())
            else:
                events.append(item)
        batch = {"op": "tel_push", "host": self._host, "pid": self._pid,
                 "role": self.role, "anchor": self._anchor,
                 "offset": self._offset, "rtt": self._best_rtt,
                 "wall": time.time(), "spans": spans,
                 "flight": flights, "events": events,
                 "dropped": dict(self.dropped)}
        self._flushes += 1
        if self._flushes % self._metrics_every == 1:
            batch["metrics"] = _obs.to_dict()
        return batch

    def flush_once(self) -> bool:
        """One drain+send cycle (the sender loop body; tests call it
        directly for determinism). Returns True when the batch was
        delivered."""
        items = self._drain()
        batch = self._build_batch(items)
        try:
            if self._best_rtt is None or self._flushes % 8 == 1:
                self._sync_clock()
                batch["offset"] = self._offset
                batch["rtt"] = self._best_rtt
            self._client().call(batch, timeout=2.0, deadline=2.0,
                                max_retries=0)
        except Exception:
            self.send_errors += 1
            _SEND_ERRORS.inc()
            self._drop_conn()
            n = len(items)
            if n:
                self.dropped["send"] = self.dropped.get("send", 0) + n
                _DROPPED.labels(kind="send").inc(n)
            return False
        self.batches_sent += 1
        _BATCHES.inc()
        return True

    def _run(self):
        backoff = self.flush_s
        while not self._stop.wait(backoff):
            ok = self.flush_once()
            # failed sends back off (capped) so a dead collector costs
            # one cheap connect attempt every few seconds, not a storm
            backoff = self.flush_s if ok \
                else min(5.0, max(backoff, self.flush_s) * 2)
        # final best-effort flush so short-lived processes (launch.py
        # children exiting) deliver their tail
        try:
            self.flush_once()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# process-wide agent
# ---------------------------------------------------------------------------

_AGENT: TelemetryAgent | None = None
_ARM_LOCK = threading.Lock()


def get_agent() -> TelemetryAgent | None:
    return _AGENT


def arm(endpoint: str, **kw) -> TelemetryAgent:
    """Start (or replace) the process agent streaming to
    ``endpoint``."""
    global _AGENT
    with _ARM_LOCK:
        if _AGENT is not None:
            _AGENT.stop(flush=False)
        _AGENT = TelemetryAgent(endpoint, **kw).start()
        return _AGENT


def disarm():
    global _AGENT
    with _ARM_LOCK:
        if _AGENT is not None:
            _AGENT.stop()
            _AGENT = None


def publish_event(kind: str, **attrs):
    """Fire-and-forget fleet event; silent no-op when no agent is
    armed (the watchdog/debug call sites are unconditional)."""
    a = _AGENT
    if a is not None:
        try:
            a.publish_event(kind, **attrs)
        except Exception:
            pass


def maybe_start_from_env():
    """Arm from ``PADDLE_TPU_TELEMETRY_COLLECTOR`` when set (called
    once at ``paddle_tpu.observability`` import)."""
    ep = os.environ.get("PADDLE_TPU_TELEMETRY_COLLECTOR", "").strip()
    if ep and _AGENT is None:
        try:
            arm(ep)
        except Exception:
            pass
