"""paddle_tpu.observability — unified runtime telemetry + postmortem.

One substrate replacing the fragmented per-tier stat dicts (serving
engine p50/p99 under a stats lock, PSClient retry counters, autobench
stderr prints, the disconnected jax.profiler wrapper):

  * ``registry`` — thread-safe labeled counters / gauges / fixed-bucket
    histograms with Prometheus-text + JSON exposition and per-process
    file dumps (``PADDLE_TPU_METRICS_DIR``) aggregatable across a
    ``launch.py`` job;
  * ``tracing`` — host spans with trace/span ids, Chrome trace_event
    export, a jax.profiler.TraceAnnotation bridge (host spans line up
    with XPlane device traces), and a trace-id field carried in the PS
    RPC wire skeleton so one request is followable across processes;
  * ``flight`` — the black box: bounded per-tier event rings (request
    lifecycles, RPC calls, PS push/snapshot/WAL commits, checkpoint
    writer transitions, compile events), cheap enough to stay on in
    production, dumped whole into postmortem bundles;
  * ``watchdog`` — progress-token stall detection: each tier registers
    a counter it must advance; no progress past a deadline raises
    ``paddle_tpu_watchdog_*`` metrics, writes a bundle, and can
    re-raise SIGTERM for the launch.py respawn path;
  * ``debug`` — atomic, CRC-manifested postmortem bundle directories
    (``PADDLE_TPU_DEBUG_DIR`` / ``launch.py --debug_dir``), written on
    watchdog fire, unhandled exception, SIGTERM, and on demand via the
    ``debug_dump`` verb of the serving frontend and PS servers.

Scrape points: the serving frontend and every PS server answer
``metrics`` (Prometheus text) and ``debug_dump`` (full bundle) verbs
(docs/OBSERVABILITY.md, docs/DEBUGGING.md).

Quick use:

    from paddle_tpu import observability as obs
    reqs = obs.counter("paddle_tpu_myapp_requests_total", "requests")
    with obs.span("myapp.handle", route="/gen"):
        reqs.inc()
        obs.flight.record("myapp", "handled", route="/gen")
    print(obs.prometheus_text())
    obs.write_bundle("/tmp/debug", reason="manual")

``obs.set_enabled(False)`` (or ``PADDLE_TPU_TELEMETRY=0``) turns every
metric write, span record and flight event into a cheap no-op; the
``BENCH_CONFIG=metrics_overhead`` / ``flight_overhead`` entries in
bench.py keep the enabled-vs-disabled decode step-time delta honest
(<2%).
"""
from __future__ import annotations

import atexit
import os
import socket

from . import agent, alerts, collector, debug, flight, meter, perf, \
    perfwatch, registry, timeseries, tracing, watchdog
from .agent import TelemetryAgent, publish_event
from .alerts import AlertManager, AlertRule
from .collector import TelemetryCollector, telemetry_dispatch
from .meter import METER, UsageMeter, usage_report
from .timeseries import TimeSeriesDB
from .debug import collect, load_bundle, write_bundle
from .flight import RECORDER
from .registry import (REGISTRY, Counter, Gauge, Histogram, MetricError,
                       MetricsRegistry, aggregate_dir, aggregate_dumps,
                       counter, dump_to_file, gauge, histogram,
                       prometheus_text, to_dict)
from .tracing import (TRACER, Span, Tracer, current_trace_id,
                      export_chrome_trace, new_trace_id, span)
from .watchdog import WATCHDOG

__all__ = [
    "registry", "tracing", "flight", "watchdog", "debug",
    "agent", "collector", "perf", "perfwatch",
    "timeseries", "alerts", "meter",
    "TelemetryAgent", "TelemetryCollector",
    "telemetry_dispatch", "publish_event",
    "TimeSeriesDB", "AlertManager", "AlertRule",
    "UsageMeter", "METER", "usage_report",
    "REGISTRY", "MetricsRegistry", "MetricError",
    "Counter", "Gauge", "Histogram",
    "counter", "gauge", "histogram",
    "prometheus_text", "to_dict", "dump_to_file",
    "aggregate_dumps", "aggregate_dir",
    "TRACER", "Tracer", "Span", "span", "current_trace_id",
    "new_trace_id", "export_chrome_trace",
    "RECORDER", "WATCHDOG",
    "collect", "write_bundle", "load_bundle",
    "set_enabled", "enabled",
]


def set_enabled(on: bool):
    """Master switch: metric writes, span recording AND flight events
    (trace ids still propagate so cross-process correlation survives a
    disabled tier)."""
    REGISTRY.set_enabled(on)
    TRACER.enabled = bool(on)
    RECORDER.set_enabled(on)


def enabled() -> bool:
    return REGISTRY.enabled


def _postmortem_dump(reason: str):
    """Evidence dump for process-death paths. Into the metrics dir:
    the registry JSON plus the trace ring and flight rings (each a
    per-process file the offline aggregator can sit next to). Into the
    debug dir: one full CRC-manifested bundle."""
    d = os.environ.get("PADDLE_TPU_METRICS_DIR")
    if d:
        tag = f"{socket.gethostname()}_{os.getpid()}"
        try:
            REGISTRY.dump_to_file()
        except Exception:
            pass
        try:
            TRACER.export_chrome_trace(
                os.path.join(d, f"trace_{tag}.json"))
        except Exception:
            pass
        try:
            RECORDER.dump_to_file(
                os.path.join(d, f"flight_{tag}.json"))
        except Exception:
            pass
    debug.try_write_bundle(reason)


if os.environ.get("PADDLE_TPU_METRICS_DIR"):
    # per-process dump at exit: each launch.py child leaves one
    # metrics_<host>_<pid>.json for registry.aggregate_dir
    @atexit.register
    def _dump_metrics_at_exit():
        try:
            REGISTRY.dump_to_file()
        except Exception:
            pass


if os.environ.get("PADDLE_TPU_METRICS_DIR") \
        or os.environ.get("PADDLE_TPU_DEBUG_DIR"):
    # SIGTERM does NOT run atexit hooks, and that is exactly how
    # launch.py stops PS servers (and any survivors after a failure or
    # a hung-rank teardown): dump the metrics + trace ring + flight
    # rings (+ a debug bundle when PADDLE_TPU_DEBUG_DIR is set), then
    # die with the default disposition so the exit code stays 143.
    # Installed only over the DEFAULT handler — an app with its own
    # SIGTERM logic keeps it (and can call _postmortem_dump itself).
    def _install_sigterm_dump():
        import signal
        import threading
        if threading.current_thread() is not threading.main_thread():
            return
        if signal.getsignal(signal.SIGTERM) != signal.SIG_DFL:
            return

        def _on_term(signum, frame):
            # the handler interrupts an arbitrary main-thread frame,
            # which may HOLD one of the non-reentrant locks the dump
            # needs (flight ring, a registry child, a scheduler lock
            # behind a requests provider). A deadlocked dump must cost
            # a bounded wait, not the exit: arm a hard-exit escalation
            # FIRST, so the process still dies 143 with whatever
            # evidence made it to disk.
            debug.arm_hard_exit(name="sigterm-dump-escalate")
            _postmortem_dump("sigterm")
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_term)

    try:
        _install_sigterm_dump()
    except Exception:
        pass


if os.environ.get("PADDLE_TPU_DEBUG_DIR"):
    # unhandled exceptions (main thread or any worker thread) leave a
    # bundle behind before the traceback prints
    try:
        debug.install_crash_hooks()
    except Exception:
        pass


if os.environ.get("PADDLE_TPU_WATCHDOG", "") not in ("", "0"):
    # opt-in background stall polling; tiers register their progress
    # tokens unconditionally (registration is free), the thread only
    # runs when a job asks for it
    try:
        WATCHDOG.start()
    except Exception:
        pass


# opt-in per-process telemetry agent: PADDLE_TPU_TELEMETRY_COLLECTOR
# (launch.py --telemetry sets it for every child) arms a streamer to
# the fleet collector — spans/flight/metrics/events, one daemon
# sender thread, never in a serving path
try:
    agent.maybe_start_from_env()
except Exception:
    pass
