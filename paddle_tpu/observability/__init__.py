"""paddle_tpu.observability — unified runtime telemetry.

One substrate replacing the fragmented per-tier stat dicts (serving
engine p50/p99 under a stats lock, PSClient retry counters, autobench
stderr prints, the disconnected jax.profiler wrapper):

  * ``registry`` — thread-safe labeled counters / gauges / fixed-bucket
    histograms with Prometheus-text + JSON exposition and per-process
    file dumps (``PADDLE_TPU_METRICS_DIR``) aggregatable across a
    ``launch.py`` job;
  * ``tracing`` — host spans with trace/span ids, Chrome trace_event
    export, a jax.profiler.TraceAnnotation bridge (host spans line up
    with XPlane device traces), and a trace-id field carried in the PS
    RPC wire skeleton so one request is followable across processes.

Scrape points: the serving frontend and every PS server answer a
``metrics`` verb with the Prometheus text (docs/OBSERVABILITY.md).

Quick use:

    from paddle_tpu import observability as obs
    reqs = obs.counter("paddle_tpu_myapp_requests_total", "requests")
    with obs.span("myapp.handle", route="/gen"):
        reqs.inc()
    print(obs.prometheus_text())
    obs.export_chrome_trace("/tmp/trace.json")

``obs.set_enabled(False)`` (or ``PADDLE_TPU_TELEMETRY=0``) turns every
metric write and span record into a cheap no-op; the
``BENCH_CONFIG=metrics_overhead`` entry in bench.py keeps the
enabled-vs-disabled decode step-time delta honest (<2%).
"""
from __future__ import annotations

import atexit
import os

from . import registry, tracing
from .registry import (REGISTRY, Counter, Gauge, Histogram, MetricError,
                       MetricsRegistry, aggregate_dir, aggregate_dumps,
                       counter, dump_to_file, gauge, histogram,
                       prometheus_text, to_dict)
from .tracing import (TRACER, Span, Tracer, current_trace_id,
                      export_chrome_trace, new_trace_id, span)

__all__ = [
    "registry", "tracing",
    "REGISTRY", "MetricsRegistry", "MetricError",
    "Counter", "Gauge", "Histogram",
    "counter", "gauge", "histogram",
    "prometheus_text", "to_dict", "dump_to_file",
    "aggregate_dumps", "aggregate_dir",
    "TRACER", "Tracer", "Span", "span", "current_trace_id",
    "new_trace_id", "export_chrome_trace",
    "set_enabled", "enabled",
]


def set_enabled(on: bool):
    """Master switch: metric writes AND span recording (trace ids still
    propagate so cross-process correlation survives a disabled tier)."""
    REGISTRY.set_enabled(on)
    TRACER.enabled = bool(on)


def enabled() -> bool:
    return REGISTRY.enabled


if os.environ.get("PADDLE_TPU_METRICS_DIR"):
    # per-process dump at exit: each launch.py child leaves one
    # metrics_<host>_<pid>.json for registry.aggregate_dir
    @atexit.register
    def _dump_metrics_at_exit():
        try:
            REGISTRY.dump_to_file()
        except Exception:
            pass

    # SIGTERM does NOT run atexit hooks, and that is exactly how
    # launch.py stops PS servers (and any survivors after a failure):
    # dump first, then die with the default disposition so the exit
    # code stays 143. Installed only over the DEFAULT handler — an app
    # with its own SIGTERM logic keeps it (and can call dump_to_file
    # itself).
    def _install_sigterm_dump():
        import signal
        import threading
        if threading.current_thread() is not threading.main_thread():
            return
        if signal.getsignal(signal.SIGTERM) != signal.SIG_DFL:
            return

        def _on_term(signum, frame):
            try:
                REGISTRY.dump_to_file()
            except Exception:
                pass
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_term)

    try:
        _install_sigterm_dump()
    except Exception:
        pass
