"""Fleet telemetry collector: cross-process trace assembly + tail
sampling + the state behind the live dashboard.

Per-process ``TelemetryAgent``s (``observability.agent``) stream
span/flight/metric/event batches here as ``tel_push`` calls over the
mux RPC wire. The ``TelemetryCollector``:

  * **assembles cross-process traces** keyed by the trace id that
    already rides the RPC skeleton (``_trace_id``): every span batch
    is bucketed by trace id, each process's ``time.monotonic`` span
    clocks are mapped onto the collector's wall clock via the agent's
    anchor (wall - monotonic) plus a clock-skew offset measured from
    ``tel_ping`` RTT midpoints (smallest RTT wins) — one request
    becomes ONE waterfall frontend -> router -> replica engine -> PS;
  * applies **tail-based sampling** at trace completion (quiescence
    past ``PADDLE_TPU_TELEMETRY_LINGER``): error / deadline-missed /
    watchdog-flagged traces are kept 100%, anything above the moving
    p99 duration of recent traces is kept, and the boring rest is kept
    at rate ``PADDLE_TPU_TELEMETRY_SAMPLE`` (decided by a hash of the
    trace id — deterministic across restarts). Kept traces live in a
    bounded ring (``PADDLE_TPU_TELEMETRY_RING``); sampled-out and
    evicted traces are counted, never silently gone;
  * tracks **fleet state** per process (role, liveness, drop counts,
    latest metric snapshot, recent watchdog/bundle events) — the feed
    behind ``python -m paddle_tpu.observability.top``. Processes that
    stop reporting past ``PADDLE_TPU_TELEMETRY_RETIRE`` are aged out
    (counted in ``paddle_tpu_telemetry_procs_retired_total``), so the
    fleet table shows the live fleet, not every process ever seen;
  * hosts the **time-series plane**: every push's fleet summary and
    every ride-along registry dump land in an embedded TSDB
    (``observability.timeseries`` — durable when
    ``PADDLE_TPU_TSDB_DIR`` is set, queryable via the ``tsdb_query``
    verb / ``top history``) and an alert engine
    (``observability.alerts``) evaluates burn-rate/threshold/absence
    rules over it on a cadence (``alerts`` verb / ``top alerts``),
    with per-tenant usage aggregation behind ``usage_report``;
  * exports any assembled trace as one merged **Chrome trace** with
    per-rank pid labels (``merge_chrome_traces`` is shared with the
    offline ``python -m paddle_tpu.observability.registry <dir>``
    aggregator).

Hosting: ``telemetry_dispatch(collector, req)`` is the ``tel_*`` verb
switch, delegated from the router and PS dispatch exactly like the
``pub_*`` verbs (``PADDLE_TPU_TELEMETRY_HOST=1``), or served
standalone by ``CollectorServer`` (``launch.py --telemetry`` runs
``python -m paddle_tpu.observability.collector``).
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time
from collections import OrderedDict, deque

from . import alerts as _alerts
from . import meter as _meter
from . import registry as _obs
from . import timeseries as _ts

__all__ = ["TelemetryCollector", "telemetry_dispatch", "TEL_READ_OPS",
           "CollectorServer", "merge_chrome_traces", "main"]

# tel_* verbs never need replay dedup: pushes are single-attempt
# fire-and-forget, everything else is a read. tsdb_query / alerts /
# usage_report are the time-series plane's read verbs — hosted by the
# same dispatch, gated into router/PS READ_OPS through this set
TEL_READ_OPS = frozenset({"tel_push", "tel_ping", "tel_fleet",
                          "tel_trace", "tel_traces", "tel_stats",
                          "tel_watch",
                          "tsdb_query", "alerts", "usage_report"})

_PUSHES = _obs.counter(
    "paddle_tpu_telemetry_push_batches_total",
    "tel_push batches ingested by the collector")
_SPANS = _obs.counter(
    "paddle_tpu_telemetry_spans_total",
    "spans ingested by the collector")
_TRACES = _obs.counter(
    "paddle_tpu_telemetry_traces_total",
    "traces finalized by the collector, by tail-sampling verdict",
    ["verdict"])
_EVICTED = _obs.counter(
    "paddle_tpu_telemetry_trace_evicted_total",
    "kept traces evicted from the bounded retention ring")
_RETIRED = _obs.counter(
    "paddle_tpu_telemetry_procs_retired_total",
    "processes aged out of the fleet table after the liveness window")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# shared Chrome-trace merging (collector export + offline registry CLI)
# ---------------------------------------------------------------------------

def merge_chrome_traces(parts) -> dict:
    """Merge per-rank Chrome ``traceEvents`` lists into ONE document.

    ``parts``: iterable of ``(label, events)`` — one entry per rank.
    Events keep their own tids but are re-pidded onto a dense per-rank
    pid with a ``process_name`` metadata row, so Perfetto shows one
    labeled track group per rank instead of colliding raw pids."""
    out = []
    for i, (label, events) in enumerate(parts):
        pid = i + 1
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": str(label)}})
        for ev in events:
            ev = dict(ev)
            ev["pid"] = pid
            out.append(ev)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def _hist_quantile(buckets, cumulative, q: float) -> float | None:
    """Nearest-bucket quantile from a cumulative histogram snapshot
    (upper bound of the first bucket reaching rank q)."""
    if not cumulative or cumulative[-1] <= 0:
        return None
    rank = q * cumulative[-1]
    for i, c in enumerate(cumulative):
        if c >= rank:
            return float(buckets[i]) if i < len(buckets) \
                else float(buckets[-1])
    return float(buckets[-1])


class _TraceBuild:
    """One in-assembly trace: spans/flight per process, flags."""

    __slots__ = ("spans", "flight", "procs", "first", "last",
                 "error", "flagged")

    def __init__(self, now: float):
        self.spans: list[dict] = []
        self.flight: list[dict] = []
        self.procs: set = set()
        self.first = now           # collector monotonic
        self.last = now
        self.error = False
        self.flagged = False       # watchdog-flagged


_ERROR_REASONS = ("error", "deadline", "timeout", "failed")


def _span_error(sp: dict) -> bool:
    a = sp.get("attrs") or {}
    if "error" in a:
        return True
    st = str(a.get("status", "")).lower()
    return any(r in st for r in ("error", "deadline"))


def _flight_error(ev: dict) -> bool:
    if str(ev.get("kind", "")).endswith("_error"):
        return True
    a = ev.get("attrs") or {}
    reason = str(a.get("reason", "")).lower()
    return reason in _ERROR_REASONS or "error" in a


class TelemetryCollector:
    """See module docstring. Thread-safe; sweeping (trace completion +
    tail sampling) runs inline on ingest/read calls — no thread of its
    own, so hosting it on a router/PS dispatch costs nothing extra."""

    def __init__(self, sample: float | None = None,
                 ring_max: int | None = None,
                 linger_s: float | None = None,
                 reservoir: int = 512, events_max: int = 64,
                 tsdb: "_ts.TimeSeriesDB | None" = None,
                 alerts: "_alerts.AlertManager | None" = None,
                 retire_s: float | None = None):
        if sample is None:
            sample = _env_float("PADDLE_TPU_TELEMETRY_SAMPLE", 0.1)
        if ring_max is None:
            ring_max = int(_env_float("PADDLE_TPU_TELEMETRY_RING", 512))
        if linger_s is None:
            linger_s = _env_float("PADDLE_TPU_TELEMETRY_LINGER", 1.0)
        if retire_s is None:
            retire_s = _env_float("PADDLE_TPU_TELEMETRY_RETIRE", 120.0)
        self.sample = min(1.0, max(0.0, float(sample)))
        self.ring_max = max(1, int(ring_max))
        self.linger_s = max(0.0, float(linger_s))
        self.retire_s = max(0.0, float(retire_s))  # 0 disables GC
        self._lock = threading.RLock()
        # (host, pid) -> process record (fleet state)
        self._procs: dict[tuple, dict] = {}
        self._open: dict[str, _TraceBuild] = {}
        self._kept: OrderedDict[str, dict] = OrderedDict()
        self._durs: deque = deque(maxlen=max(32, int(reservoir)))
        self._recent_events: deque = deque(maxlen=max(8, events_max))
        self.counts = {"batches": 0, "spans": 0, "assembled": 0,
                       "kept_error": 0, "kept_slow": 0,
                       "kept_sampled": 0, "sampled_out": 0,
                       "evicted": 0, "procs_retired": 0,
                       "tsdb_errors": 0}
        self._started = time.time()
        # time-series plane: memory-only TSDB unless PADDLE_TPU_TSDB_DIR
        # points at a data dir; PADDLE_TPU_TSDB=0 turns the whole plane
        # off (the bench A/B toggle)
        if tsdb is None \
                and os.environ.get("PADDLE_TPU_TSDB", "1") != "0":
            tsdb = _ts.TimeSeriesDB()
        self.tsdb = tsdb
        if alerts is None and self.tsdb is not None \
                and os.environ.get("PADDLE_TPU_ALERTS", "1") != "0":
            alerts = _alerts.AlertManager(
                tsdb=self.tsdb, fleet_fn=self.fleet,
                event_cb=self._note_alert_event)
        self.alerts = alerts

    def _note_alert_event(self, ev: dict):
        """AlertManager transition tap: alert lifecycle shows up in the
        fleet's recent-events feed (the `top` footer) even when no
        local agent is armed. Called OUTSIDE the alert manager's lock."""
        rec = {"host": socket.gethostname(), "pid": os.getpid(),
               "role": "collector", "wall": time.time(),
               "kind": str(ev.get("kind", "?")),
               "attrs": ev.get("attrs") or {}}
        with self._lock:
            self._recent_events.append(rec)

    def close(self):
        if self.tsdb is not None:
            self.tsdb.close()

    # -- ingest (tel_push) ---------------------------------------------
    def ingest(self, batch: dict) -> dict:
        now = time.monotonic()
        key = (str(batch.get("host", "?")), int(batch.get("pid", 0)))
        offset = float(batch.get("offset") or 0.0)
        anchor = float(batch.get("anchor") or 0.0)
        spans = batch.get("spans") or ()
        flights = batch.get("flight") or ()
        events = batch.get("events") or ()
        with self._lock:
            proc = self._procs.get(key)
            if proc is None:
                proc = self._procs[key] = {
                    "host": key[0], "pid": key[1],
                    "role": str(batch.get("role") or "?"),
                    "events": deque(maxlen=32),
                    "prev_requests": None, "summary": {}}
            proc["role"] = str(batch.get("role") or proc["role"])
            proc["last_seen"] = time.time()
            proc["offset"] = offset
            proc["rtt"] = batch.get("rtt")
            proc["dropped"] = dict(batch.get("dropped") or {})
            self.counts["batches"] += 1
            _PUSHES.inc()
            for sp in spans:
                tid = sp.get("trace_id")
                if not tid:
                    continue
                tb = self._open.get(tid)
                if tb is None:
                    if tid in self._kept:
                        continue  # late span after finalize
                    tb = self._open[tid] = _TraceBuild(now)
                sp = dict(sp)
                # agent monotonic -> collector wall
                start = float(sp.get("start") or 0.0)
                end = float(sp.get("end") or start)
                sp["t0"] = start + anchor + offset
                sp["t1"] = end + anchor + offset
                sp["host"], sp["pid"] = key
                sp["role"] = proc["role"]
                tb.spans.append(sp)
                tb.procs.add(key)
                tb.last = now
                if _span_error(sp):
                    tb.error = True
                self.counts["spans"] += 1
                _SPANS.inc()
            for ev in flights:
                tid = ev.get("trace_id")
                err = _flight_error(ev)
                if tid and tid in self._open:
                    tb = self._open[tid]
                    ev = dict(ev)
                    ev["host"], ev["pid"] = key
                    tb.flight.append(ev)
                    tb.last = now
                    if err:
                        tb.error = True
            for ev in events:
                rec = {"host": key[0], "pid": key[1],
                       "role": proc["role"],
                       "wall": ev.get("wall"),
                       "kind": str(ev.get("kind", "?")),
                       "attrs": ev.get("attrs") or {}}
                proc["events"].append(rec)
                self._recent_events.append(rec)
                if rec["kind"].startswith("watchdog"):
                    # a stalled process taints every trace it still
                    # has in assembly — keep them all
                    for tb in self._open.values():
                        if key in tb.procs:
                            tb.flagged = True
            metrics = batch.get("metrics")
            if metrics is not None:
                proc["metrics"] = metrics
                proc["summary"] = self._summarize(proc, metrics)
            role = proc["role"]
            summary = dict(proc.get("summary") or {})
            self._sweep_locked(now)
        # TSDB ingest runs outside the collector lock: block seals do
        # disk IO and the TSDB has its own lock
        if self.tsdb is not None:
            try:
                if metrics is not None:
                    self.tsdb.ingest_dump(key[0], key[1], role, metrics)
                scal = {f"paddle_tpu_fleet_{k}": v
                        for k, v in summary.items()
                        if isinstance(v, (int, float))}
                if scal:
                    self.tsdb.ingest_scalars(
                        time.time(), scal,
                        {"host": key[0], "pid": str(key[1]),
                         "role": role})
            except Exception:
                with self._lock:
                    self.counts["tsdb_errors"] += 1
        if self.alerts is not None:
            self.alerts.maybe_evaluate()
        return {"ok": True}

    # -- fleet summary ---------------------------------------------------
    def _summarize(self, proc: dict, dump: dict) -> dict:
        by_name = {m["name"]: m for m in dump.get("metrics", ())}

        def total(name):
            m = by_name.get(name)
            if not m:
                return None
            return sum((s.get("value") or 0.0) for s in m["samples"])

        def quantiles(name, qs=(0.5, 0.99)):
            m = by_name.get(name)
            if not m or not m.get("samples"):
                return None
            buckets = m.get("buckets") or ()
            cum = [0] * (len(buckets) + 1)
            for s in m["samples"]:
                cum = [a + b for a, b in
                       zip(cum, s.get("cumulative") or cum)]
            return [_hist_quantile(buckets, cum, q) for q in qs]

        out = {}
        req = total("paddle_tpu_serving_requests_total")
        if req is not None:
            out["requests_total"] = req
            prev = proc.get("prev_requests")
            now = time.time()
            if prev is not None and now > prev[1]:
                out["rps"] = max(0.0, (req - prev[0]) / (now - prev[1]))
            proc["prev_requests"] = (req, now)
        for key_, name in (("queue_depth",
                            "paddle_tpu_serving_queue_depth"),
                           ("page_occupancy",
                            "paddle_tpu_serving_page_occupancy")):
            v = total(name)
            if v is not None:
                out[key_] = v
        for key_, name in (("ttft", "paddle_tpu_slo_ttft_seconds"),
                           ("itl", "paddle_tpu_slo_inter_token_seconds"),
                           ("latency",
                            "paddle_tpu_serving_request_latency_seconds")):
            q = quantiles(name)
            if q and q[0] is not None:
                out[f"{key_}_p50"], out[f"{key_}_p99"] = q
        pushes = total("paddle_tpu_ps_push_rows_total") \
            or total("paddle_tpu_rpc_server_requests_total")
        if pushes is not None:
            out["server_requests_total"] = pushes

        def by_labels(name, *keys):
            m = by_name.get(name)
            if not m:
                return {}
            return {"/".join(str(s["labels"].get(k, "")) for k in keys):
                    s.get("value") for s in m.get("samples", ())
                    if s.get("value") is not None}

        # perf plane (docs/OBSERVABILITY.md): per-loop MFU, last
        # sampled step breakdown, compile counts, HBM + KV bytes —
        # what the `top` perf pane renders per process
        perf = {}
        mfu = by_labels("paddle_tpu_perf_mfu", "name")
        if mfu:
            perf["mfu"] = mfu
        bd = by_labels("paddle_tpu_perf_step_breakdown_seconds",
                       "name", "phase")
        if bd:
            perf["breakdown"] = bd
        compiles = total("paddle_tpu_serving_compiles_total")
        ecompiles = total("paddle_tpu_executor_compiles_total")
        if compiles or ecompiles:
            perf["compiles_total"] = (compiles or 0.0) + (ecompiles or 0.0)
        hbm = by_labels("paddle_tpu_perf_hbm_bytes", "kind")
        if any(hbm.values()):
            perf["hbm"] = hbm
        kv = total("paddle_tpu_perf_kv_cache_bytes")
        if kv:
            perf["kv_cache_bytes"] = kv
        kern = by_labels("paddle_tpu_autobench_candidate_ms",
                         "key", "candidate")
        if kern:
            perf["kernel_ms"] = kern
        if perf:
            out["perf"] = perf
        # tiered PS store (docs/PS_TIERED.md): per-tier hits and
        # residency, faults/demotions, by-tier pull latency — what the
        # `top` tier columns render per PS shard
        tier = {}
        hits = by_labels("paddle_tpu_ps_tier_hits_total", "tier")
        if hits:
            tier["hits"] = hits
        rows = by_labels("paddle_tpu_ps_tier_resident_rows", "tier")
        if any(rows.values()):
            tier["resident_rows"] = rows
            tier["resident_bytes"] = by_labels(
                "paddle_tpu_ps_tier_resident_bytes", "tier")
        for key_, name in (("faults",
                            "paddle_tpu_ps_tier_faults_total"),
                           ("demotions",
                            "paddle_tpu_ps_tier_demotions_total"),
                           ("cold_read_errors",
                            "paddle_tpu_ps_tier_cold_read_errors_total")):
            v = total(name)
            if v:
                tier[key_] = v
        q = quantiles("paddle_tpu_ps_tier_pull_seconds")
        if q and q[0] is not None:
            tier["pull_p50"], tier["pull_p99"] = q
        if tier:
            out["tier"] = tier
        # shared-prefix KV cache + stochastic decode (docs/SERVING.md):
        # hit ratio, prefill tokens the cache absorbed, COW/eviction
        # churn and residency — what the `top` prefix row renders
        prefix = {}
        hits_ = total("paddle_tpu_prefix_lookup_hits_total")
        misses_ = total("paddle_tpu_prefix_lookup_misses_total")
        if hits_ or misses_:
            prefix["lookups"] = (hits_ or 0.0) + (misses_ or 0.0)
            prefix["hit_ratio"] = (hits_ or 0.0) / prefix["lookups"]
        for key_, name in (
                ("tokens_saved",
                 "paddle_tpu_prefix_prefill_tokens_saved_total"),
                ("cow_copies", "paddle_tpu_prefix_cow_copies_total"),
                ("evicted", "paddle_tpu_prefix_evicted_pages_total"),
                ("cached_pages", "paddle_tpu_prefix_cached_pages"),
                ("shared_pages", "paddle_tpu_prefix_shared_pages"),
                ("sampled_requests",
                 "paddle_tpu_sampling_requests_total"),
                ("sampled_tokens",
                 "paddle_tpu_sampling_tokens_total")):
            v = total(name)
            if v:
                prefix[key_] = v
        if prefix:
            out["prefix"] = prefix
        return out

    # -- completion + tail sampling --------------------------------------
    def _p99_threshold(self) -> float | None:
        if len(self._durs) < 32:
            return None
        s = sorted(self._durs)
        return s[min(len(s) - 1, int(0.99 * len(s)))]

    def _sweep_locked(self, now: float):
        done = [tid for tid, tb in self._open.items()
                if now - tb.last >= self.linger_s]
        for tid in done:
            self._finalize_locked(tid, self._open.pop(tid))
        # fleet-state GC: age out processes that stopped reporting —
        # a dead agent must not pad the fleet table forever (the
        # absence alert has already had retire_s > its max_age_s to
        # notice the silence first)
        if self.retire_s > 0:
            wall = time.time()
            stale = [k for k, p in self._procs.items()
                     if wall - (p.get("last_seen") or wall)
                     > self.retire_s]
            for k in stale:
                p = self._procs.pop(k)
                self.counts["procs_retired"] += 1
                _RETIRED.inc()
                self._recent_events.append(
                    {"host": k[0], "pid": k[1], "role": p.get("role"),
                     "wall": wall, "kind": "proc_retired",
                     "attrs": {"last_seen": p.get("last_seen")}})

    def sweep(self, force: bool = False) -> int:
        """Finalize quiescent (or, with ``force``, all) open traces;
        returns how many closed. Tests drive this deterministically."""
        with self._lock:
            before = len(self._open)
            now = time.monotonic() + (1e12 if force else 0.0)
            self._sweep_locked(now)
            return before - len(self._open)

    def _finalize_locked(self, tid: str, tb: _TraceBuild):
        tb.spans.sort(key=lambda s: s["t0"])
        t0 = min((s["t0"] for s in tb.spans), default=0.0)
        t1 = max((s["t1"] for s in tb.spans), default=t0)
        dur = t1 - t0
        thresh = self._p99_threshold()
        self._durs.append(dur)
        self.counts["assembled"] += 1
        if tb.error or tb.flagged:
            verdict = "kept_error"
        elif thresh is not None and dur >= thresh:
            verdict = "kept_slow"
        elif self.sample > 0 and (int(tid[:12] or "0", 16) % 1000000
                                  < self.sample * 1000000):
            verdict = "kept_sampled"
        else:
            verdict = "sampled_out"
        self.counts[verdict] += 1
        _TRACES.labels(verdict=verdict).inc()
        if verdict == "sampled_out":
            return
        assembled = {
            "trace_id": tid, "verdict": verdict, "complete": True,
            "start_wall": t0, "duration_ms": dur * 1000.0,
            "error": tb.error, "watchdog_flagged": tb.flagged,
            "procs": sorted({(s["host"], s["pid"], s["role"])
                             for s in tb.spans}),
            "spans": tb.spans, "flight": tb.flight,
        }
        self._kept[tid] = assembled
        self._kept.move_to_end(tid)
        while len(self._kept) > self.ring_max:
            self._kept.popitem(last=False)
            self.counts["evicted"] += 1
            _EVICTED.inc()

    # -- reads -----------------------------------------------------------
    def trace(self, tid: str) -> dict | None:
        """The assembled trace, or a ``complete: False`` partial while
        spans are still arriving, or None if unknown/sampled out."""
        with self._lock:
            self._sweep_locked(time.monotonic())
            got = self._kept.get(tid)
            if got is not None:
                return got
            tb = self._open.get(tid)
            if tb is None:
                return None
            spans = sorted(tb.spans, key=lambda s: s["t0"])
            return {"trace_id": tid, "complete": False,
                    "error": tb.error,
                    "watchdog_flagged": tb.flagged,
                    "procs": sorted({(s["host"], s["pid"], s["role"])
                                     for s in spans}),
                    "spans": spans, "flight": list(tb.flight)}

    def traces(self, limit: int = 64) -> list[dict]:
        with self._lock:
            self._sweep_locked(time.monotonic())
            out = [{"trace_id": t["trace_id"],
                    "verdict": t["verdict"],
                    "duration_ms": t["duration_ms"],
                    "start_wall": t["start_wall"],
                    "spans": len(t["spans"]),
                    "procs": len(t["procs"]),
                    "error": t["error"]}
                   for t in self._kept.values()]
        out.reverse()           # newest first
        return out[:max(1, int(limit))]

    def fleet(self) -> dict:
        with self._lock:
            self._sweep_locked(time.monotonic())
            procs = []
            for (host, pid), p in sorted(self._procs.items()):
                procs.append({
                    "host": host, "pid": pid, "role": p.get("role"),
                    "last_seen": p.get("last_seen"),
                    "age_s": max(0.0, time.time()
                                 - (p.get("last_seen") or 0.0)),
                    "rtt": p.get("rtt"),
                    "offset": p.get("offset"),
                    "dropped": p.get("dropped") or {},
                    "summary": dict(p.get("summary") or {}),
                    "events": list(p["events"])[-8:],
                })
            return {"time": time.time(), "procs": procs,
                    "recent_events": list(self._recent_events),
                    "traces": {k: self.counts[k] for k in
                               ("assembled", "kept_error", "kept_slow",
                                "kept_sampled", "sampled_out",
                                "evicted")},
                    "open_traces": len(self._open),
                    "kept_traces": len(self._kept)}

    def stats(self) -> dict:
        with self._lock:
            out = {"counts": dict(self.counts),
                   "open": len(self._open), "kept": len(self._kept),
                   "procs": len(self._procs),
                   "sample": self.sample, "ring_max": self.ring_max,
                   "linger_s": self.linger_s,
                   "retire_s": self.retire_s,
                   "p99_threshold_s": self._p99_threshold(),
                   "started": self._started}
        if self.tsdb is not None:
            out["tsdb"] = self.tsdb.stats()
        if self.alerts is not None:
            out["alerts"] = dict(self.alerts.counts)
        return out

    # -- TSDB query verb -------------------------------------------------
    def tsdb_query(self, req: dict) -> dict:
        """``tsdb_query`` verb body: one query per request.

        ``{"op": "tsdb_query", "query": "rate", "metric": ...,
           "labels": {...}, "window": 60, "q": 0.99,
           "start": t, "end": t}``

        queries: series | latest | range | delta | rate | quantile.
        """
        if self.tsdb is None:
            return {"error": "tsdb disabled (PADDLE_TPU_TSDB=0)"}
        what = str(req.get("query") or "latest")
        metric = req.get("metric")
        labels = req.get("labels") or None
        try:
            if what == "series":
                return {"series": self.tsdb.series(metric)}
            if metric is None:
                return {"error": "metric required"}
            window = float(req.get("window") or 300.0)
            if what == "latest":
                return {"value": self.tsdb.latest(metric, labels)}
            if what == "range":
                end = req.get("end")
                end = float(end) if end is not None \
                    else self.tsdb._default_at(metric)
                start = req.get("start")
                start = float(start) if start is not None \
                    else end - window
                return {"points": self.tsdb.range(
                    metric, labels, start, end)}
            if what == "delta":
                return {"value": self.tsdb.delta(
                    metric, window, labels)}
            if what == "rate":
                return {"value": self.tsdb.rate(
                    metric, window, labels)}
            if what == "quantile":
                return {"value": self.tsdb.quantile(
                    metric, float(req.get("q") or 0.99), window,
                    labels)}
            return {"error": f"unknown query {what!r}"}
        except Exception as e:          # noqa: BLE001 — wire boundary
            return {"error": f"{type(e).__name__}: {e}"}

    # -- Chrome export ---------------------------------------------------
    def chrome_trace(self, tid: str) -> dict | None:
        """One merged Chrome trace for an assembled trace id: per-rank
        pid labels, timestamps on the collector-aligned wall clock
        (relative to trace start)."""
        t = self.trace(tid)
        if t is None or not t.get("spans"):
            return None
        t0 = min(s["t0"] for s in t["spans"])
        per_rank: dict = OrderedDict()
        for s in t["spans"]:
            key = (s["host"], s["pid"])
            per_rank.setdefault(
                key, (f"{s['role']} {s['host']}:{s['pid']}", []))
            args = {"trace_id": s["trace_id"],
                    "span_id": s.get("span_id")}
            if s.get("parent_id"):
                args["parent_id"] = s["parent_id"]
            args.update(s.get("attrs") or {})
            per_rank[key][1].append({
                "name": s["name"], "ph": "X", "cat": "paddle_tpu",
                "ts": round((s["t0"] - t0) * 1e6, 3),
                "dur": round((s["t1"] - s["t0"]) * 1e6, 3),
                "tid": s.get("tid", 0), "args": args})
        return merge_chrome_traces(per_rank.values())


# ---------------------------------------------------------------------------
# verb switch (shared by the standalone server and router/PS hosting)
# ---------------------------------------------------------------------------

def telemetry_dispatch(collector: TelemetryCollector, req: dict,
                       keepalive: float = 2.0):
    """The ``tel_*`` verb switch. Returns a reply dict — or, for
    ``tel_watch``, a dispatch generator the RPC layer streams as
    server-push frames (the ``pub_watch`` idiom)."""
    op = req["op"]
    if op == "tel_push":
        return collector.ingest(req)
    if op == "tel_ping":
        return {"ok": True, "t_collector": time.time()}
    if op == "tel_fleet":
        if collector.alerts is not None:
            collector.alerts.maybe_evaluate()
        return {"fleet": collector.fleet()}
    if op == "tsdb_query":
        return collector.tsdb_query(req)
    if op == "alerts":
        if collector.alerts is None:
            return {"alerts": {"active": [], "history": [],
                               "rules": []}}
        collector.alerts.maybe_evaluate()
        return {"alerts": collector.alerts.state()}
    if op == "usage_report":
        return {"usage": _meter.usage_report(
            collector.tsdb, window=req.get("window"))}
    if op == "tel_trace":
        tid = str(req["trace_id"])
        rep = {"trace": collector.trace(tid)}
        if req.get("chrome"):
            rep["chrome"] = collector.chrome_trace(tid)
        return rep
    if op == "tel_traces":
        return {"traces": collector.traces(
            limit=int(req.get("limit", 64)))}
    if op == "tel_stats":
        return collector.stats()
    if op == "tel_watch":
        return _watch_stream(collector, keepalive)
    raise ValueError(f"unknown telemetry op {op!r}")


def _watch_stream(collector: TelemetryCollector, keepalive: float):
    """tel_watch dispatch generator: fleet snapshot ack, then one
    frame per keepalive tick — `top` renders each frame. Cancellation
    (the client abandoning the stream) is observed at each yield."""
    yield {"subscribed": True, "fleet": collector.fleet()}
    while True:
        time.sleep(max(0.1, keepalive))
        yield {"fleet": collector.fleet()}


# ---------------------------------------------------------------------------
# standalone server (launch.py --telemetry)
# ---------------------------------------------------------------------------

class CollectorServer:
    """Standalone collector endpoint over the mux wire (the
    RegistryServer shape): serves exactly `telemetry_dispatch` plus
    ping."""

    READ_OPS = frozenset(TEL_READ_OPS | {"ping"})

    def __init__(self, endpoint: str = "127.0.0.1:0",
                 secret: str | None = None,
                 collector: TelemetryCollector | None = None):
        import socketserver

        from ..distributed.fleet.runtime.rpc import (RpcServerState,
                                                     serve_connection)
        self.collector = collector or TelemetryCollector()
        if secret is None:
            secret = os.environ.get("PADDLE_PS_SECRET") or None
        self._rpc = RpcServerState(read_ops=self.READ_OPS,
                                   secret=secret)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                serve_connection(self.request, outer._dispatch,
                                 outer._rpc)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        host, port = endpoint.rsplit(":", 1)
        self._server = Server((host, int(port)), Handler)
        self.endpoint = f"{host}:{self._server.server_address[1]}"
        self._thread: threading.Thread | None = None

    def _dispatch(self, req: dict):
        if req.get("op") == "ping":
            return {"ok": True, "role": "telemetry-collector"}
        return telemetry_dispatch(self.collector, req)

    def start(self) -> "CollectorServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="telemetry-collector")
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self.collector.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def main(argv=None) -> int:
    """``python -m paddle_tpu.observability.collector`` — the child
    ``launch.py --telemetry`` spawns. Prints a READY line (the replica
    fixture convention) and serves until killed."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.observability.collector")
    ap.add_argument("--endpoint", default=os.environ.get(
        "PADDLE_TPU_TELEMETRY_COLLECTOR") or "127.0.0.1:0")
    args = ap.parse_args(argv)
    srv = CollectorServer(endpoint=args.endpoint).start()
    print(json.dumps({"ready": True, "endpoint": srv.endpoint,
                      "pid": os.getpid(),
                      "host": socket.gethostname()}), flush=True)
    try:
        while True:
            time.sleep(1.0)
            srv.collector.sweep()
            if srv.collector.alerts is not None:
                srv.collector.alerts.maybe_evaluate()
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
