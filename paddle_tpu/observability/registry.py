"""Metrics registry: labeled counters, gauges, fixed-bucket histograms.

One process-wide registry (``REGISTRY``) is the telemetry substrate the
serving engine, PS runtime, executor and autobench all write into
(reference analog: the platform profiler's event aggregation, here
re-expressed as Prometheus-style series so one scrape shows every tier).
Design rules:

  * thread-safe — every child series carries its own lock; an increment
    can never be lost to a concurrent reader or writer (tests hammer one
    counter from 8 threads);
  * names are ``paddle_tpu_``-prefixed snake_case, enforced at
    registration AND statically by scripts/check_metric_names.py;
  * registration is idempotent per (name, kind, labelnames) — the same
    module-level ``counter(...)`` call may run once per process, but a
    name re-registered with a different kind/labelset raises;
  * exposition: Prometheus text (``prometheus_text``), JSON
    (``to_dict``), and a per-process file dump (``dump_to_file``) so
    ``launch.py`` multi-process jobs can be merged offline with
    ``aggregate_dumps`` / ``python -m paddle_tpu.observability.registry
    <dir>``.

Disabling (``REGISTRY.set_enabled(False)`` or
``PADDLE_TPU_TELEMETRY=0``) turns every write into a cheap early return
— the metrics-overhead microbench (``BENCH_CONFIG=metrics_overhead``)
measures the enabled-vs-disabled step-time delta.

No jax/framework imports here: the registry must be importable from the
deepest transport modules without cycles.
"""
from __future__ import annotations

import json
import math
import os
import re
import socket
import threading
import time

__all__ = [
    "MetricError", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "REGISTRY", "counter", "gauge", "histogram", "prometheus_text",
    "to_dict", "dump_to_file", "aggregate_dumps", "aggregate_dir",
    "DEFAULT_BUCKETS",
]

_NAME_RE = re.compile(r"^paddle_tpu_[a-z][a-z0-9_]*$")
_LABEL_RE = re.compile(r"^[a-z_][a-z0-9_]*$")

# latency-flavored default buckets (seconds): sub-ms host work up to
# multi-second compiles
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class MetricError(ValueError):
    """Bad metric name/labels or a conflicting re-registration."""


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def _label_str(labelnames, labelvalues) -> str:
    if not labelnames:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in zip(labelnames, labelvalues))
    return "{" + inner + "}"


class _Child:
    """One labeled series. Holds its own lock so concurrent increments
    from handler/scheduler threads never lose updates."""

    __slots__ = ("_metric", "_values", "_lock")

    def __init__(self, metric, labelvalues):
        self._metric = metric
        self._values = labelvalues
        self._lock = threading.Lock()


class _CounterChild(_Child):
    __slots__ = ("_v",)

    def __init__(self, metric, labelvalues):
        super().__init__(metric, labelvalues)
        self._v = 0.0

    def inc(self, n: float = 1.0):
        if not (self._metric.always
                or self._metric._registry._enabled):
            return
        if n < 0:
            raise MetricError("counters only go up")
        with self._lock:
            self._v += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class _GaugeChild(_Child):
    __slots__ = ("_v", "_fn")

    def __init__(self, metric, labelvalues):
        super().__init__(metric, labelvalues)
        self._v = 0.0
        self._fn = None

    def set(self, v: float):
        if not (self._metric.always
                or self._metric._registry._enabled):
            return
        with self._lock:
            self._v = float(v)

    def inc(self, n: float = 1.0):
        if not (self._metric.always
                or self._metric._registry._enabled):
            return
        with self._lock:
            self._v += n

    def dec(self, n: float = 1.0):
        self.inc(-n)

    def set_function(self, fn):
        """Evaluate ``fn()`` at exposition time (live queue depth /
        occupancy without a write on every transition)."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self._fn
            v = self._v
        if fn is None:
            return v
        # evaluate OUTSIDE the series lock: set_function callbacks
        # take subsystem locks (scheduler queue depth, pool occupancy)
        # whose holders write metrics — running them under this lock
        # closes a lock-order cycle (analysis lock-callback rule), and
        # a callback touching its own series would self-deadlock
        try:
            return float(fn())
        except Exception:
            return float("nan")


class _HistogramChild(_Child):
    __slots__ = ("_counts", "_sum", "_count", "_exemplars")

    def __init__(self, metric, labelvalues):
        super().__init__(metric, labelvalues)
        self._counts = [0] * (len(metric.buckets) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0
        # bucket index -> {"trace_id", "value"}: the newest observation
        # in that bucket that carried a trace id (OpenMetrics-style
        # exemplars — an slo_report p99 links straight to an assembled
        # trace in the telemetry collector)
        self._exemplars: dict[int, dict] = {}

    def observe(self, v: float, trace_id: str | None = None):
        if not (self._metric.always
                or self._metric._registry._enabled):
            return
        v = float(v)
        buckets = self._metric.buckets
        i = 0
        for i, b in enumerate(buckets):  # noqa: B007
            if v <= b:
                break
        else:
            i = len(buckets)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if trace_id:
                self._exemplars[i] = {"trace_id": str(trace_id),
                                      "value": v}

    def exemplars(self) -> dict[int, dict]:
        """{bucket index: {"trace_id", "value"}} — newest exemplar per
        bucket (index len(buckets) is +Inf)."""
        with self._lock:
            return {i: dict(e) for i, e in self._exemplars.items()}

    def snapshot(self):
        """(cumulative bucket counts incl +Inf, sum, count)."""
        with self._lock:
            counts = list(self._counts)
            s, c = self._sum, self._count
        cum, acc = [], 0
        for n in counts:
            acc += n
            cum.append(acc)
        return cum, s, c

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum


class _Metric:
    kind = "untyped"
    _child_cls = _Child

    def __init__(self, name: str, help_: str, labelnames, registry,
                 always: bool = False):
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        # always=True: writes ignore the registry kill switch. For
        # series that BACK a functional surface (Engine/Scheduler/
        # PagePool.stats read their counts from here) — disabling
        # telemetry must not freeze behavior callers relied on before
        # the registry rebase.
        self.always = bool(always)
        self._registry = registry
        self._children: dict[tuple, _Child] = {}
        # RLock: remove_matching() runs from gc-driven finalizers (a
        # dead Router/RpcClient dropping its per-instance series) and
        # gc can trigger inside labels()/_series() while THIS thread
        # already holds the lock — a plain Lock self-deadlocks there
        self._lock = threading.RLock()
        for ln in self.labelnames:
            if not _LABEL_RE.match(ln):
                raise MetricError(f"bad label name {ln!r}")
        if not self.labelnames:
            self._default = self._make_child(())
        else:
            self._default = None

    def _make_child(self, values):
        child = self._child_cls(self, values)
        self._children[values] = child
        return child

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise MetricError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.labelnames)}")
        values = tuple(str(kv[k]) for k in self.labelnames)
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._make_child(values)
            return child

    def _series(self):
        with self._lock:
            return list(self._children.items())

    def remove_matching(self, **kv):
        """Drop every child whose labels match the given subset (an
        engine/pool tearing down its per-instance series so a
        long-lived process's exposition does not grow with every
        instance ever created). Unknown label keys match nothing."""
        idx = {ln: i for i, ln in enumerate(self.labelnames)}
        if not all(k in idx for k in kv):
            return 0
        with self._lock:
            doomed = [vals for vals in self._children
                      if all(vals[idx[k]] == str(v)
                             for k, v in kv.items())]
            for vals in doomed:
                del self._children[vals]
            return len(doomed)

    # no-label convenience: metric itself acts as its default child
    def __getattr__(self, item):
        if item in ("inc", "dec", "set", "observe", "set_function",
                    "value", "count", "sum", "snapshot", "exemplars"):
            default = self.__dict__.get("_default")
            if default is None:
                raise MetricError(
                    f"{self.name} has labels {self.labelnames}; call "
                    f".labels(...) first")
            return getattr(default, item)
        raise AttributeError(item)


class Counter(_Metric):
    kind = "counter"
    _child_cls = _CounterChild


class Gauge(_Metric):
    kind = "gauge"
    _child_cls = _GaugeChild


class Histogram(_Metric):
    kind = "histogram"
    _child_cls = _HistogramChild

    def __init__(self, name, help_, labelnames, registry,
                 buckets=DEFAULT_BUCKETS, always: bool = False):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise MetricError("histogram needs at least one bucket")
        super().__init__(name, help_, labelnames, registry,
                         always=always)


class MetricsRegistry:
    """Process-wide metric store; see module docstring."""

    def __init__(self, enabled: bool | None = None):
        if enabled is None:
            enabled = os.environ.get("PADDLE_TPU_TELEMETRY", "1") != "0"
        self._enabled = bool(enabled)
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    # -- enable/disable -------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool):
        self._enabled = bool(on)

    # -- registration ---------------------------------------------------
    def _register(self, cls, name, help_, labels, **kw):
        if not _NAME_RE.match(name):
            raise MetricError(
                f"metric name {name!r} must match {_NAME_RE.pattern} "
                f"(snake_case with a paddle_tpu_ prefix)")
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if (type(m) is not cls
                        or m.labelnames != tuple(labels)
                        or (cls is Histogram and m.buckets != tuple(
                            sorted(float(b) for b in kw.get(
                                "buckets", DEFAULT_BUCKETS))))):
                    raise MetricError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}{m.labelnames} — conflicting "
                        f"re-registration")
                return m
            m = cls(name, help_, labels, self, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_: str = "", labels=(),
                always: bool = False) -> Counter:
        return self._register(Counter, name, help_, labels,
                              always=always)

    def gauge(self, name: str, help_: str = "", labels=(),
              always: bool = False) -> Gauge:
        return self._register(Gauge, name, help_, labels,
                              always=always)

    def histogram(self, name: str, help_: str = "", labels=(),
                  buckets=DEFAULT_BUCKETS,
                  always: bool = False) -> Histogram:
        return self._register(Histogram, name, help_, labels,
                              buckets=buckets, always=always)

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._metrics)

    # -- exposition -----------------------------------------------------
    def prometheus_text(self) -> str:
        """Prometheus text format 0.0.4 over every registered series."""
        out: list[str] = []
        with self._lock:
            metrics = sorted(self._metrics.items())
        for name, m in metrics:
            out.append(f"# HELP {name} {m.help}")
            out.append(f"# TYPE {name} {m.kind}")
            for values, child in sorted(m._series()):
                ls = _label_str(m.labelnames, values)
                if m.kind == "histogram":
                    cum, s, c = child.snapshot()
                    edges = list(m.buckets) + [float("inf")]
                    for b, n in zip(edges, cum):
                        inner = ",".join(filter(None, [
                            ls[1:-1] if ls else "",
                            f'le="{_fmt(b)}"']))
                        out.append(
                            f"{name}_bucket{{{inner}}} {n}")
                    out.append(f"{name}_sum{ls} {_fmt(s)}")
                    out.append(f"{name}_count{ls} {c}")
                else:
                    out.append(f"{name}{ls} {_fmt(child.value)}")
        return "\n".join(out) + "\n"

    def to_dict(self) -> dict:
        """JSON-safe snapshot (the file-dump / aggregation format)."""
        metrics = []
        with self._lock:
            items = sorted(self._metrics.items())
        for name, m in items:
            rec = {"name": name, "kind": m.kind, "help": m.help,
                   "labelnames": list(m.labelnames), "samples": []}
            if m.kind == "histogram":
                rec["buckets"] = list(m.buckets)
            for values, child in sorted(m._series()):
                sample = {"labels": dict(zip(m.labelnames, values))}
                if m.kind == "histogram":
                    cum, s, c = child.snapshot()
                    sample.update(cumulative=cum, sum=s, count=c)
                    ex = child.exemplars()
                    if ex:
                        sample["exemplars"] = {str(i): e
                                               for i, e in ex.items()}
                else:
                    v = child.value
                    # NaN/Inf-safe: json.dump would emit the
                    # nonstandard NaN/Infinity tokens strict parsers
                    # reject (autobench marks an erroring candidate
                    # with inf)
                    sample["value"] = v if math.isfinite(v) else None
                rec["samples"].append(sample)
            metrics.append(rec)
        return {"pid": os.getpid(), "host": socket.gethostname(),
                "time": time.time(), "metrics": metrics}

    def dump_to_file(self, path: str | None = None) -> str:
        """Write the JSON snapshot for this process (atomic rename).
        Default path: $PADDLE_TPU_METRICS_DIR/metrics_<host>_<pid>.json
        — the per-process dump `launch.py --metrics_dir` jobs aggregate."""
        if path is None:
            d = os.environ.get("PADDLE_TPU_METRICS_DIR") or "."
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"metrics_{socket.gethostname()}_{os.getpid()}.json")
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f)
        os.replace(tmp, path)
        return path


def aggregate_dumps(dumps: list[dict]) -> dict:
    """Merge per-process JSON dumps: counters and histograms SUM across
    processes; gauges keep the value from the newest dump that carries
    the series (a gauge is a point-in-time reading, not a flow)."""
    merged: dict[str, dict] = {}
    order = sorted(dumps, key=lambda d: d.get("time", 0))
    for dump in order:
        for m in dump.get("metrics", []):
            name = m["name"]
            tgt = merged.get(name)
            if tgt is None:
                tgt = merged[name] = {
                    "name": name, "kind": m["kind"], "help": m["help"],
                    "labelnames": m["labelnames"], "samples": {}}
                if "buckets" in m:
                    tgt["buckets"] = m["buckets"]
            for s in m["samples"]:
                key = tuple(sorted(s["labels"].items()))
                cur = tgt["samples"].get(key)
                if m["kind"] == "histogram":
                    if cur is None:
                        tgt["samples"][key] = {
                            "labels": s["labels"],
                            "cumulative": list(s["cumulative"]),
                            "sum": s["sum"], "count": s["count"]}
                    else:
                        cur["cumulative"] = [
                            a + b for a, b in zip(cur["cumulative"],
                                                  s["cumulative"])]
                        cur["sum"] += s["sum"]
                        cur["count"] += s["count"]
                elif m["kind"] == "gauge" or cur is None:
                    tgt["samples"][key] = dict(s)
                else:  # counter: sum
                    cur["value"] = (cur.get("value") or 0.0) \
                        + (s.get("value") or 0.0)
    out = []
    for name in sorted(merged):
        rec = merged[name]
        rec["samples"] = [rec["samples"][k]
                          for k in sorted(rec["samples"])]
        out.append(rec)
    return {"aggregated_from": len(dumps), "time": time.time(),
            "metrics": out}


def aggregate_dir(path: str) -> dict:
    """Aggregate every metrics_*.json under `path` (one per process,
    as written by dump_to_file / PADDLE_TPU_METRICS_DIR at exit)."""
    dumps = []
    for fn in sorted(os.listdir(path)):
        if fn.startswith("metrics_") and fn.endswith(".json"):
            with open(os.path.join(path, fn), encoding="utf-8") as f:
                dumps.append(json.load(f))
    return aggregate_dumps(dumps)


# process-wide default registry + module-level shortcuts
REGISTRY = MetricsRegistry()


def counter(name: str, help_: str = "", labels=(),
            always: bool = False) -> Counter:
    return REGISTRY.counter(name, help_, labels, always=always)


def gauge(name: str, help_: str = "", labels=(),
          always: bool = False) -> Gauge:
    return REGISTRY.gauge(name, help_, labels, always=always)


def histogram(name: str, help_: str = "", labels=(),
              buckets=DEFAULT_BUCKETS,
              always: bool = False) -> Histogram:
    return REGISTRY.histogram(name, help_, labels, buckets=buckets,
                              always=always)


def prometheus_text() -> str:
    return REGISTRY.prometheus_text()


def to_dict() -> dict:
    return REGISTRY.to_dict()


def dump_to_file(path: str | None = None) -> str:
    return REGISTRY.dump_to_file(path)


if __name__ == "__main__":  # python -m paddle_tpu.observability.registry
    import sys
    _dir = sys.argv[1] if len(sys.argv) > 1 else "."
    # bundle-aware job aggregation: metrics_*.json dumps PLUS the
    # metrics.json of every postmortem bundle in the dir, with a
    # "bundles" listing (reason/host/pid/valid) when any exist; only a
    # missing debug module degrades to the plain aggregate — a real
    # aggregation failure must surface, not masquerade as "no bundles"
    try:
        from .debug import aggregate_with_bundles
    except ImportError:
        agg = aggregate_dir(_dir)
    else:
        agg = aggregate_with_bundles(_dir)
    # merge the per-rank trace_<host>_<pid>.json span rings (the
    # SIGTERM dump / launch.py --metrics_dir artifacts) into ONE
    # Chrome trace with per-rank pid labels, using the telemetry
    # collector's merge code — one Perfetto load instead of one per
    # rank
    _parts = []
    for _fn in sorted(os.listdir(_dir) if os.path.isdir(_dir) else ()):
        if (_fn.startswith("trace_") and _fn.endswith(".json")
                and _fn != "trace_merged.json"):
            try:
                with open(os.path.join(_dir, _fn),
                          encoding="utf-8") as _f:
                    _doc = json.load(_f)
            except (OSError, json.JSONDecodeError):
                continue
            _parts.append((_fn[len("trace_"):-len(".json")],
                           _doc.get("traceEvents") or []))
    if _parts:
        from .collector import merge_chrome_traces
        _merged = merge_chrome_traces(_parts)
        _out = os.path.join(_dir, "trace_merged.json")
        _tmp = f"{_out}.tmp{os.getpid()}"
        with open(_tmp, "w", encoding="utf-8") as _f:
            json.dump(_merged, _f)
        os.replace(_tmp, _out)
        agg["trace_merged"] = {
            "path": _out, "ranks": len(_parts),
            "events": len(_merged["traceEvents"]) - len(_parts)}
    json.dump(agg, sys.stdout, indent=2)
    print()
