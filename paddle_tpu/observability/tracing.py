"""Structured host-side tracing: spans with trace/span ids.

The cross-tier half of the telemetry substrate (registry.py holds the
numbers; this holds the *timeline*):

  * ``span(name, **attrs)`` — context manager recording a host span
    into a bounded ring buffer; spans nest via a thread-local stack and
    children inherit their parent's ``trace_id``;
  * trace propagation — ``current_trace_id()`` reads the ambient id so
    a transport can carry it across processes (the PS wire skeleton
    carries it as ``_trace_id``, see runtime/rpc.py), and
    ``span(..., trace_id=...)`` re-roots the receiving side, so ONE
    generate request is followable frontend -> engine and
    worker -> PS server;
  * Chrome export — ``export_chrome_trace()`` emits ``trace_event``
    JSON (Perfetto / chrome://tracing), one complete event per span
    with trace/span ids in ``args``;
  * XPlane bridge — every recorded span also enters
    ``jax.profiler.TraceAnnotation`` when available, so host spans line
    up with device traces inside a ``jax.profiler.start_trace`` window.
    Older jax without the attr degrades to a silent no-op (the same
    guard utils/profiler.py uses).

``PADDLE_TPU_TRACE=0`` disables recording (ids still propagate so
downstream tiers keep correlating); ``PADDLE_TPU_TRACE_BRIDGE=0``
disables only the jax annotation bridge.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque

from . import registry as _obs

__all__ = ["Span", "Tracer", "TRACER", "span", "current_trace_id",
           "export_chrome_trace", "new_trace_id"]

# the span ring is bounded; overwrites used to be silent — mirror the
# flight rings' drop accounting so a reader knows the window clipped
_DROPPED = _obs.counter(
    "paddle_tpu_trace_dropped_total",
    "spans overwritten by a full trace ring")
_HIGH_WATER = _obs.gauge(
    "paddle_tpu_trace_ring_high_water",
    "max spans ever resident in the trace ring (ring size when the "
    "ring has wrapped)")


def new_trace_id() -> str:
    return os.urandom(8).hex()


def _jax_trace_annotation():
    """jax.profiler.TraceAnnotation, or None when jax/the attr is
    missing (older jax) — the graceful-no-op contract."""
    global _TA
    if _TA is _UNSET:
        try:
            import jax
            _TA = getattr(getattr(jax, "profiler", None),
                          "TraceAnnotation", None)
        except Exception:
            _TA = None
    return _TA


_UNSET = object()
_TA = _UNSET


class Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start",
                 "end", "tid", "attrs")

    def __init__(self, name, trace_id, span_id, parent_id, start,
                 tid, attrs):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start
        self.end = None
        self.tid = tid
        self.attrs = attrs

    def duration(self) -> float | None:
        return None if self.end is None else self.end - self.start

    def to_event(self) -> dict:
        """One Chrome trace_event 'X' (complete) event."""
        args = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id:
            args["parent_id"] = self.parent_id
        args.update(self.attrs)
        return {"name": self.name, "ph": "X", "cat": "paddle_tpu",
                "ts": round(self.start * 1e6, 3),
                "dur": round(((self.end or self.start) - self.start)
                             * 1e6, 3),
                "pid": os.getpid(), "tid": self.tid, "args": args}


class Tracer:
    """Bounded span recorder + thread-local trace context."""

    def __init__(self, max_spans: int = 16384, enabled: bool | None
                 = None, bridge_jax: bool | None = None):
        if enabled is None:
            enabled = os.environ.get("PADDLE_TPU_TRACE", "1") != "0"
        if bridge_jax is None:
            bridge_jax = os.environ.get(
                "PADDLE_TPU_TRACE_BRIDGE", "1") != "0"
        self.enabled = bool(enabled)
        self.bridge_jax = bool(bridge_jax)
        self._spans: deque[Span] = deque(maxlen=max_spans)
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._high_water = 0
        # optional per-span tap (the telemetry agent): called OUTSIDE
        # the ring lock with each finished span; must never block
        self._sink = None

    def set_sink(self, fn):
        """``fn(span)`` is called for every finished span (after ring
        append, outside the tracer lock). Pass None to detach. The sink
        must be cheap and non-blocking — it runs on the traced thread."""
        self._sink = fn

    # -- context --------------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current_trace_id(self) -> str | None:
        st = self._stack()
        return st[-1].trace_id if st else None

    def current_span(self) -> Span | None:
        st = self._stack()
        return st[-1] if st else None

    @contextlib.contextmanager
    def span(self, name: str, trace_id: str | None = None, **attrs):
        """Record one host span. ``trace_id`` re-roots the context (a
        request id that arrived over the wire); otherwise the ambient
        parent's id is inherited, else a fresh one is minted."""
        stack = self._stack()
        parent = stack[-1] if stack else None
        tid = trace_id or (parent.trace_id if parent else None) \
            or new_trace_id()
        sp = Span(name, tid, new_trace_id(),
                  parent.span_id if parent and parent.trace_id == tid
                  else None,
                  time.monotonic(), threading.get_ident(), attrs)
        stack.append(sp)
        ann = None
        if self.enabled and self.bridge_jax:
            ta = _jax_trace_annotation()
            if ta is not None:
                try:
                    ann = ta(name)
                    ann.__enter__()
                except Exception:
                    ann = None
        try:
            yield sp
        finally:
            sp.end = time.monotonic()
            if ann is not None:
                try:
                    ann.__exit__(None, None, None)
                except Exception:
                    pass
            stack.pop()
            if self.enabled:
                with self._lock:
                    if len(self._spans) == self._spans.maxlen:
                        _DROPPED.inc()
                    self._spans.append(sp)
                    n = len(self._spans)
                    if n > self._high_water:
                        self._high_water = n
                        _HIGH_WATER.set(n)
                sink = self._sink
                if sink is not None:
                    try:
                        sink(sp)
                    except Exception:
                        pass

    # -- inspection / export --------------------------------------------
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self):
        with self._lock:
            self._spans.clear()

    def export_chrome_trace(self, path: str | None = None) -> dict:
        """{"traceEvents": [...]} — load in Perfetto/chrome://tracing.
        Open it next to the XPlane trace of the same window: the bridge
        gives device-side TraceMe slices the same span names."""
        doc = {"traceEvents": [s.to_event() for s in self.spans()],
               "displayTimeUnit": "ms"}
        if path:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        return doc


TRACER = Tracer()
span = TRACER.span
current_trace_id = TRACER.current_trace_id
export_chrome_trace = TRACER.export_chrome_trace
