"""Performance observability plane: cost registry, MFU, step attribution.

This module turns "MFU is 44%" into "these buckets/phases burn the gap".
Three pieces, all views over the one metrics registry:

* **cost registry** — every jitted callable we own (Executor programs,
  Engine per-bucket prefill/decode, fused-block ops) registers its
  ``lower().cost_analysis()`` FLOPs / bytes-accessed at trace time,
  keyed by ``(name, key)`` where ``key`` is the compile bucket or feed
  shape.  Exposed as ``paddle_tpu_perf_flops`` / ``paddle_tpu_perf_bytes``
  gauges and a :func:`roofline` table (arithmetic intensity vs the
  chip's ridge point).
* **step-time decomposition** — :class:`StepSampler` gates a sampled
  profile of one step in ``PADDLE_TPU_PERFWATCH_EVERY`` (default 50;
  0 disables).  On a sampled step the caller fences phase boundaries
  with ``block_until_ready`` and reports host / dispatch / device /
  transfer seconds via :func:`record_breakdown`; between samples the
  hot path is untouched, so steady-state overhead stays ~0.
* **MFU accounting** — :func:`chip_peak_flops` resolves the chip's
  peak bf16 FLOP/s from ``jax.devices()[0].device_kind`` (bench.py
  delegates here, so live gauges and bench reports share one peak
  table by construction) and :func:`mfu` converts achieved FLOP/s to
  model-flops-utilisation.

:func:`snapshot` serialises the whole plane (costs, breakdowns, kernel
margins, HBM stats) into the schema-versioned dict ``perfwatch record``
writes and ``perfwatch compare`` diffs.
"""
from __future__ import annotations

import math
import os
import threading
import time
import weakref

from . import flight as _flight
from . import registry as _obs

__all__ = [
    "SNAPSHOT_SCHEMA",
    "StepSampler",
    "analytic_gpt_flops",
    "chip_peak_bytes_per_s",
    "chip_peak_flops",
    "breakdowns",
    "costs",
    "drop_instance",
    "kernels",
    "kv_cache_gauge",
    "mfu",
    "mfu_gauge",
    "note_compile_seconds",
    "note_kernel",
    "record_breakdown",
    "register_cost",
    "register_jit_cost",
    "register_provider",
    "reset",
    "roofline",
    "sampling_every",
    "set_every",
    "set_mfu",
    "snapshot",
    "weak_provider",
]

SNAPSHOT_SCHEMA = "paddle_tpu.perf/1"

# ---------------------------------------------------------------------------
# Peak tables.  bench.py's chip_peak_flops() delegates here so the live
# MFU gauges and the bench reports can never disagree on the peak.
# ---------------------------------------------------------------------------

# (device_kind substring, peak bf16 FLOP/s).  Order matters: first match
# wins, so the more specific names come first.
_PEAKS = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5litepod", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]
_DEFAULT_PEAK = 275e12

# (device_kind substring, HBM bandwidth bytes/s) — for the roofline
# ridge point.  Same shape as _PEAKS; override with TPU_PEAK_GBPS.
_BWS = [
    ("v6", 1640e9),
    ("v5p", 2765e9),
    ("v5 lite", 819e9),
    ("v5e", 819e9),
    ("v5litepod", 819e9),
    ("v5", 2765e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
]
_DEFAULT_BW = 1228e9


def _device_kind() -> str:
    try:
        import jax

        return str(jax.devices()[0].device_kind)
    except Exception:
        return "unknown"


def chip_peak_flops() -> tuple[float, str]:
    """(peak bf16 FLOP/s, device kind) for one chip.

    ``TPU_PEAK_TFLOPS_BF16`` overrides the table (e.g. for new chips or
    int8 serving); on CPU the TPU-class default keeps MFU numbers
    comparable across hosts rather than meaningful in absolute terms.
    """
    kind = _device_kind()
    env = os.environ.get("TPU_PEAK_TFLOPS_BF16")
    if env:
        try:
            return float(env) * 1e12, kind
        except ValueError:
            pass
    low = kind.lower()
    for sub, peak in _PEAKS:
        if sub in low:
            return peak, kind
    return _DEFAULT_PEAK, kind


def chip_peak_bytes_per_s() -> tuple[float, str]:
    """(HBM bandwidth bytes/s, device kind); ``TPU_PEAK_GBPS`` overrides."""
    kind = _device_kind()
    env = os.environ.get("TPU_PEAK_GBPS")
    if env:
        try:
            return float(env) * 1e9, kind
        except ValueError:
            pass
    low = kind.lower()
    for sub, bw in _BWS:
        if sub in low:
            return bw, kind
    return _DEFAULT_BW, kind


def mfu(flops: float, seconds: float) -> float:
    """Model-flops-utilisation of `flops` model FLOPs in `seconds`."""
    if seconds <= 0 or flops <= 0:
        return 0.0
    peak, _ = chip_peak_flops()
    return float(flops) / seconds / peak


def analytic_gpt_flops(cfg, tokens: int, ctx: int) -> float:
    """Matmul-only forward FLOPs for `tokens` new tokens of a GPT block
    stack at context length `ctx` — the fallback when XLA cost analysis
    is unavailable.  Matches bench.py's convention (qkv+proj+mlp+attn
    matmuls + the LM head, no norms/softmax)."""
    H = int(getattr(cfg, "hidden_size", 0))
    L = int(getattr(cfg, "num_layers", 0))
    F = int(getattr(cfg, "intermediate_size", 4 * H) or 4 * H)
    V = int(getattr(cfg, "vocab_size", 0))
    if not (H and L):
        return 0.0
    per_layer = (
        3 * 2 * H * H        # qkv projections
        + 2 * H * H          # output projection
        + 2 * 2 * ctx * H    # qk^T and attn@v
        + 2 * H * F + 2 * F * H  # mlp
    )
    return float(tokens) * (L * per_layer + 2 * H * V)


# ---------------------------------------------------------------------------
# Metric series (the ONE registration site for every paddle_tpu_perf_*
# name — check_metric_names.py holds this).
# ---------------------------------------------------------------------------

_FLOPS = _obs.gauge(
    "paddle_tpu_perf_flops",
    "XLA/analytic FLOPs per invocation of a jitted callable",
    ["name", "key"])
_BYTES = _obs.gauge(
    "paddle_tpu_perf_bytes",
    "XLA bytes accessed per invocation of a jitted callable",
    ["name", "key"])
_MFU = _obs.gauge(
    "paddle_tpu_perf_mfu",
    "live model-flops-utilisation (achieved/peak) per instrumented loop",
    ["name"])
_BREAKDOWN = _obs.gauge(
    "paddle_tpu_perf_step_breakdown_seconds",
    "last sampled step-time decomposition (host/dispatch/device/transfer)",
    ["name", "phase"])
# Compiles run 0.1s (tiny CPU programs) to minutes (big TPU models);
# the default request-latency buckets top out far too low.
_COMPILE_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                    30.0, 60.0, 120.0, 300.0)
_COMPILE_H = _obs.histogram(
    "paddle_tpu_perf_compile_seconds",
    "jit compile wall time per site (first-call wall clock)",
    ["site"], buckets=_COMPILE_BUCKETS)
_HBM = _obs.gauge(
    "paddle_tpu_perf_hbm_bytes",
    "device memory stats from jax (0 when the backend has none)",
    ["kind"])
_KV_BYTES = _obs.gauge(
    "paddle_tpu_perf_kv_cache_bytes",
    "bytes held by a serving engine's paged KV cache",
    ["engine"])


def _hbm_stat(stat: str) -> float:
    try:
        import jax

        st = jax.devices()[0].memory_stats()
        if st:
            return float(st.get(stat, 0) or 0)
    except Exception:
        pass
    return 0.0


_HBM.labels(kind="in_use").set_function(lambda: _hbm_stat("bytes_in_use"))
_HBM.labels(kind="limit").set_function(lambda: _hbm_stat("bytes_limit"))
_HBM.labels(kind="peak").set_function(lambda: _hbm_stat("peak_bytes_in_use"))


def kv_cache_gauge(engine_id: str):
    """Per-engine KV-cache-bytes gauge child (engine sets a weakref
    function on it; dropped with the engine's other series)."""
    return _KV_BYTES.labels(engine=engine_id)


def mfu_gauge(name: str):
    """Labeled MFU gauge child for `name` (callers may set_function)."""
    return _MFU.labels(name=name)


# ---------------------------------------------------------------------------
# Cost registry
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_COSTS: dict[tuple[str, str], dict] = {}
_BREAKDOWNS: dict[str, dict] = {}
_KERNELS: dict[str, dict] = {}
_MFU_VALUES: dict[str, float] = {}
# name -> zero-arg callable returning a JSON-safe dict merged into
# snapshot()["providers"].  Callables must be cheap and must not block.
_PROVIDERS: dict[str, object] = {}


def costs_enabled() -> bool:
    return os.environ.get("PADDLE_TPU_PERFWATCH_COSTS", "1") != "0"


def register_cost(name: str, key: str, flops: float | None,
                  bytes_accessed: float | None = None,
                  source: str = "analytic") -> float | None:
    """Record the per-invocation cost of jitted callable (name, key)."""
    fl = float(flops) if flops and flops > 0 else None
    by = float(bytes_accessed) if bytes_accessed and bytes_accessed > 0 else None
    with _LOCK:
        _COSTS[(name, key)] = {"flops": fl, "bytes": by, "source": source}
    if fl is not None:
        _FLOPS.labels(name=name, key=key).set(fl)
    if by is not None:
        _BYTES.labels(name=name, key=key).set(by)
    return fl


def _cost_from_analysis(ca) -> tuple[float | None, float | None]:
    # jax returns a dict, a list of per-computation dicts, or None
    # depending on version/backend.
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None, None
    fl = ca.get("flops")
    by = ca.get("bytes accessed")
    fl = float(fl) if isinstance(fl, (int, float)) and fl > 0 else None
    by = float(by) if isinstance(by, (int, float)) and by > 0 else None
    return fl, by


def register_jit_cost(name: str, key: str, jitfn, *args,
                      analytic_flops: float | None = None) -> float | None:
    """Lower `jitfn(*args)` and register its XLA cost analysis.

    Lowering is abstract (shapes only — safe with donated buffers) but
    not free, so call this once per compile bucket, on the same path
    that pays the compile.  Falls back to `analytic_flops` when the
    backend reports nothing; never raises.
    """
    fl = by = None
    if costs_enabled():
        try:
            fl, by = _cost_from_analysis(jitfn.lower(*args).cost_analysis())
        except Exception:
            fl = by = None
    if fl is not None:
        return register_cost(name, key, fl, by, source="xla")
    return register_cost(name, key, analytic_flops, by, source="analytic")


def costs() -> dict[tuple[str, str], dict]:
    with _LOCK:
        return {k: dict(v) for k, v in _COSTS.items()}


def roofline() -> list[dict]:
    """Rows of (name, key, flops, bytes, intensity, bound, frac_of_ridge).

    `bound` says whether the op sits left (memory-bound) or right
    (compute-bound) of the chip's ridge point peak_flops/peak_bw.
    """
    peak, _ = chip_peak_flops()
    bw, _ = chip_peak_bytes_per_s()
    ridge = peak / bw if bw else float("inf")
    rows = []
    for (name, key), c in sorted(costs().items()):
        fl, by = c.get("flops"), c.get("bytes")
        inten = (fl / by) if fl and by else None
        rows.append({
            "name": name, "key": key,
            "flops": fl, "bytes": by,
            "intensity": inten,
            "ridge": ridge,
            "bound": (None if inten is None
                      else ("compute" if inten >= ridge else "memory")),
            "source": c.get("source"),
        })
    return rows


# ---------------------------------------------------------------------------
# Step sampling + breakdown
# ---------------------------------------------------------------------------

def _env_every() -> int:
    try:
        return max(0, int(os.environ.get("PADDLE_TPU_PERFWATCH_EVERY", "50")))
    except ValueError:
        return 50


_EVERY = _env_every()


def sampling_every() -> int:
    """Current sampling cadence (every Nth step; 0 = off)."""
    return _EVERY


def set_every(n: int) -> None:
    """Override the sampling cadence at runtime (bench A/B/A, tests)."""
    global _EVERY
    _EVERY = max(0, int(n))


class StepSampler:
    """Decides which steps pay for a fenced profile.

    ``tick()`` returns True on every Nth call where N is the *current*
    module cadence (so ``set_every`` toggles live samplers too).  The
    first tick never samples: step 1 is usually a compile.
    """

    __slots__ = ("name", "_n")

    def __init__(self, name: str):
        self.name = name
        self._n = 0

    def tick(self) -> bool:
        every = _EVERY
        if every <= 0:
            return False
        self._n += 1
        return self._n % every == 0


def record_breakdown(name: str, phases: dict[str, float]) -> None:
    """Report one sampled step's phase decomposition (seconds)."""
    now = time.time()
    with _LOCK:
        ent = _BREAKDOWNS.setdefault(name, {"samples": 0, "phases": {}})
        ent["samples"] += 1
        ent["time"] = now
        for ph, v in phases.items():
            ent["phases"][ph] = float(v)
    for ph, v in phases.items():
        _BREAKDOWN.labels(name=name, phase=ph).set(float(v))
    _flight.record("perf", "sample", name=name,
                   **{k: round(float(v), 6) for k, v in phases.items()})


def breakdowns() -> dict[str, dict]:
    with _LOCK:
        return {k: {"samples": v["samples"], "time": v.get("time"),
                    "phases": dict(v["phases"])}
                for k, v in _BREAKDOWNS.items()}


def set_mfu(name: str, value: float) -> None:
    """Set the live MFU gauge for `name` (explicit-update style; loops
    that prefer pull register a set_function on mfu_gauge instead)."""
    v = float(value)
    if not math.isfinite(v):
        v = 0.0
    with _LOCK:
        _MFU_VALUES[name] = v
    _MFU.labels(name=name).set(v)


def note_compile_seconds(site: str, seconds: float) -> None:
    """Record one jit compile's wall time (first-call wall clock)."""
    _COMPILE_H.labels(site=site).observe(float(seconds))


# ---------------------------------------------------------------------------
# Kernel margins (autobench feeds this)
# ---------------------------------------------------------------------------

def note_kernel(key: str, winner: str, timings_ms: dict[str, float]) -> None:
    """Record an autobench decision: all measured candidate times, the
    winner, and the winner's margin over the best loser."""
    ts = {c: float(v) for c, v in timings_ms.items() if math.isfinite(v)}
    margin = None
    win_ms = ts.get(winner)
    losers = [v for c, v in ts.items() if c != winner]
    if win_ms and losers:
        margin = min(losers) / win_ms  # >1: winner is margin× faster
    with _LOCK:
        _KERNELS[key] = {"winner": winner, "candidates_ms": ts,
                         "margin": margin}


def kernels() -> dict[str, dict]:
    with _LOCK:
        return {k: dict(v) for k, v in _KERNELS.items()}


# ---------------------------------------------------------------------------
# Providers + snapshot
# ---------------------------------------------------------------------------

def register_provider(name: str, fn) -> None:
    """Register a cheap zero-arg callable contributing a dict to
    snapshot()["providers"][name] (engines register a weakref-wrapped
    rates summary).  Re-registering replaces."""
    with _LOCK:
        _PROVIDERS[name] = fn


def unregister_provider(name: str) -> None:
    with _LOCK:
        _PROVIDERS.pop(name, None)


def drop_instance(name: str, engine_id: str | None = None) -> None:
    """Drop the per-instance series for a garbage-collected owner."""
    unregister_provider(name)
    _MFU.remove_matching(name=name)
    _BREAKDOWN.remove_matching(name=name)
    if engine_id is not None:
        _KV_BYTES.remove_matching(engine=engine_id)
    with _LOCK:
        _BREAKDOWNS.pop(name, None)
        _MFU_VALUES.pop(name, None)


def snapshot() -> dict:
    """Schema-versioned JSON-safe dump of the whole perf plane — the
    payload of ``perfwatch record`` and the input to ``compare``."""
    peak, kind = chip_peak_flops()
    bw, _ = chip_peak_bytes_per_s()
    with _LOCK:
        providers = dict(_PROVIDERS)
        mfus = dict(_MFU_VALUES)
    prov_out = {}
    for name, fn in providers.items():  # outside _LOCK: fns may lock
        try:
            d = fn()
            if isinstance(d, dict):
                prov_out[name] = d
        except Exception:
            pass
    return {
        "schema": SNAPSHOT_SCHEMA,
        "created_unix": time.time(),
        "device_kind": kind,
        "peak_flops": peak,
        "peak_bytes_per_s": bw,
        "costs": [
            {"name": n, "key": k, **c} for (n, k), c in sorted(costs().items())
        ],
        "breakdown": breakdowns(),
        "mfu": mfus,
        "kernels": kernels(),
        "hbm": {k: _hbm_stat(s) for k, s in
                (("in_use", "bytes_in_use"), ("limit", "bytes_limit"),
                 ("peak", "peak_bytes_in_use"))},
        "providers": prov_out,
    }


def reset() -> None:
    """Test hook: clear tables and per-(name,key) series."""
    with _LOCK:
        _COSTS.clear()
        _BREAKDOWNS.clear()
        _KERNELS.clear()
        _MFU_VALUES.clear()
        _PROVIDERS.clear()
    for g in (_FLOPS, _BYTES):
        g.remove_matching()
    _MFU.remove_matching()
    _BREAKDOWN.remove_matching()


def weak_provider(obj, method_name: str):
    """A provider callable holding only a weakref to `obj`."""
    ref = weakref.ref(obj)
    def call():
        o = ref()
        if o is None:
            return {}
        return getattr(o, method_name)()
    return call
