"""Embedded append-only time-series store for the fleet collector.

The telemetry collector is point-in-time: `top` shows "now" and the
flight recorder shows "recently". This module gives the fleet history
— every metric snapshot an agent pushes (`tel_push` ride-along
registry dumps plus per-push fleet summaries) lands in an embedded
TSDB the collector hosts, so "what did p99 TTFT look like over the
last hour, per tenant?" is a `tsdb_query` away and the alert engine
(`observability.alerts`) has something to evaluate burn rates over.

Storage model (the PR-4 WAL idiom, docs/OBSERVABILITY.md):

  * ``active.tsb`` — append-only CRC'd records
    (``magic | crc32(payload) | len | payload``), one record per
    ingested batch. A torn tail (crash mid-write) is detected by the
    same magic/length/CRC walk the checkpoint WAL uses and truncated
    on reopen — committed history survives, the torn record does not.
  * sealed blocks — when the active file exceeds the block budget its
    committed records are downsampled to the 10s tier and rewritten
    as ``block-<seq>.tsb`` via tmp + fsync + ``os.rename`` (atomic
    publish; a crash leaves either the old active or the sealed
    block, never a half block).
  * retention — when total on-disk bytes exceed the budget the oldest
    sealed block is first compacted to the 5m tier
    (``block-<seq>c.tsb``, same tmp+rename publish) and only deleted
    once already compacted; history degrades in resolution before it
    disappears.

In memory each series keeps three query tiers — raw points over a
short window, 10s last-sample buckets, 5m last-sample buckets — so
queries pick the finest tier that still covers the asked-for range.
Last-sample-per-bucket downsampling is exact for cumulative counters
and cumulative histogram buckets (the only shapes the registry
exports), which is what keeps ``rate()`` and ``quantile()`` honest
after compaction.

Histograms are stored bucket-aware (cumulative counts + sum + count
per sample), so p50/p99 over any past window is computable after the
fact: ``quantile()`` takes the elementwise bucket delta across the
window and runs the same nearest-bucket estimate the collector's live
summary uses.

A ``TimeSeriesDB(dir_=None)`` is memory-only (tests, hosted
collectors without a data dir); set ``PADDLE_TPU_TSDB_DIR`` (or
``launch.py --tsdb_dir``) for durable history.
"""
from __future__ import annotations

import json
import math
import os
import re
import struct
import threading
import zlib
from collections import deque

from . import registry as _obs

__all__ = ["TimeSeriesDB", "series_key", "hist_quantile",
           "TSB_MAGIC", "committed_records"]

# record framing: magic u32 | crc32(payload) u32 | payload_len u64
# (the checkpoint WAL's layout with its own magic, so a stray WAL file
# in the TSDB dir is rejected rather than replayed)
TSB_MAGIC = 0x50545342  # "PTSB"
_REC = struct.Struct("<IIQ")
_MAX_RECORD = 64 * 1024 * 1024

_BLOCK_RE = re.compile(r"^block-(\d+)(c?)\.tsb$")

_SAMPLES = _obs.counter(
    "paddle_tpu_tsdb_samples_total",
    "samples appended to the collector time-series store")
_SERIES = _obs.gauge(
    "paddle_tpu_tsdb_series",
    "live series tracked by the collector time-series store")
_DISK = _obs.gauge(
    "paddle_tpu_tsdb_bytes_on_disk",
    "bytes held by TSDB block files (active + sealed)")
_SEALED = _obs.counter(
    "paddle_tpu_tsdb_blocks_sealed_total",
    "active TSDB segments sealed into 10s-tier blocks")
_COMPACTED = _obs.counter(
    "paddle_tpu_tsdb_blocks_compacted_total",
    "sealed TSDB blocks compacted to the 5m tier under retention")
_DELETED = _obs.counter(
    "paddle_tpu_tsdb_blocks_deleted_total",
    "TSDB blocks deleted by byte-budget retention")
_TORN = _obs.counter(
    "paddle_tpu_tsdb_torn_tail_truncated_total",
    "torn TSDB tails truncated on reopen (crash mid-append)")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def series_key(name: str, labels: dict | None) -> str:
    """Canonical ``name{k="v",...}`` identity (sorted label keys)."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


def hist_quantile(buckets, cumulative, q: float) -> float | None:
    """Nearest-bucket quantile from cumulative histogram counts (upper
    bound of the first bucket reaching rank q; same estimate as the
    collector's live summary, so history agrees with `top`)."""
    if not cumulative or cumulative[-1] <= 0:
        return None
    rank = q * cumulative[-1]
    for i, c in enumerate(cumulative):
        if c >= rank:
            return float(buckets[i]) if i < len(buckets) \
                else float(buckets[-1])
    return float(buckets[-1])


def committed_records(blob: bytes):
    """Yield ``(payload_bytes, end_offset)`` for each committed record;
    stops at the first bad magic / short frame / CRC mismatch — the
    checkpoint WAL's torn-tail walk."""
    off = 0
    n = len(blob)
    while off + _REC.size <= n:
        magic, crc, length = _REC.unpack_from(blob, off)
        if magic != TSB_MAGIC or length > _MAX_RECORD:
            return
        start = off + _REC.size
        end = start + length
        if end > n:
            return
        payload = blob[start:end]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            return
        yield payload, end
        off = end


def _encode_record(payload: dict) -> bytes:
    raw = json.dumps(payload, separators=(",", ":"),
                     sort_keys=True).encode("utf-8")
    return _REC.pack(TSB_MAGIC, zlib.crc32(raw) & 0xFFFFFFFF,
                     len(raw)) + raw


class _Series:
    """One series: identity + the three in-memory query tiers."""

    __slots__ = ("key", "name", "labels", "kind", "buckets",
                 "raw", "mid", "coarse", "first_t", "last_t")

    def __init__(self, key, name, labels, kind, buckets=None):
        self.key = key
        self.name = name
        self.labels = dict(labels or {})
        self.kind = kind
        self.buckets = list(buckets) if buckets else None
        self.raw: deque = deque()    # (t, value), append order = time
        self.mid: dict = {}          # 10s bucket start -> (t, value)
        self.coarse: dict = {}       # 5m bucket start -> (t, value)
        self.first_t: float | None = None
        self.last_t: float | None = None

    def append(self, t: float, value, raw_window: float,
               mid_keep: int, coarse_keep: int):
        if self.first_t is None or t < self.first_t:
            self.first_t = t
        if self.last_t is None or t > self.last_t:
            self.last_t = t
        self.raw.append((t, value))
        while self.raw and self.raw[0][0] < t - raw_window:
            self.raw.popleft()
        self.mid[int(t // 10.0) * 10] = (t, value)
        self.coarse[int(t // 300.0) * 300] = (t, value)
        # bucket dicts grow once per bucket, so the trim triggers at
        # most once per bucket rollover — O(n log n) is fine here
        if len(self.mid) > mid_keep:
            for b in sorted(self.mid)[:len(self.mid) - mid_keep]:
                del self.mid[b]
        if len(self.coarse) > coarse_keep:
            for b in sorted(self.coarse)[:len(self.coarse)
                                         - coarse_keep]:
                del self.coarse[b]

    def points(self, start: float, end: float) -> list:
        """Time-ordered (t, value) over [start, end], finest tier
        winning where tiers overlap."""
        raw_first = self.raw[0][0] if self.raw else math.inf
        mid_pts = [self.mid[b] for b in sorted(self.mid)]
        mid_first = mid_pts[0][0] if mid_pts else math.inf
        out = [p for b in sorted(self.coarse)
               for p in (self.coarse[b],) if p[0] < mid_first]
        out.extend(p for p in mid_pts if p[0] < raw_first)
        out.extend(self.raw)
        return [p for p in out if start <= p[0] <= end]

    def value_at(self, t: float):
        """Last value at or before t (None if the series starts
        later) — the window-edge read rate()/quantile() build on."""
        prev = None
        for pt, pv in self.points(-math.inf, t):
            prev = pv
        return prev


def _scalar(v) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return 0.0


class TimeSeriesDB:
    """See module docstring. Thread-safe behind one lock; every
    public query returns plain copies, so callers never hold it."""

    def __init__(self, dir_: str | None = None,
                 retention_bytes: int | None = None,
                 block_bytes: int | None = None,
                 raw_window_s: float | None = None,
                 mid_keep: int = 2160, coarse_keep: int = 2016):
        if dir_ is None:
            dir_ = os.environ.get("PADDLE_TPU_TSDB_DIR") or None
        if retention_bytes is None:
            retention_bytes = int(_env_float(
                "PADDLE_TPU_TSDB_RETENTION_BYTES", 64 * 2**20))
        if block_bytes is None:
            block_bytes = int(_env_float(
                "PADDLE_TPU_TSDB_BLOCK_BYTES", 1 * 2**20))
        if raw_window_s is None:
            raw_window_s = _env_float("PADDLE_TPU_TSDB_RAW_WINDOW",
                                      900.0)
        self.dir = dir_
        self.retention_bytes = max(4096, int(retention_bytes))
        self.block_bytes = max(4096, int(block_bytes))
        self.raw_window_s = max(1.0, float(raw_window_s))
        self.mid_keep = max(16, int(mid_keep))
        self.coarse_keep = max(16, int(coarse_keep))
        self._lock = threading.Lock()
        self._series: dict[str, _Series] = {}
        self._fd: int | None = None
        self._active_bytes = 0
        self._block_sizes: dict[str, int] = {}  # fname -> bytes
        self._seq = 0
        self._meta_written: set[str] = set()
        self.counts = {"appended": 0, "sealed": 0, "compacted": 0,
                       "deleted": 0, "torn": 0, "replayed": 0}
        if self.dir:
            os.makedirs(self.dir, exist_ok=True)
            with self._lock:
                self._open_locked()

    # -- disk: open / replay -------------------------------------------
    def _blocks_locked(self) -> list[tuple[int, bool, str]]:
        """(seq, compacted, fname) for every sealed block, seq order.
        When both the raw and the compacted block of one seq exist, a
        crash hit between the compaction rename and the unlink: the
        compacted block is the committed one, the raw original goes."""
        found: dict[int, dict[bool, str]] = {}
        for fn in os.listdir(self.dir):
            m = _BLOCK_RE.match(fn)
            if m:
                found.setdefault(int(m.group(1)), {})[
                    m.group(2) == "c"] = fn
        out = []
        for seq in sorted(found):
            pair = found[seq]
            if True in pair and False in pair:
                try:
                    os.unlink(os.path.join(self.dir, pair[False]))
                except OSError:
                    pass
                del pair[False]
            compacted = True in pair
            out.append((seq, compacted, pair[compacted]))
        return out

    def _open_locked(self):
        for seq, compacted, fn in self._blocks_locked():
            path = os.path.join(self.dir, fn)
            try:
                with open(path, "rb") as f:
                    blob = f.read()
            except OSError:
                continue
            self._block_sizes[fn] = len(blob)
            for payload, _ in committed_records(blob):
                self._replay_payload(payload)
            self._seq = max(self._seq, seq + 1)
        active = os.path.join(self.dir, "active.tsb")
        blob = b""
        if os.path.exists(active):
            with open(active, "rb") as f:
                blob = f.read()
        good = 0
        for payload, end in committed_records(blob):
            self._replay_payload(payload)
            good = end
        if good < len(blob):
            os.truncate(active, good)
            self.counts["torn"] += 1
            _TORN.inc()
        self._fd = os.open(active,
                           os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                           0o644)
        self._active_bytes = good
        self._publish_gauges_locked()

    def _replay_payload(self, payload: bytes):
        try:
            rec = json.loads(payload)
        except ValueError:
            return
        for key, meta in (rec.get("m") or {}).items():
            self._series_for_locked(
                key, meta.get("name") or key,
                meta.get("labels") or {},
                meta.get("kind") or "gauge", meta.get("b"))
        t = float(rec.get("t") or 0.0)
        for key, enc in (rec.get("s") or {}).items():
            s = self._series.get(key)
            if s is None:
                s = self._series_for_locked(key, key, {}, "gauge",
                                            None)
            s.append(t, self._decode_value(enc), self.raw_window_s,
                     self.mid_keep, self.coarse_keep)
            self.counts["replayed"] += 1

    @staticmethod
    def _decode_value(enc):
        if isinstance(enc, dict):
            return (tuple(_scalar(c) for c in enc.get("c") or ()),
                    _scalar(enc.get("s")), _scalar(enc.get("n")))
        return _scalar(enc)

    @staticmethod
    def _encode_value(v):
        if isinstance(v, tuple):
            return {"c": list(v[0]), "s": v[1], "n": v[2]}
        return v

    # -- ingest --------------------------------------------------------
    def _series_for_locked(self, key, name, labels, kind, buckets):
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = _Series(key, name, labels, kind,
                                            buckets)
        return s

    def append(self, t: float, entries) -> int:
        """Append one batch. ``entries``: iterable of
        ``(name, labels, kind, value, buckets)`` where value is a
        float (counter/gauge) or ``(cumulative, sum, count)`` for a
        histogram. Returns the number of samples appended."""
        n = 0
        with self._lock:
            samples = {}
            meta = {}
            for name, labels, kind, value, buckets in entries:
                key = series_key(name, labels)
                s = self._series_for_locked(key, name, labels, kind,
                                            buckets)
                s.append(float(t), value, self.raw_window_s,
                         self.mid_keep, self.coarse_keep)
                samples[key] = self._encode_value(value)
                if self._fd is not None \
                        and key not in self._meta_written:
                    meta[key] = self._meta_locked(s)
                    self._meta_written.add(key)
                n += 1
            if n == 0:
                return 0
            self.counts["appended"] += n
            _SAMPLES.inc(n)
            if self._fd is not None:
                rec = {"t": float(t), "s": samples}
                if meta:
                    rec["m"] = meta
                buf = _encode_record(rec)
                os.write(self._fd, buf)
                self._active_bytes += len(buf)
                if self._active_bytes >= self.block_bytes:
                    self._seal_locked()
                    self._enforce_retention_locked()
            self._publish_gauges_locked()
        return n

    @staticmethod
    def _meta_locked(s: _Series) -> dict:
        m = {"name": s.name, "labels": s.labels, "kind": s.kind}
        if s.buckets:
            m["b"] = s.buckets
        return m

    def ingest_dump(self, host: str, pid, role: str, dump: dict,
                    ts: float | None = None) -> int:
        """One full registry dump (the agent's every-Nth-flush
        ride-along). host/pid/role become labels — the sample's own
        labels win on collision — so fleet-wide queries sum across
        processes and per-process history stays addressable."""
        t = float(ts if ts is not None
                  else dump.get("time") or 0.0)
        base = {"host": str(host), "pid": str(pid),
                "role": str(role)}
        entries = []
        for m in dump.get("metrics", ()):
            kind = m.get("kind") or "gauge"
            buckets = m.get("buckets")
            for smp in m.get("samples", ()):
                labels = dict(base)
                labels.update(smp.get("labels") or {})
                if kind == "histogram":
                    v = (tuple(_scalar(c)
                               for c in smp.get("cumulative") or ()),
                         _scalar(smp.get("sum")),
                         _scalar(smp.get("count")))
                else:
                    if smp.get("value") is None:
                        continue
                    v = _scalar(smp.get("value"))
                entries.append((m["name"], labels, kind, v, buckets))
        return self.append(t, entries)

    def ingest_scalars(self, t: float, values: dict,
                       labels: dict | None = None,
                       kind: str = "gauge") -> int:
        """Flat ``{name: number}`` ingest (per-push fleet summary
        scalars land through here on every tel_push)."""
        entries = [(name, labels, kind, _scalar(v), None)
                   for name, v in values.items()
                   if isinstance(v, (int, float))
                   and math.isfinite(float(v))]
        return self.append(t, entries)

    # -- seal / compaction / retention ---------------------------------
    def _downsample_records(self, payloads, bucket_s: float):
        """Re-bucket committed record payloads to last-sample-per-
        bucket-per-series; yields (meta, [(bucket_t, {key: enc})])."""
        meta: dict[str, dict] = {}
        per_bucket: dict[float, dict] = {}
        for payload in payloads:
            try:
                rec = json.loads(payload)
            except ValueError:
                continue
            for key, m in (rec.get("m") or {}).items():
                meta.setdefault(key, m)
            t = float(rec.get("t") or 0.0)
            b = int(t // bucket_s) * bucket_s
            slot = per_bucket.setdefault(b, {"t": t, "s": {}})
            if t >= slot["t"]:
                slot["t"] = t
                slot["s"].update(rec.get("s") or {})
            else:
                for key, enc in (rec.get("s") or {}).items():
                    slot["s"].setdefault(key, enc)
        # a series may predate this file: pull meta from memory so a
        # sealed block always replays standalone
        for b in per_bucket.values():
            for key in b["s"]:
                if key not in meta and key in self._series:
                    meta[key] = self._meta_locked(self._series[key])
        return meta, [(b, per_bucket[b]) for b in sorted(per_bucket)]

    def _write_block_locked(self, fname: str, meta: dict,
                            buckets) -> int:
        tmp = os.path.join(self.dir, fname + ".tmp")
        final = os.path.join(self.dir, fname)
        buf = bytearray()
        first = True
        for _, slot in buckets:
            rec = {"t": slot["t"], "s": slot["s"]}
            if first and meta:
                rec["m"] = meta
                first = False
            buf += _encode_record(rec)
        with open(tmp, "wb") as f:
            f.write(bytes(buf))
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, final)
        self._block_sizes[fname] = len(buf)
        return len(buf)

    def _seal_locked(self):
        active = os.path.join(self.dir, "active.tsb")
        try:
            with open(active, "rb") as f:
                blob = f.read()
        except OSError:
            return
        payloads = [p for p, _ in committed_records(blob)]
        if payloads:
            meta, buckets = self._downsample_records(payloads, 10.0)
            self._write_block_locked(f"block-{self._seq:06d}.tsb",
                                     meta, buckets)
            self._seq += 1
            self.counts["sealed"] += 1
            _SEALED.inc()
        os.close(self._fd)
        self._fd = os.open(active,
                           os.O_WRONLY | os.O_CREAT | os.O_TRUNC
                           | os.O_APPEND, 0o644)
        self._active_bytes = 0
        self._meta_written.clear()

    def _disk_bytes_locked(self) -> int:
        return self._active_bytes + sum(self._block_sizes.values())

    def _enforce_retention_locked(self):
        # degrade before deleting: oldest raw block -> 5m compaction;
        # an already-compacted oldest block is dropped outright
        while self._disk_bytes_locked() > self.retention_bytes:
            blocks = self._blocks_locked()
            if not blocks:
                return
            seq, compacted, fn = blocks[0]
            path = os.path.join(self.dir, fn)
            if not compacted:
                try:
                    with open(path, "rb") as f:
                        blob = f.read()
                except OSError:
                    blob = b""
                payloads = [p for p, _ in committed_records(blob)]
                meta, buckets = self._downsample_records(payloads,
                                                         300.0)
                self._write_block_locked(f"block-{seq:06d}c.tsb",
                                         meta, buckets)
                self.counts["compacted"] += 1
                _COMPACTED.inc()
            try:
                os.unlink(path)
            except OSError:
                pass
            self._block_sizes.pop(fn, None)
            if compacted:
                self.counts["deleted"] += 1
                _DELETED.inc()

    def _publish_gauges_locked(self):
        _SERIES.set(len(self._series))
        if self.dir:
            _DISK.set(self._disk_bytes_locked())

    # -- queries -------------------------------------------------------
    def _match_locked(self, name: str,
                      labels: dict | None) -> list[_Series]:
        out = []
        for s in self._series.values():
            if s.name != name:
                continue
            ok = True
            for k, want in (labels or {}).items():
                have = s.labels.get(k)
                if isinstance(want, (list, tuple, set, frozenset)):
                    ok = have in {str(w) for w in want}
                else:
                    ok = have == str(want)
                if not ok:
                    break
            if ok:
                out.append(s)
        return out

    def series(self, name: str | None = None) -> list[dict]:
        with self._lock:
            return [{"key": s.key, "name": s.name,
                     "labels": dict(s.labels), "kind": s.kind,
                     "last_t": s.last_t}
                    for s in self._series.values()
                    if name is None or s.name == name
                    or s.name.startswith(name)]

    def range(self, name: str, labels: dict | None = None,
              start: float | None = None,
              end: float | None = None) -> list[dict]:
        """Per matching series: time-ordered points. Histogram points
        surface as their sample count (sparkline-friendly); use
        ``quantile()`` for the distribution itself."""
        lo = -math.inf if start is None else float(start)
        hi = math.inf if end is None else float(end)
        with self._lock:
            out = []
            for s in self._match_locked(name, labels):
                pts = [(t, v[2] if isinstance(v, tuple) else v)
                       for t, v in s.points(lo, hi)]
                out.append({"key": s.key, "labels": dict(s.labels),
                            "kind": s.kind, "points": pts})
            return out

    def latest(self, name: str, labels: dict | None = None) -> float:
        """Sum of each matching series' latest value."""
        with self._lock:
            tot = 0.0
            for s in self._match_locked(name, labels):
                pts = s.points(-math.inf, math.inf)
                if pts:
                    v = pts[-1][1]
                    tot += v[2] if isinstance(v, tuple) else v
            return tot

    def latest_by(self, name: str, group_by,
                  labels: dict | None = None) -> dict:
        """Latest values summed per distinct group-label tuple."""
        group_by = list(group_by)
        with self._lock:
            out: dict[tuple, float] = {}
            for s in self._match_locked(name, labels):
                pts = s.points(-math.inf, math.inf)
                if not pts:
                    continue
                v = pts[-1][1]
                v = v[2] if isinstance(v, tuple) else v
                g = tuple(s.labels.get(k, "") for k in group_by)
                out[g] = out.get(g, 0.0) + v
            return out

    def _series_delta_locked(self, s: _Series, start: float,
                             end: float):
        """Window delta for one series. The value at the window start
        is the last sample at or before it; a series born inside the
        window counts from zero (its counter started there)."""
        pts = s.points(-math.inf, end)
        if not pts:
            return None
        v_end = pts[-1][1]
        v_start = s.value_at(start)
        if v_start is None:
            if isinstance(v_end, tuple):
                v_start = (tuple(0.0 for _ in v_end[0]), 0.0, 0.0)
            else:
                v_start = 0.0
        if isinstance(v_end, tuple):
            cum = tuple(max(0.0, a - b) for a, b in
                        zip(v_end[0], v_start[0])) \
                if len(v_end[0]) == len(v_start[0]) else v_end[0]
            return (cum, max(0.0, v_end[1] - v_start[1]),
                    max(0.0, v_end[2] - v_start[2]))
        return max(0.0, v_end - v_start)

    def delta(self, name: str, window: float,
              labels: dict | None = None,
              at: float | None = None) -> float:
        """Summed counter increase over the trailing window."""
        end = float(at) if at is not None else self._default_at(name)
        start = end - float(window)
        with self._lock:
            tot = 0.0
            for s in self._match_locked(name, labels):
                d = self._series_delta_locked(s, start, end)
                if d is None:
                    continue
                tot += d[2] if isinstance(d, tuple) else d
            return tot

    def delta_by(self, name: str, window: float, group_by,
                 labels: dict | None = None,
                 at: float | None = None) -> dict:
        """Window deltas summed per distinct group-label tuple (the
        per-tenant burn-rate feed)."""
        end = float(at) if at is not None else self._default_at(name)
        start = end - float(window)
        group_by = list(group_by)
        with self._lock:
            out: dict[tuple, float] = {}
            for s in self._match_locked(name, labels):
                d = self._series_delta_locked(s, start, end)
                if d is None:
                    continue
                v = d[2] if isinstance(d, tuple) else d
                g = tuple(s.labels.get(k, "") for k in group_by)
                out[g] = out.get(g, 0.0) + v
            return out

    def rate(self, name: str, window: float,
             labels: dict | None = None,
             at: float | None = None) -> float:
        """Per-second counter rate over the trailing window."""
        return self.delta(name, window, labels, at) \
            / max(1e-9, float(window))

    def quantile(self, name: str, q: float, window: float,
                 labels: dict | None = None,
                 at: float | None = None) -> float | None:
        """Histogram quantile over the trailing window: elementwise
        bucket-count delta across matching series, then the nearest-
        bucket estimate."""
        end = float(at) if at is not None else self._default_at(name)
        start = end - float(window)
        with self._lock:
            buckets = None
            cum = None
            for s in self._match_locked(name, labels):
                if s.kind != "histogram" or not s.buckets:
                    continue
                d = self._series_delta_locked(s, start, end)
                if not isinstance(d, tuple):
                    continue
                if buckets is None:
                    buckets = s.buckets
                    cum = list(d[0])
                elif len(d[0]) == len(cum):
                    cum = [a + b for a, b in zip(cum, d[0])]
            if cum is None:
                return None
            return hist_quantile(buckets, cum, float(q))

    def _default_at(self, name: str) -> float:
        """Default query anchor: the newest sample time of the metric
        (wall clocks of pushers, not the collector's own) — so replay
        and tests are deterministic. Takes the lock itself; callers
        invoke it before entering theirs."""
        with self._lock:
            return max((s.last_t or 0.0
                        for s in self._series.values()
                        if s.name == name), default=0.0)

    # -- admin ---------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {"series": len(self._series),
                    "dir": self.dir,
                    "bytes_on_disk": self._disk_bytes_locked()
                    if self.dir else 0,
                    "active_bytes": self._active_bytes,
                    "blocks": sorted(self._block_sizes),
                    "retention_bytes": self.retention_bytes,
                    "block_bytes": self.block_bytes,
                    "counts": dict(self.counts)}

    def close(self):
        with self._lock:
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
