"""Per-tenant usage metering for the serving plane.

The scheduler has carried tenant/priority tags since the quota work
(PR 6), but nothing ever aggregated them — fleet dashboards showed
totals, so one tenant's burn hid inside the aggregate and there was
nothing to bill or quota against. This module is the accounting
layer: Engine/Scheduler/Router hooks land every request's resource
footprint in per-(tenant, tier) registry counters, which ride the
normal telemetry push into the collector TSDB as per-tenant series —
feeding the `tenant-burn-rate` alert rule, the `top tenants` pane,
and the `usage_report` wire verb.

What is metered per (tenant, tier):

  * tokens in (prompt) and out (generated);
  * queue seconds (submit -> admission) — what the tenant waited;
  * KV page-seconds (pages held × slot residency) — the HBM a
    tenant's requests occupied, the honest cost of long contexts;
  * request outcomes (completed / rejected / quota / shed / expired /
    preempted / cancelled / failed — a bounded set);
  * a FLOPs estimate from the perf-plane cost registry (PR 14): the
    prefill bucket's compiled cost plus a per-token share of the
    decode bucket.

Label cardinality is the TSDB's survival constraint (the
``metric-label-cardinality`` analysis rule polices it): tenant label
values pass through bounded interning — the first
``PADDLE_TPU_TENANT_CAP`` distinct tenants keep their names, the
rest collapse into the ``~other`` overflow bucket (counted, never
dropped). Tier labels clamp to a single digit.

Process-locality: ``METER`` accounts the traffic of *this* process
(engine/router); the fleet-wide view is assembled collector-side
from TSDB series (``usage_report(tsdb)``), summing across hosts.
"""
from __future__ import annotations

import os
import threading

from . import registry as _obs

__all__ = ["UsageMeter", "METER", "OVERFLOW_TENANT", "OUTCOMES",
           "usage_report"]

OVERFLOW_TENANT = "~other"

# the bounded outcome vocabulary; anything unknown lands on "other"
OUTCOMES = ("completed", "rejected", "quota", "shed", "expired",
            "preempted", "cancelled", "failed", "other")

_TOKENS_IN = _obs.counter(
    "paddle_tpu_tenant_tokens_in_total",
    "prompt tokens submitted, per tenant and tier",
    ["tenant", "tier"])
_TOKENS_OUT = _obs.counter(
    "paddle_tpu_tenant_tokens_out_total",
    "tokens generated, per tenant and tier", ["tenant", "tier"])
_QUEUE_S = _obs.counter(
    "paddle_tpu_tenant_queue_seconds_total",
    "seconds requests waited for admission, per tenant and tier",
    ["tenant", "tier"])
_KV_PAGE_S = _obs.counter(
    "paddle_tpu_tenant_kv_page_seconds_total",
    "KV page-seconds held in slots, per tenant and tier",
    ["tenant", "tier"])
_FLOPS = _obs.counter(
    "paddle_tpu_tenant_flops_total",
    "estimated FLOPs spent (compiled-cost registry), per tenant and "
    "tier", ["tenant", "tier"])
_REQS = _obs.counter(
    "paddle_tpu_tenant_requests_total",
    "request outcomes, per tenant, tier and outcome",
    ["tenant", "tier", "outcome"])
_ROUTER_REQS = _obs.counter(
    "paddle_tpu_tenant_router_requests_total",
    "router relays by tenant and outcome", ["tenant", "outcome"])
_OVERFLOWED = _obs.counter(
    "paddle_tpu_tenant_overflow_total",
    "submissions whose tenant collapsed into the overflow bucket")

# the scheduler's finer-grained finish reasons -> the bounded vocab
_OUTCOME_MAP = {"done": "completed", "expired_in_queue": "expired",
                "deadline": "preempted", "queue_full": "rejected",
                "draining": "rejected", "error": "failed"}


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _tier(priority) -> str:
    try:
        p = int(priority)
    except (TypeError, ValueError):
        return "?"
    return str(p) if 0 <= p <= 8 else ("9+" if p > 8 else "?")


def normalize_outcome(raw) -> str:
    out = _OUTCOME_MAP.get(str(raw), str(raw))
    return out if out in OUTCOMES else "other"


class UsageMeter:
    """See module docstring. Cheap enough for the submit path: one
    set lookup + a few counter incs per event."""

    def __init__(self, cap: int | None = None):
        if cap is None:
            cap = _env_int("PADDLE_TPU_TENANT_CAP", 64)
        self.cap = max(1, int(cap))
        self._lock = threading.Lock()
        self._tenants: set[str] = set()
        self._overflowed: set[str] = set()

    def intern(self, tenant) -> str:
        """The label value for a tenant: its own name while under the
        cap, the overflow bucket after — bounded cardinality no matter
        what the frontend sends."""
        t = str(tenant or "default")
        with self._lock:
            if t in self._tenants:
                return t
            if len(self._tenants) < self.cap:
                self._tenants.add(t)
                return t
            if t not in self._overflowed:
                self._overflowed.add(t)
                _OVERFLOWED.inc()
        return OVERFLOW_TENANT

    # -- hooks ---------------------------------------------------------
    def note_submitted(self, tenant, priority, tokens_in: int):
        """Engine.submit: prompt tokens offered (counted even when the
        scheduler later rejects — offered load is what billing sees)."""
        _TOKENS_IN.labels(tenant=self.intern(tenant),
                          tier=_tier(priority)).inc(max(0, int(tokens_in)))

    def note_outcome(self, tenant, priority, outcome,
                     tokens_out: int = 0, queue_s: float = 0.0,
                     kv_page_s: float = 0.0):
        """Scheduler finish/reject: one terminal outcome per request
        plus the resources it consumed getting there."""
        t = self.intern(tenant)
        tier = _tier(priority)
        _REQS.labels(tenant=t, tier=tier,
                     outcome=normalize_outcome(outcome)).inc()
        if tokens_out > 0:
            _TOKENS_OUT.labels(tenant=t, tier=tier).inc(int(tokens_out))
        if queue_s > 0:
            _QUEUE_S.labels(tenant=t, tier=tier).inc(float(queue_s))
        if kv_page_s > 0:
            _KV_PAGE_S.labels(tenant=t, tier=tier).inc(float(kv_page_s))

    def note_flops(self, tenant, priority, flops: float):
        if flops and flops > 0:
            _FLOPS.labels(tenant=self.intern(tenant),
                          tier=_tier(priority)).inc(float(flops))

    def note_routed(self, tenant, outcome):
        _ROUTER_REQS.labels(tenant=self.intern(tenant),
                            outcome=normalize_outcome(outcome)).inc()

    # -- local report ----------------------------------------------------
    def report(self) -> dict:
        """This process's usage, per (tenant, tier), read back from the
        registry children (one source of truth — parity with what the
        TSDB sees)."""
        out: dict[str, dict] = {}

        def add(metric, field):
            names = metric.labelnames
            for values, child in metric._series():
                labels = dict(zip(names, values))
                v = float(child.value)
                key = f"{labels.get('tenant', '')}/{labels.get('tier', '')}"
                slot = out.setdefault(key, {"tenant": labels.get(
                    "tenant", ""), "tier": labels.get("tier", "")})
                if field == "outcomes":
                    slot.setdefault("outcomes", {})[
                        labels.get("outcome", "?")] = v
                else:
                    slot[field] = slot.get(field, 0.0) + v

        add(_TOKENS_IN, "tokens_in")
        add(_TOKENS_OUT, "tokens_out")
        add(_QUEUE_S, "queue_seconds")
        add(_KV_PAGE_S, "kv_page_seconds")
        add(_FLOPS, "flops")
        add(_REQS, "outcomes")
        return {"tenants": out, "interned": len(self._tenants),
                "cap": self.cap}


# one process-wide meter: engine/scheduler/router hooks share it so a
# process's tenants intern once
METER = UsageMeter()


def usage_report(tsdb=None, window: float | None = None) -> dict:
    """The ``usage_report`` verb body. With a TSDB (collector-side):
    fleet-wide usage summed across processes from the tenant series —
    latest totals plus, when ``window`` is given, trailing-window
    deltas. Without one: this process's local meter."""
    if tsdb is None:
        return {"scope": "process", **METER.report()}
    gb = ("tenant", "tier")
    names = {"tokens_in": "paddle_tpu_tenant_tokens_in_total",
             "tokens_out": "paddle_tpu_tenant_tokens_out_total",
             "queue_seconds": "paddle_tpu_tenant_queue_seconds_total",
             "kv_page_seconds":
                 "paddle_tpu_tenant_kv_page_seconds_total",
             "flops": "paddle_tpu_tenant_flops_total"}
    out: dict[str, dict] = {}

    def slot(g):
        key = "/".join(g)
        return out.setdefault(key, {"tenant": g[0], "tier": g[1]})

    for field, name in names.items():
        for g, v in tsdb.latest_by(name, gb).items():
            slot(g)[field] = v
        if window:
            for g, v in tsdb.delta_by(name, window, gb).items():
                slot(g)[f"{field}_window"] = v
    for g, v in tsdb.latest_by("paddle_tpu_tenant_requests_total",
                               ("tenant", "tier", "outcome")).items():
        slot(g[:2]).setdefault("outcomes", {})[g[2]] = v
    rep = {"scope": "fleet", "tenants": out}
    if window:
        rep["window_s"] = float(window)
    return rep
