"""Progress-token stall watchdog.

The launcher-side elastic heartbeat (``distributed/elastic.py``) only
sees a rank that stopped touching a file; it cannot tell *which tier
inside the process* wedged, and a process whose heartbeat thread is
alive but whose decode loop is stuck looks healthy from outside. This
module is the in-process half: each tier registers a **progress token**
— a counter it must keep advancing while it has work — and the watchdog
fires when a token goes `deadline` seconds without progress while not
idle:

  * serving engine — decode ``steps`` counter; idle = scheduler idle
    (an empty engine is never a stall);
  * PS server — completed dispatches; idle = no non-barrier op in
    flight (barrier/DGC verbs legitimately block on straggler trainers
    and never arm the watchdog);
  * launcher heartbeats — ``watch_heartbeats`` wraps
    ``elastic.stale_ranks`` as a healthy-predicate token (mtimes fresh
    = progress).

On fire the watchdog raises ``paddle_tpu_watchdog_*`` metrics, records
a ``watchdog`` flight event, writes a postmortem bundle
(``observability.debug``, when ``PADDLE_TPU_DEBUG_DIR`` or the
constructor's ``debug_dir`` names a directory), invokes the token's
``on_stall`` callback, and — with ``PADDLE_TPU_WATCHDOG_SIGTERM=1`` or
``sigterm=True`` — re-raises SIGTERM at its own process so the
``launch.py`` respawn semantics (PR 1) take over, with the bundle
already on disk.

A token fires ONCE per stall episode; any later progress clears the
episode so a recovered tier can stall (and dump) again. Probes that
return ``None`` unregister themselves — registrants hold only weakrefs
to their owners, so a dead engine's token evaporates instead of
pinning it.

The background poll thread starts only on ``start()`` (or when
``PADDLE_TPU_WATCHDOG`` is set at import, see ``observability``);
``check_once()`` is the deterministic entry point tests drive
directly.

Knobs: ``PADDLE_TPU_WATCHDOG`` (truthy = auto-start),
``PADDLE_TPU_WATCHDOG_INTERVAL`` (poll seconds, default 1),
``PADDLE_TPU_WATCHDOG_DEADLINE`` (default token deadline seconds,
default 300, read at registration time), ``PADDLE_TPU_WATCHDOG_SIGTERM``.
"""
from __future__ import annotations

import os
import signal
import threading
import time

from . import flight as _flight
from . import registry as _obs

__all__ = ["Watchdog", "WATCHDOG", "watch", "watch_healthy",
           "watch_heartbeats", "unwatch", "check_once",
           "default_deadline"]

_CHECKS = _obs.counter(
    "paddle_tpu_watchdog_checks_total",
    "watchdog poll passes over the registered progress tokens")
_STALLS = _obs.counter(
    "paddle_tpu_watchdog_stalls_total",
    "no-progress deadline expiries (one per stall episode), by token",
    ["token"])
_STALLED = _obs.gauge(
    "paddle_tpu_watchdog_stalled",
    "1 while a token is inside a stall episode, by token", ["token"])
_AGE = _obs.gauge(
    "paddle_tpu_watchdog_progress_age_seconds",
    "seconds since each token last made progress", ["token"])


def default_deadline() -> float:
    """Token deadline when the registrant does not pass one (env
    PADDLE_TPU_WATCHDOG_DEADLINE, read at call time so tests/jobs can
    retune without reimporting)."""
    try:
        return float(os.environ.get(
            "PADDLE_TPU_WATCHDOG_DEADLINE", "300") or 300)
    except ValueError:
        return 300.0


class _Token:
    __slots__ = ("name", "probe", "deadline", "idle", "on_stall",
                 "healthy", "last_value", "last_progress", "fired")

    def __init__(self, name, probe, deadline, idle, on_stall, healthy,
                 now):
        self.name = name
        self.probe = probe
        self.deadline = float(deadline)
        self.idle = idle
        self.on_stall = on_stall
        self.healthy = healthy     # True: probe is a health predicate
        self.last_value = None     # counter probes: last observed value
        self.last_progress = now
        self.fired = False


class Watchdog:
    """Registry of progress tokens + the poll loop; see module doc."""

    def __init__(self, interval: float | None = None,
                 debug_dir: str | None = None,
                 sigterm: bool | None = None, now=time.monotonic):
        if interval is None:
            interval = float(os.environ.get(
                "PADDLE_TPU_WATCHDOG_INTERVAL", "1.0") or 1.0)
        if sigterm is None:
            sigterm = os.environ.get(
                "PADDLE_TPU_WATCHDOG_SIGTERM", "") not in ("", "0")
        self.interval = interval
        self.debug_dir = debug_dir   # None -> PADDLE_TPU_DEBUG_DIR
        self.sigterm = bool(sigterm)
        self._now = now
        self._tokens: dict[str, _Token] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- registration ---------------------------------------------------
    def watch(self, name: str, probe, deadline: float | None = None,
              idle=None, on_stall=None, healthy: bool = False) -> str:
        """Register a progress token. ``probe`` returns the counter the
        tier must advance (or, with ``healthy=True``, a truthy health
        flag); ``None`` from the probe unregisters the token (dead
        weakref). ``idle`` (optional) returns True while the tier has
        no work — an idle tier never stalls and its deadline restarts
        when work appears."""
        tok = _Token(name, probe, deadline if deadline is not None
                     else default_deadline(), idle, on_stall, healthy,
                     self._now())
        with self._lock:
            self._tokens[name] = tok
        return name

    def watch_healthy(self, name: str, healthy_fn,
                      deadline: float | None = None,
                      on_stall=None) -> str:
        """Predicate token: progress = ``healthy_fn()`` truthy; fires
        after `deadline` seconds of continuous unhealth."""
        return self.watch(name, healthy_fn, deadline=deadline,
                          on_stall=on_stall, healthy=True)

    def watch_heartbeats(self, dir_: str, timeout: float,
                         expected: int, grace: float = 0.0,
                         deadline: float | None = None,
                         name: str = "elastic.heartbeats",
                         on_stall=None) -> str:
        """Arm the watchdog on the launcher-side heartbeat files: the
        token is healthy while ``elastic.stale_ranks`` reports no hung
        rank, so stale mtimes become an in-process stall (bundle +
        metrics) instead of only a launcher kill."""
        def healthy():
            from ..distributed.elastic import stale_ranks
            return not stale_ranks(dir_, timeout, expected, grace=grace)

        return self.watch_healthy(
            name, healthy, deadline=deadline if deadline is not None
            else timeout, on_stall=on_stall)

    def unwatch(self, name: str) -> bool:
        with self._lock:
            tok = self._tokens.pop(name, None)
        for m in (_STALLS, _STALLED, _AGE):
            m.remove_matching(token=name)
        return tok is not None

    def tokens(self) -> list[str]:
        with self._lock:
            return sorted(self._tokens)

    def stalled(self) -> list[str]:
        with self._lock:
            return sorted(n for n, t in self._tokens.items() if t.fired)

    # -- the check ------------------------------------------------------
    def check_once(self, now: float | None = None) -> list[str]:
        """One poll pass over every token; returns the tokens that
        FIRED on this pass (entered a stall episode)."""
        t = self._now() if now is None else now
        _CHECKS.inc()
        with self._lock:
            toks = list(self._tokens.values())
        fired = []
        for tok in toks:
            try:
                if tok.idle is not None and tok.idle():
                    # no work: reset the clock AND the baseline so the
                    # first post-idle probe re-anchors progress
                    tok.last_progress = t
                    tok.last_value = None
                    if tok.fired:
                        tok.fired = False
                    _STALLED.labels(token=tok.name).set(0)
                    _AGE.labels(token=tok.name).set(0)
                    continue
                v = tok.probe()
            except Exception:
                continue        # transient probe failure: skip the pass
            if v is None:
                self.unwatch(tok.name)   # owner died (weakref probe)
                continue
            if tok.healthy:
                progressed = bool(v)
            else:
                progressed = tok.last_value is None \
                    or v != tok.last_value
                tok.last_value = v
            if progressed:
                tok.last_progress = t
                if tok.fired:
                    tok.fired = False
                _STALLED.labels(token=tok.name).set(0)
            age = t - tok.last_progress
            _AGE.labels(token=tok.name).set(age)
            if age > tok.deadline and not tok.fired:
                tok.fired = True
                self._fire(tok, age)
                fired.append(tok.name)
        return fired

    def _fire(self, tok: _Token, age: float):
        _STALLS.labels(token=tok.name).inc()
        _STALLED.labels(token=tok.name).set(1)
        _flight.record("watchdog", "stall", token=tok.name,
                       age_s=round(age, 3), deadline_s=tok.deadline)
        from . import debug as _debug
        if self.sigterm:
            # escalation is armed BEFORE the bundle write: the stall
            # may itself be a hung filesystem, and the dump would then
            # wedge this poll thread too — the rank must still die
            # within the grace period so launch.py's respawn semantics
            # (its SIGTERM forward/teardown path, PR 1) take over with
            # whatever evidence made it to disk. The hard exit also
            # covers a main thread wedged inside a blocking C call,
            # where a PYTHON SIGTERM handler (the observability dump
            # hook runs only on the main thread) is queued forever.
            _debug.arm_hard_exit(name="watchdog-sigterm-escalate")
        path = _debug.try_write_bundle(f"watchdog:{tok.name}",
                                       self.debug_dir)
        # stall episodes surface on the fleet dashboard (with their
        # bundle path) when a telemetry agent is armed; no-op otherwise
        from . import agent as _agent
        # attr is `name`, not `token`: the agent's credential redaction
        # blanks TOKEN-ish keys, and a progress-token name is the one
        # thing the dashboard must show
        _agent.publish_event("watchdog_stall", name=tok.name,
                             age_s=round(age, 3),
                             deadline_s=tok.deadline, bundle=path)
        if tok.on_stall is not None:
            try:
                tok.on_stall(tok.name, age, path)
            except Exception:
                pass
        if self.sigterm:
            os.kill(os.getpid(), signal.SIGTERM)

    # -- background thread ---------------------------------------------
    def start(self, interval: float | None = None) -> "Watchdog":
        if self._thread is not None:
            return self
        if interval is not None:
            self.interval = interval
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.check_once()
                except Exception:
                    pass    # the watchdog itself must never die

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="paddle-tpu-watchdog")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# process-wide watchdog + module-level shortcuts (tiers register here)
WATCHDOG = Watchdog()
watch = WATCHDOG.watch
watch_healthy = WATCHDOG.watch_healthy
watch_heartbeats = WATCHDOG.watch_heartbeats
unwatch = WATCHDOG.unwatch
check_once = WATCHDOG.check_once
