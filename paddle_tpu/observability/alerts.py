"""Declarative alert rules evaluated over the collector TSDB.

The time-series plane (``observability.timeseries``) gives the fleet
history; this module makes it proactive. An ``AlertManager`` hosted
next to the collector evaluates a rule set on a cadence and walks
each alert instance through the pending → firing → resolved
lifecycle, so an SLO burn surfaces *before* a human runs ``top``.

Rule kinds (docs/OBSERVABILITY.md has the full syntax + recipe):

  * ``threshold`` — a metric's latest value or trailing-window rate
    compared against a bound (queue depth too deep, drop counter
    rising);
  * ``absence`` — liveness: a process the collector knows stopped
    reporting for longer than ``max_age_s``. Uses the same
    ``last_seen`` signal fleet-state GC retires processes by — size
    the GC window (``PADDLE_TPU_TELEMETRY_RETIRE``) longer than
    ``max_age_s + for_s`` or the alert never gets to fire;
  * ``burn_rate`` — the SRE-workbook multi-window, multi-burn-rate
    SLO rule: error ratio = bad/(bad+good) over a short AND a long
    trailing window, each divided by the error budget; fires only
    when BOTH windows burn faster than ``factor``× budget (the short
    window gives fast detection, the long window keeps a transient
    blip from paging). ``group_by`` splits the evaluation per label
    value — per-tenant rules fire for the tenant that burns, not the
    fleet aggregate one loud tenant hides in.

Lifecycle: a true condition creates a *pending* instance; still true
``for_s`` later it transitions to *firing* (fleet event + flight
event, and optionally a PR-5 debug bundle — symptom to postmortem
artifact with no human in the loop). A firing instance must stay
clear for ``resolve_s`` before it *resolves* (flap damping); a
pending one that clears simply drops. One event per transition per
instance — re-notification only after a genuine re-fire.

Rules load from JSON (``PADDLE_TPU_ALERTS_RULES`` or
``AlertRule.from_dict``); ``default_rules()`` ships the fleet SLO
burn-rate, agent-liveness, and per-tenant burn-rate rules.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from . import debug as _debug
from . import registry as _obs
from .agent import publish_event as _publish_event
from .flight import RECORDER as _flight

__all__ = ["AlertRule", "AlertManager", "default_rules", "load_rules"]

_EVALS = _obs.counter(
    "paddle_tpu_alerts_evaluations_total",
    "alert rule-set evaluation passes")
_TRANSITIONS = _obs.counter(
    "paddle_tpu_alerts_transitions_total",
    "alert lifecycle transitions", ["state"])
_FIRING = _obs.gauge(
    "paddle_tpu_alerts_firing",
    "alert instances currently firing")

_OPS = {">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
        "<": lambda a, b: a < b, "<=": lambda a, b: a <= b}


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class AlertRule:
    """One declarative rule; see module docstring for kinds."""

    def __init__(self, name: str, kind: str, *,
                 metric: str | None = None,
                 labels: dict | None = None,
                 op: str = ">", value: float = 0.0,
                 mode: str = "latest", window: float = 60.0,
                 max_age_s: float = 30.0,
                 good_metric: str | None = None,
                 bad_metric: str | None = None,
                 good_labels: dict | None = None,
                 bad_labels: dict | None = None,
                 budget: float = 0.01, factor: float = 14.4,
                 short_window: float = 300.0,
                 long_window: float = 3600.0,
                 min_bad: float = 1.0,
                 group_by=None,
                 for_s: float = 0.0, resolve_s: float = 0.0,
                 severity: str = "warning",
                 capture_bundle: bool = False):
        if kind not in ("threshold", "absence", "burn_rate"):
            raise ValueError(f"unknown alert kind {kind!r}")
        if kind == "threshold" and not metric:
            raise ValueError(f"rule {name!r}: threshold needs metric")
        if kind == "burn_rate" and not bad_metric:
            raise ValueError(f"rule {name!r}: burn_rate needs "
                             f"bad_metric")
        if op not in _OPS:
            raise ValueError(f"rule {name!r}: unknown op {op!r}")
        if mode not in ("latest", "rate"):
            raise ValueError(f"rule {name!r}: unknown mode {mode!r}")
        self.name = str(name)
        self.kind = kind
        self.metric = metric
        self.labels = dict(labels or {})
        self.op = op
        self.value = float(value)
        self.mode = mode
        self.window = float(window)
        self.max_age_s = float(max_age_s)
        self.good_metric = good_metric
        self.bad_metric = bad_metric
        self.good_labels = dict(good_labels or {})
        self.bad_labels = dict(bad_labels or {})
        self.budget = max(1e-9, float(budget))
        self.factor = float(factor)
        self.short_window = float(short_window)
        self.long_window = float(long_window)
        self.min_bad = float(min_bad)
        self.group_by = list(group_by or [])
        self.for_s = max(0.0, float(for_s))
        self.resolve_s = max(0.0, float(resolve_s))
        self.severity = str(severity)
        self.capture_bundle = bool(capture_bundle)

    @classmethod
    def from_dict(cls, d: dict) -> "AlertRule":
        d = dict(d)
        return cls(d.pop("name"), d.pop("kind"), **d)

    def to_dict(self) -> dict:
        out = {"name": self.name, "kind": self.kind,
               "severity": self.severity, "for_s": self.for_s}
        if self.kind == "threshold":
            out.update(metric=self.metric, op=self.op,
                       value=self.value, mode=self.mode,
                       window=self.window)
        elif self.kind == "absence":
            out.update(max_age_s=self.max_age_s)
        else:
            out.update(bad_metric=self.bad_metric,
                       good_metric=self.good_metric,
                       budget=self.budget, factor=self.factor,
                       short_window=self.short_window,
                       long_window=self.long_window,
                       group_by=self.group_by)
        return out

    # -- evaluation: {instance_key: (labels, measured_value)} ----------
    def evaluate(self, tsdb, fleet: dict | None) -> dict:
        if self.kind == "absence":
            return self._eval_absence(fleet)
        if tsdb is None:
            return {}
        if self.kind == "threshold":
            return self._eval_threshold(tsdb)
        return self._eval_burn(tsdb)

    def _eval_threshold(self, tsdb) -> dict:
        if self.group_by:
            if self.mode == "rate":
                vals = {g: d / max(1e-9, self.window)
                        for g, d in tsdb.delta_by(
                            self.metric, self.window, self.group_by,
                            self.labels).items()}
            else:
                vals = tsdb.latest_by(self.metric, self.group_by,
                                      self.labels)
            out = {}
            for g, v in vals.items():
                if _OPS[self.op](v, self.value):
                    labels = dict(zip(self.group_by, g))
                    out["|".join(g)] = (labels, v)
            return out
        v = tsdb.rate(self.metric, self.window, self.labels) \
            if self.mode == "rate" \
            else tsdb.latest(self.metric, self.labels)
        return {"": ({}, v)} if _OPS[self.op](v, self.value) else {}

    def _eval_absence(self, fleet: dict | None) -> dict:
        out = {}
        for p in (fleet or {}).get("procs") or ():
            age = p.get("age_s")
            if age is not None and age > self.max_age_s:
                labels = {"host": str(p.get("host")),
                          "pid": str(p.get("pid")),
                          "role": str(p.get("role"))}
                key = f"{labels['host']}:{labels['pid']}"
                out[key] = (labels, float(age))
        return out

    def _burn(self, tsdb, window: float) -> dict:
        """{group: burn multiple} over one trailing window."""
        gb = self.group_by or []
        if gb:
            bad = tsdb.delta_by(self.bad_metric, window, gb,
                                self.bad_labels)
            good = tsdb.delta_by(self.good_metric, window, gb,
                                 self.good_labels) \
                if self.good_metric else {}
        else:
            bad = {(): tsdb.delta(self.bad_metric, window,
                                  self.bad_labels)}
            good = {(): tsdb.delta(self.good_metric, window,
                                   self.good_labels)
                    if self.good_metric else 0.0}
        out = {}
        for g, b in bad.items():
            if b < self.min_bad:
                continue
            total = b + max(0.0, good.get(g, 0.0))
            ratio = b / total if total > 0 else 0.0
            out[g] = ratio / self.budget
        return out

    def _eval_burn(self, tsdb) -> dict:
        short = self._burn(tsdb, self.short_window)
        if not short:
            return {}
        long_ = self._burn(tsdb, self.long_window)
        out = {}
        for g, s_burn in short.items():
            l_burn = long_.get(g, 0.0)
            if s_burn >= self.factor and l_burn >= self.factor:
                labels = dict(zip(self.group_by, g))
                out["|".join(g)] = (labels, s_burn)
        return out


class _Instance:
    __slots__ = ("rule", "key", "labels", "state", "since",
                 "firing_since", "clear_since", "value", "bundle")

    def __init__(self, rule: AlertRule, key: str, labels: dict,
                 now: float):
        self.rule = rule
        self.key = key
        self.labels = labels
        self.state = "pending"
        self.since = now
        self.firing_since: float | None = None
        self.clear_since: float | None = None
        self.value = 0.0
        self.bundle: str | None = None

    def to_dict(self) -> dict:
        return {"rule": self.rule.name, "kind": self.rule.kind,
                "severity": self.rule.severity, "state": self.state,
                "labels": dict(self.labels), "since": self.since,
                "firing_since": self.firing_since,
                "value": self.value, "bundle": self.bundle}


class AlertManager:
    """Evaluates a rule set over a TSDB + fleet snapshot on a cadence.

    Never call ``evaluate`` while holding the collector lock: a
    firing rule may write a debug bundle (disk IO) and ``fleet_fn``
    itself takes that lock. The collector calls ``maybe_evaluate``
    after releasing its lock on each ingest; the standalone
    ``CollectorServer`` loop drives it between pushes too."""

    def __init__(self, tsdb=None, fleet_fn=None, rules=None,
                 eval_s: float | None = None, event_cb=None,
                 history_max: int = 128):
        if eval_s is None:
            eval_s = _env_float("PADDLE_TPU_ALERTS_EVAL", 5.0)
        if rules is None:
            rules = load_rules()
        self.tsdb = tsdb
        self.fleet_fn = fleet_fn
        self.rules = list(rules)
        self.eval_s = max(0.0, float(eval_s))
        # event_cb(dict): the hosting collector mirrors transitions
        # into its recent-events feed so `top` shows them even when no
        # local agent is armed
        self.event_cb = event_cb
        self._lock = threading.Lock()
        self._active: dict[tuple, _Instance] = {}
        self._history: deque = deque(maxlen=max(8, history_max))
        self._last_eval = 0.0
        self.counts = {"evaluations": 0, "pending": 0, "firing": 0,
                       "resolved": 0, "bundles": 0}

    # -- cadence -------------------------------------------------------
    def maybe_evaluate(self, now: float | None = None) -> bool:
        if self.eval_s <= 0:
            return False
        t = time.monotonic()
        with self._lock:
            if t - self._last_eval < self.eval_s:
                return False
            self._last_eval = t
        self.evaluate(now)
        return True

    # -- one pass ------------------------------------------------------
    def evaluate(self, now: float | None = None):
        now = time.time() if now is None else float(now)
        fleet = None
        if any(r.kind == "absence" for r in self.rules) \
                and self.fleet_fn is not None:
            fleet = self.fleet_fn()
        true_now: dict[tuple, tuple] = {}
        for rule in self.rules:
            try:
                hits = rule.evaluate(self.tsdb, fleet)
            except Exception:
                continue  # one bad rule must not kill the pass
            for key, (labels, value) in hits.items():
                true_now[(rule.name, key)] = (rule, labels, value)
        transitions = []
        with self._lock:
            self.counts["evaluations"] += 1
            for ikey, (rule, labels, value) in true_now.items():
                inst = self._active.get(ikey)
                if inst is None:
                    inst = self._active[ikey] = _Instance(
                        rule, ikey[1], labels, now)
                    self.counts["pending"] += 1
                    transitions.append(("pending", inst))
                inst.value = value
                inst.clear_since = None
                if inst.state == "pending" \
                        and now - inst.since >= rule.for_s:
                    inst.state = "firing"
                    inst.firing_since = now
                    self.counts["firing"] += 1
                    transitions.append(("firing", inst))
            for ikey, inst in list(self._active.items()):
                if ikey in true_now:
                    continue
                if inst.state == "pending":
                    del self._active[ikey]  # never fired: just drop
                    continue
                if inst.clear_since is None:
                    inst.clear_since = now
                if now - inst.clear_since >= inst.rule.resolve_s:
                    inst.state = "resolved"
                    self.counts["resolved"] += 1
                    transitions.append(("resolved", inst))
                    self._history.append(inst.to_dict())
                    del self._active[ikey]
            _FIRING.set(sum(1 for i in self._active.values()
                            if i.state == "firing"))
        for state, inst in transitions:
            self._notify(state, inst)

    def _notify(self, state: str, inst: _Instance):
        _TRANSITIONS.labels(state=state).inc()
        attrs = {"rule": inst.rule.name, "state": state,
                 "severity": inst.rule.severity,
                 "value": round(float(inst.value), 4),
                 **inst.labels}
        _flight.record("alerts", f"alert_{state}", **attrs)
        if state == "firing" and inst.rule.capture_bundle:
            # symptom -> postmortem artifact with no human in the
            # loop; best-effort, never blocks the pass on IO errors
            inst.bundle = _debug.try_write_bundle(
                f"alert:{inst.rule.name}")
            if inst.bundle:
                self.counts["bundles"] += 1
                attrs["bundle"] = inst.bundle
        if state != "pending":
            _publish_event(f"alert_{state}", **attrs)
        if self.event_cb is not None:
            try:
                self.event_cb({"kind": f"alert_{state}",
                               "attrs": attrs})
            except Exception:
                pass

    # -- reads ---------------------------------------------------------
    def active(self) -> list[dict]:
        with self._lock:
            return sorted((i.to_dict() for i in
                           self._active.values()),
                          key=lambda d: (d["rule"], d["labels"].get(
                              "tenant", ""), d["since"]))

    def state(self) -> dict:
        with self._lock:
            return {"active": [i.to_dict()
                               for i in self._active.values()],
                    "history": list(self._history),
                    "rules": [r.to_dict() for r in self.rules],
                    "eval_s": self.eval_s,
                    "counts": dict(self.counts)}


def default_rules() -> list[AlertRule]:
    """The shipped rule set: fleet SLO burn rate, agent liveness, and
    the per-tenant burn rate that keeps one tenant's pain visible
    under a healthy fleet aggregate."""
    return [
        AlertRule(
            "slo-burn-rate", "burn_rate",
            bad_metric="paddle_tpu_slo_deadline_missed_total",
            good_metric="paddle_tpu_slo_deadline_met_total",
            budget=0.01, factor=14.4,
            short_window=300.0, long_window=3600.0,
            for_s=15.0, resolve_s=60.0, severity="page",
            capture_bundle=True),
        AlertRule(
            "agent-absent", "absence", max_age_s=30.0,
            for_s=10.0, resolve_s=30.0, severity="warning"),
        AlertRule(
            "tenant-burn-rate", "burn_rate",
            bad_metric="paddle_tpu_tenant_requests_total",
            bad_labels={"outcome": ["rejected", "shed", "expired",
                                    "quota", "preempted"]},
            good_metric="paddle_tpu_tenant_requests_total",
            good_labels={"outcome": ["completed"]},
            group_by=["tenant"],
            budget=0.05, factor=6.0,
            short_window=300.0, long_window=1800.0,
            for_s=15.0, resolve_s=60.0, severity="warning"),
    ]


def load_rules(path: str | None = None) -> list[AlertRule]:
    """Rules from a JSON file (a list of rule dicts), else the
    defaults. ``PADDLE_TPU_ALERTS_RULES`` names the file for hosted
    collectors; a broken file falls back to the defaults rather than
    silently disabling alerting."""
    path = path or os.environ.get("PADDLE_TPU_ALERTS_RULES") or None
    if not path:
        return default_rules()
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
        return [AlertRule.from_dict(d) for d in raw]
    except (OSError, ValueError, KeyError, TypeError):
        return default_rules()
