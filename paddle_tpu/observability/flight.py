"""Flight recorder: bounded per-tier rings of structured events.

The registry holds the *numbers* and the tracer holds *timed spans*;
this module holds the **black box** — the last N discrete things each
tier did (request admitted, decode step ran, push journaled, snapshot
written, program compiled), cheap enough to leave on in production and
small enough to dump whole into a postmortem bundle
(``observability.debug``). When a process wedges or dies, the rings are
the evidence of what it was doing right before.

Design rules:

  * one bounded ``deque`` ring PER TIER (``serving``, ``rpc``, ``ps``,
    ``ckpt``, ``executor``, ``watchdog``) so a chatty tier (decode
    steps) can never evict another tier's sparse events (snapshots);
  * every event carries a monotonic timestamp, a wall-clock stamp, an
    optional PR-3 ``trace_id`` and free-form attrs — ``timeline(tid)``
    reassembles one request's story across tiers, keyed by the same id
    that rides the RPC wire skeleton;
  * recording is thread-safe (one recorder lock; events are built
    outside it) and NEAR-ZERO when disabled: ``record()`` is one
    attribute check and a return (``PADDLE_TPU_FLIGHT=0`` or
    ``RECORDER.set_enabled(False)``; the master ``obs.set_enabled``
    switch toggles this recorder too). The
    ``BENCH_CONFIG=flight_overhead`` microbench holds the enabled cost
    on the serving decode hot path under the same <2% bar as the
    metrics registry;
  * ``snapshot()`` is JSON-safe by construction (attrs are sanitized at
    export time, not on the hot path) so a ring dump can ride the
    data-only RPC wire (``debug_dump`` verb) and land in a bundle file
    unmodified.

Ring size: ``PADDLE_TPU_FLIGHT_RING`` (default 2048 events per tier);
overwrites are counted in ``paddle_tpu_flight_dropped_total`` so a
postmortem reader knows the window was clipped.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

import numpy as np

from . import registry as _obs

__all__ = ["FlightEvent", "FlightRecorder", "RECORDER", "record",
           "events", "snapshot", "timeline", "clear", "dump_to_file",
           "DEFAULT_RING_EVENTS"]

DEFAULT_RING_EVENTS = 2048

_EVENTS = _obs.counter(
    "paddle_tpu_flight_events_total",
    "flight-recorder events recorded, by tier ring", ["tier"])
_DROPPED = _obs.counter(
    "paddle_tpu_flight_dropped_total",
    "flight-recorder events overwritten by a full ring, by tier",
    ["tier"])


def _safe(v):
    """JSON-safe attr value (applied at snapshot/export time only —
    the record hot path stores attrs raw)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist() if v.size <= 64 \
            else f"<ndarray shape={v.shape} dtype={v.dtype}>"
    if isinstance(v, (list, tuple)):
        return [_safe(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _safe(x) for k, x in v.items()}
    return str(v)


class FlightEvent:
    __slots__ = ("ts", "wall", "tier", "kind", "trace_id", "attrs")

    def __init__(self, ts, wall, tier, kind, trace_id, attrs):
        self.ts = ts              # time.monotonic() — orders events
        self.wall = wall          # time.time() — for humans/merging
        self.tier = tier
        self.kind = kind
        self.trace_id = trace_id
        self.attrs = attrs

    def to_dict(self) -> dict:
        d = {"ts": self.ts, "wall": self.wall, "tier": self.tier,
             "kind": self.kind}
        if self.trace_id:
            d["trace_id"] = self.trace_id
        if self.attrs:
            d["attrs"] = {k: _safe(v) for k, v in self.attrs.items()}
        return d


class FlightRecorder:
    """Bounded per-tier event rings; see module docstring."""

    def __init__(self, max_events: int | None = None,
                 enabled: bool | None = None):
        if max_events is None:
            max_events = int(os.environ.get(
                "PADDLE_TPU_FLIGHT_RING", str(DEFAULT_RING_EVENTS))
                or DEFAULT_RING_EVENTS)
        if enabled is None:
            enabled = os.environ.get("PADDLE_TPU_FLIGHT", "1") != "0"
        self.max_events = max(1, int(max_events))
        self.enabled = bool(enabled)
        self._rings: dict[str, deque[FlightEvent]] = {}
        self._lock = threading.Lock()
        # optional per-event tap (the telemetry agent): called OUTSIDE
        # the recorder lock with each event; must never block
        self._sink = None

    def set_enabled(self, on: bool):
        self.enabled = bool(on)

    def set_sink(self, fn):
        """``fn(event)`` runs for every recorded event (after ring
        append, outside the recorder lock). Pass None to detach. The
        sink must be cheap and non-blocking — it runs on the recording
        thread."""
        self._sink = fn

    # -- hot path -------------------------------------------------------
    def record(self, tier: str, kind: str, /,
               trace_id: str | None = None,
               **attrs) -> FlightEvent | None:
        # tier/kind are positional-ONLY so attrs may freely reuse those
        # names (e.g. a snapshot event's kind="base"|"delta" attr)
        if not self.enabled:
            return None
        ev = FlightEvent(time.monotonic(), time.time(), tier, kind,
                         trace_id, attrs)
        with self._lock:
            ring = self._rings.get(tier)
            if ring is None:
                ring = self._rings[tier] = deque(maxlen=self.max_events)
            if len(ring) == ring.maxlen:
                _DROPPED.labels(tier=tier).inc()
            ring.append(ev)
        _EVENTS.labels(tier=tier).inc()
        sink = self._sink
        if sink is not None:
            try:
                sink(ev)
            except Exception:
                pass
        return ev

    # -- inspection / export --------------------------------------------
    def events(self, tier: str | None = None) -> list[FlightEvent]:
        with self._lock:
            if tier is not None:
                return list(self._rings.get(tier, ()))
            out = [ev for ring in self._rings.values() for ev in ring]
        out.sort(key=lambda e: e.ts)
        return out

    def timeline(self, trace_id: str) -> list[FlightEvent]:
        """Every recorded event carrying `trace_id`, across all tiers,
        in monotonic order — one request's story."""
        return [ev for ev in self.events() if ev.trace_id == trace_id]

    def snapshot(self) -> dict:
        """JSON-safe dump of every ring (the bundle/`debug_dump`
        format)."""
        with self._lock:
            tiers = {t: [ev.to_dict() for ev in ring]
                     for t, ring in self._rings.items()}
        return {"enabled": self.enabled, "max_events": self.max_events,
                "monotonic": time.monotonic(), "time": time.time(),
                "tiers": tiers}

    def clear(self):
        with self._lock:
            self._rings.clear()

    def dump_to_file(self, path: str) -> str:
        """Atomic JSON dump (tmp + rename, like the registry dump)."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.snapshot(), f)
        os.replace(tmp, path)
        return path


# process-wide recorder + module-level shortcuts
RECORDER = FlightRecorder()
record = RECORDER.record
events = RECORDER.events
snapshot = RECORDER.snapshot
timeline = RECORDER.timeline
clear = RECORDER.clear
dump_to_file = RECORDER.dump_to_file
