"""Perf-regression sentinel + the shared bench result writer.

Two halves:

* **writer** — :func:`finalize_record` stamps every bench.py result
  with the ``paddle_tpu.bench/1`` schema and (when
  ``PADDLE_TPU_BENCH_OUT`` is set) appends it as one JSON line to that
  file, so every ``BENCH_CONFIG`` leaves a machine-readable artifact.
  ``perfwatch record`` snapshots the *live* perf registry
  (:func:`paddle_tpu.observability.perf.snapshot`) the same way.
* **sentinel** — ``python -m paddle_tpu.observability.perfwatch
  compare old.json new.json`` diffs two artifacts with noise-aware
  thresholds (median-of-k samples, per-metric tolerance bands) and
  exits nonzero naming each regressed metric.  ``--tests`` mode diffs
  the per-test duration artifact the tier-1 conftest writes and flags
  tests that got >2x slower.

Accepted input formats (auto-detected): a perf snapshot
(``paddle_tpu.perf/1``), a bench record or JSONL of records
(``paddle_tpu.bench/1`` or legacy schema-less bench.py output), a
``BENCH_r*.json`` wrapper (``{"n", "cmd", "rc", "tail"}`` — records are
parsed out of the captured stdout tail), and a test-times artifact
(``paddle_tpu.test_times/1``).
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

BENCH_SCHEMA = "paddle_tpu.bench/1"
TEST_TIMES_SCHEMA = "paddle_tpu.test_times/1"

_KNOWN_SCHEMAS = ("paddle_tpu.perf/", "paddle_tpu.bench/",
                  "paddle_tpu.test_times/")


# ---------------------------------------------------------------------------
# Shared writer
# ---------------------------------------------------------------------------

def finalize_record(rec: dict, config: str) -> dict:
    """Stamp a bench.py result dict with the versioned schema and, when
    ``PADDLE_TPU_BENCH_OUT`` names a file, append it as one JSON line
    (JSONL: one BENCH_CONFIG per line, a whole sweep in one artifact)."""
    rec.setdefault("schema", BENCH_SCHEMA)
    rec.setdefault("config", config)
    rec.setdefault("created_unix", time.time())
    out = os.environ.get("PADDLE_TPU_BENCH_OUT")
    if out:
        try:
            with open(out, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError as e:  # never fail the bench over the artifact
            print(f"perfwatch: cannot write {out}: {e}", file=sys.stderr)
    return rec


# ---------------------------------------------------------------------------
# Validation (also driven by scripts/check_bench_schema.py and the
# analysis invariants suite)
# ---------------------------------------------------------------------------

def validate_record(rec) -> list[str]:
    """Problems with one bench-style record ([] = valid).

    Legacy records (pre-schema bench.py output) are accepted when they
    carry the metric/value shape; anything claiming a paddle_tpu schema
    must honor it."""
    if not isinstance(rec, dict):
        return ["record is not an object"]
    schema = rec.get("schema")
    if schema is not None:
        if not isinstance(schema, str) or \
                not schema.startswith(_KNOWN_SCHEMAS):
            return [f"unknown schema {schema!r}"]
        if schema.startswith("paddle_tpu.perf/"):
            return _validate_perf_snapshot(rec)
        if schema.startswith("paddle_tpu.test_times/"):
            return _validate_test_times(rec)
    probs = []
    if "metric" not in rec:
        probs.append("missing 'metric'")
    elif not isinstance(rec["metric"], str):
        probs.append("'metric' is not a string")
    if "value" not in rec:
        probs.append("missing 'value'")
    else:
        v = rec["value"]
        if v is not None and not isinstance(v, (int, float)):
            probs.append("'value' is not numeric or null")
        if v is None and "error" not in rec:
            probs.append("null 'value' without 'error'")
    if schema is not None and not isinstance(rec.get("unit"), str):
        probs.append("missing 'unit'")
    ex = rec.get("extras")
    if ex is not None and not isinstance(ex, dict):
        probs.append("'extras' is not an object")
    return probs


def _validate_perf_snapshot(rec: dict) -> list[str]:
    probs = []
    for k, ty in (("costs", list), ("breakdown", dict), ("mfu", dict),
                  ("kernels", dict)):
        if not isinstance(rec.get(k), ty):
            probs.append(f"perf snapshot: '{k}' is not {ty.__name__}")
    for c in rec.get("costs") or []:
        if not isinstance(c, dict) or "name" not in c or "key" not in c:
            probs.append("perf snapshot: cost row without name/key")
            break
    return probs


def _validate_test_times(rec: dict) -> list[str]:
    t = rec.get("tests")
    if not isinstance(t, dict):
        return ["test-times artifact: 'tests' is not an object"]
    bad = [k for k, v in t.items() if not isinstance(v, (int, float))]
    if bad:
        return [f"test-times artifact: non-numeric duration for {bad[0]}"]
    return []


def _records_from_tail(tail: str) -> list[dict]:
    """Bench records embedded in a BENCH_r*.json captured-stdout tail."""
    recs = []
    for line in tail.splitlines():
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            recs.append(obj)
    return recs


def validate_file(path: str) -> list[str]:
    """Problems with a results file ([] = valid); format auto-detected."""
    try:
        text = open(path).read()
    except OSError as e:
        return [f"cannot read {path}: {e}"]
    recs, probs = [], []
    try:
        obj = json.loads(text)
    except ValueError:
        obj = None
    if obj is None:  # JSONL from the shared writer
        for i, line in enumerate(text.splitlines()):
            if not line.strip():
                continue
            try:
                recs.append(json.loads(line))
            except ValueError:
                probs.append(f"line {i + 1}: not JSON")
    elif isinstance(obj, dict) and "tail" in obj and "cmd" in obj:
        tail = str(obj.get("tail") or "")
        recs = _records_from_tail(tail)
        # a tail is a bounded stdout suffix: when capture clipped the
        # head mid-line (first line is not JSON), record loss is
        # expected — only a complete-looking, record-free tail of a
        # successful run is a schema problem
        truncated = bool(tail) and not tail.lstrip().startswith("{")
        if not recs and not truncated and obj.get("rc", 0) == 0:
            probs.append("wrapper tail contains no bench records")
    else:
        recs = [obj]
    for r in recs:
        for p in validate_record(r):
            name = r.get("metric") or r.get("schema") or "?" \
                if isinstance(r, dict) else "?"
            probs.append(f"{name}: {p}")
    return probs


# ---------------------------------------------------------------------------
# Loading + flattening for compare
# ---------------------------------------------------------------------------

_HIGHER_HINTS = ("per_sec", "per_s", "tokens_per", "mfu", "margin",
                 "throughput", "tps", "hits")


def _direction(name: str, unit: str = "") -> str:
    """'higher' if bigger is better for this metric, else 'lower'."""
    s = (name + " " + unit).lower()
    if "/sec" in s or "/s/chip" in s or any(h in s for h in _HIGHER_HINTS):
        return "higher"
    return "lower"


def _median(v):
    if isinstance(v, (list, tuple)):
        nums = [x for x in v if isinstance(x, (int, float))]
        return statistics.median(nums) if nums else None
    return v if isinstance(v, (int, float)) else None


def _flatten(obj: dict) -> dict[str, tuple[float, str]]:
    """{metric_name: (median value, direction)} from any accepted
    artifact.  List-valued leaves (median-of-k recordings) collapse to
    their median here — that is the noise-awareness of the sentinel."""
    out: dict[str, tuple[float, str]] = {}

    def put(name, v, unit=""):
        m = _median(v)
        if m is not None:
            out[name] = (float(m), _direction(name, unit))

    schema = obj.get("schema", "")
    if schema.startswith("paddle_tpu.perf/"):
        for n, v in (obj.get("mfu") or {}).items():
            put(f"mfu.{n}", v)
        for n, ent in (obj.get("breakdown") or {}).items():
            for ph, v in (ent.get("phases") or {}).items():
                put(f"breakdown.{n}.{ph}", v, "seconds")
        for key, ent in (obj.get("kernels") or {}).items():
            win = ent.get("winner")
            win_ms = (ent.get("candidates_ms") or {}).get(win)
            put(f"kernel.{key}.winner_ms", win_ms, "ms")
        for n, d in (obj.get("providers") or {}).items():
            if isinstance(d, dict):
                for k, v in d.items():
                    put(f"{n}.{k}", v)
    elif schema.startswith("paddle_tpu.test_times/"):
        for nodeid, secs in (obj.get("tests") or {}).items():
            put(f"test.{nodeid}", secs, "seconds")
    elif "metric" in obj:  # one bench record (schema'd or legacy)
        unit = str(obj.get("unit", ""))
        put(str(obj["metric"]), obj.get("value"), unit)
        for k, v in (obj.get("extras") or {}).items():
            if isinstance(v, dict):
                put(f"{obj['metric']}.{k}", v.get("value"),
                    str(v.get("unit", "")))
            else:
                put(f"{obj['metric']}.{k}", v)
    return out


def load_result(path: str) -> dict[str, tuple[float, str]]:
    """Flat metric map from a results file (see module docstring for
    the accepted formats)."""
    text = open(path).read()
    try:
        obj = json.loads(text)
    except ValueError:
        obj = None
    merged: dict[str, tuple[float, str]] = {}
    if obj is None:  # JSONL
        for line in text.splitlines():
            if line.strip():
                try:
                    merged.update(_flatten(json.loads(line)))
                except ValueError:
                    pass
    elif isinstance(obj, dict) and "tail" in obj and "cmd" in obj:
        for rec in _records_from_tail(str(obj.get("tail") or "")):
            merged.update(_flatten(rec))
    elif isinstance(obj, dict):
        merged.update(_flatten(obj))
    return merged


# ---------------------------------------------------------------------------
# Compare
# ---------------------------------------------------------------------------

DEFAULT_TOL_PCT = 5.0
# Below this absolute delta a metric never regresses — sub-epsilon
# noise on near-zero readings (a 0.2ms phase) should not fail CI.
_ABS_FLOOR = {"seconds": 1e-4, "ms": 0.05, "": 0.0}


def compare(old: dict[str, tuple[float, str]],
            new: dict[str, tuple[float, str]],
            tol_pct: float = DEFAULT_TOL_PCT,
            tol_map: dict[str, float] | None = None,
            ) -> tuple[int, list[str]]:
    """(exit code, report lines).  0 = no regression; 1 = at least one
    metric regressed beyond its tolerance band, named in the lines."""
    tol_map = tol_map or {}
    lines, regressed = [], []
    common = sorted(set(old) & set(new))
    for name in common:
        ov, direction = old[name]
        nv = new[name][0]
        tol = tol_map.get(name, tol_pct) / 100.0
        delta = nv - ov
        rel = delta / abs(ov) if ov else (0.0 if not delta else float("inf"))
        worse = rel > tol if direction == "lower" else rel < -tol
        floor = 1e-4 if name.startswith(("breakdown.", "test.")) else 0.0
        if worse and abs(delta) > floor:
            regressed.append(name)
            lines.append(
                f"REGRESSION {name}: {ov:.6g} -> {nv:.6g} "
                f"({rel:+.1%}, tol ±{tol:.0%}, {direction}-is-better)")
        else:
            lines.append(f"ok         {name}: {ov:.6g} -> {nv:.6g} "
                         f"({rel:+.1%})")
    for name in sorted(set(old) - set(new)):
        lines.append(f"note       {name}: only in old")
    for name in sorted(set(new) - set(old)):
        lines.append(f"note       {name}: only in new")
    if regressed:
        lines.append(f"{len(regressed)} regressed metric(s): "
                     + ", ".join(regressed))
    elif common:
        lines.append(f"no regressions across {len(common)} metric(s)")
    else:
        lines.append("no comparable metrics")
    return (1 if regressed else 0), lines


def compare_tests(old_path: str, new_path: str,
                  ratio: float = 2.0, floor_s: float = 0.25,
                  ) -> tuple[int, list[str]]:
    """Flag tests that got > `ratio`x slower (and slower by more than
    `floor_s` seconds — sub-second jitter is not a regression)."""
    old = json.load(open(old_path))
    new = json.load(open(new_path))
    for p, rec in ((old_path, old), (new_path, new)):
        probs = _validate_test_times(rec)
        if probs:
            return 2, [f"{p}: {probs[0]}"]
    lines, flagged = [], []
    ot, nt = old["tests"], new["tests"]
    for nodeid in sorted(set(ot) & set(nt)):
        o, n = float(ot[nodeid]), float(nt[nodeid])
        if n > max(ratio * o, o + floor_s):
            flagged.append(nodeid)
            lines.append(f"SLOWER {nodeid}: {o:.2f}s -> {n:.2f}s "
                         f"({n / o if o else float('inf'):.1f}x)")
    tot_o, tot_n = sum(ot.values()), sum(nt.values())
    lines.append(f"wall: {tot_o:.1f}s -> {tot_n:.1f}s over "
                 f"{len(set(ot) & set(nt))} shared test(s)")
    if flagged:
        lines.append(f"{len(flagged)} test(s) >"
                     f"{ratio:g}x slower: " + ", ".join(flagged))
    return (1 if flagged else 0), lines


# ---------------------------------------------------------------------------
# Record
# ---------------------------------------------------------------------------

def record_snapshot(out: str | None, samples: int = 1,
                    interval: float = 0.0) -> dict:
    """Snapshot the live perf registry; with samples>1, numeric leaves
    of mfu/breakdown become value *lists* (compare medianizes them)."""
    from . import perf as _perf

    snaps = []
    for i in range(max(1, samples)):
        if i and interval > 0:
            time.sleep(interval)
        snaps.append(_perf.snapshot())
    snap = snaps[-1]
    if len(snaps) > 1:
        mfu = {}
        for s in snaps:
            for n, v in (s.get("mfu") or {}).items():
                mfu.setdefault(n, []).append(v)
        snap["mfu"] = mfu
        bd: dict[str, dict] = {}
        for s in snaps:
            for n, ent in (s.get("breakdown") or {}).items():
                slot = bd.setdefault(n, {"samples": ent.get("samples", 0),
                                         "phases": {}})
                for ph, v in (ent.get("phases") or {}).items():
                    slot["phases"].setdefault(ph, []).append(v)
        snap["breakdown"] = bd
        snap["samples"] = len(snaps)
    payload = json.dumps(snap, indent=1, sort_keys=True)
    if out:
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            f.write(payload + "\n")
        os.replace(tmp, out)
    else:
        print(payload)
    return snap


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _parse_tol(items) -> dict[str, float]:
    out = {}
    for it in items or ():
        name, _, pct = it.partition("=")
        try:
            out[name] = float(pct)
        except ValueError:
            raise SystemExit(f"bad --tol entry {it!r} (want name=pct)")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.perfwatch",
        description="perf snapshot recorder + regression sentinel")
    sub = ap.add_subparsers(dest="cmd", required=True)

    rp = sub.add_parser("record", help="snapshot the live perf registry")
    rp.add_argument("-o", "--out", default=None,
                    help="output path (default: stdout)")
    rp.add_argument("--samples", type=int, default=1,
                    help="median-of-k: take k snapshots")
    rp.add_argument("--interval", type=float, default=1.0,
                    help="seconds between snapshots when --samples > 1")

    cp = sub.add_parser("compare", help="diff two result files")
    cp.add_argument("old")
    cp.add_argument("new")
    cp.add_argument("--tol-pct", type=float, default=DEFAULT_TOL_PCT,
                    help="default tolerance band, percent "
                         f"(default {DEFAULT_TOL_PCT:g})")
    cp.add_argument("--tol", action="append", metavar="NAME=PCT",
                    help="per-metric tolerance override (repeatable)")
    cp.add_argument("--tests", action="store_true",
                    help="inputs are test-times artifacts; flag >2x "
                         "slower tests")

    vp = sub.add_parser("validate", help="schema-check result files")
    vp.add_argument("files", nargs="+")

    args = ap.parse_args(argv)
    if args.cmd == "record":
        record_snapshot(args.out, samples=args.samples,
                        interval=args.interval)
        return 0
    if args.cmd == "validate":
        rc = 0
        for p in args.files:
            probs = validate_file(p)
            for prob in probs:
                print(f"{p}: {prob}")
                rc = 1
            if not probs:
                print(f"{p}: ok")
        return rc
    # compare
    try:
        if args.tests:
            rc, lines = compare_tests(args.old, args.new)
        else:
            rc, lines = compare(load_result(args.old),
                                load_result(args.new),
                                tol_pct=args.tol_pct,
                                tol_map=_parse_tol(args.tol))
    except (OSError, ValueError) as e:
        print(f"perfwatch: {e}", file=sys.stderr)
        return 2
    for ln in lines:
        print(ln)
    return rc


if __name__ == "__main__":
    sys.exit(main())
