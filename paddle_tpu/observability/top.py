"""Live terminal fleet dashboard over the telemetry collector.

``python -m paddle_tpu.observability.top --collector host:port``
renders the fleet every interval: one row per process (role,
liveness, rps, p50/p99 TTFT/ITL, queue depth, page occupancy, agent
drop counts), the tail-sampling counters, and the most recent
watchdog/bundle events with their bundle paths — the
"start from the dashboard" entry point of docs/DEBUGGING.md.

``python -m paddle_tpu.observability.top trace <id>`` prints the
assembled cross-process waterfall for one trace id and, with
``--out f.json``, exports it as ONE merged Chrome trace with
per-rank pid labels (Perfetto / chrome://tracing).

``python -m paddle_tpu.observability.top perf`` renders the perf
pane (docs/OBSERVABILITY.md perf plane): per-role MFU, the last
sampled step breakdown, compile counts (a rising number mid-run is a
compile storm), HBM/KV headroom, and the autobench per-kernel
Pallas-vs-XLA margins.

Rendering is pure (``render_fleet`` / ``render_waterfall`` /
``render_perf`` take the collector reply dicts), so tests drive it
without a terminal.
"""
from __future__ import annotations

import json
import os
import sys
import time

__all__ = ["render_fleet", "render_perf", "render_tier",
           "render_waterfall", "main"]


def _f(v, spec="7.1f", dash="      -") -> str:
    if v is None:
        return dash
    try:
        return format(float(v), spec)
    except (TypeError, ValueError):
        return dash


def _ms(v) -> str:
    return "-" if v is None else f"{float(v) * 1000:.1f}ms"


def render_fleet(fleet: dict) -> str:
    """One screen of fleet state from a ``tel_fleet`` reply."""
    lines = []
    t = fleet.get("time") or time.time()
    tr = fleet.get("traces") or {}
    lines.append(
        f"paddle-tpu fleet  {time.strftime('%H:%M:%S', time.localtime(t))}"
        f"  procs={len(fleet.get('procs') or ())}"
        f"  open={fleet.get('open_traces', 0)}"
        f"  kept={fleet.get('kept_traces', 0)}")
    lines.append(
        "traces: assembled=%d kept(err=%d slow=%d sampled=%d) "
        "sampled_out=%d evicted=%d" % (
            tr.get("assembled", 0), tr.get("kept_error", 0),
            tr.get("kept_slow", 0), tr.get("kept_sampled", 0),
            tr.get("sampled_out", 0), tr.get("evicted", 0)))
    lines.append("")
    lines.append(f"{'ROLE':<16} {'HOST:PID':<22} {'AGE':>5} {'RPS':>7} "
                 f"{'TTFT p50/p99':>15} {'ITL p50/p99':>15} "
                 f"{'QUEUE':>6} {'OCC':>5} {'DROPS':>6}")
    for p in fleet.get("procs") or ():
        s = p.get("summary") or {}
        drops = sum((p.get("dropped") or {}).values())
        ttft = f"{_ms(s.get('ttft_p50'))}/{_ms(s.get('ttft_p99'))}" \
            if "ttft_p50" in s else \
            (f"{_ms(s.get('latency_p50'))}/{_ms(s.get('latency_p99'))}"
             if "latency_p50" in s else "-")
        itl = f"{_ms(s.get('itl_p50'))}/{_ms(s.get('itl_p99'))}" \
            if "itl_p50" in s else "-"
        lines.append(
            f"{str(p.get('role'))[:16]:<16} "
            f"{p.get('host')}:{p.get('pid'):<10} "
            f"{_f(p.get('age_s'), '5.1f', '    -')} "
            f"{_f(s.get('rps'), '7.1f')} "
            f"{ttft:>15} {itl:>15} "
            f"{_f(s.get('queue_depth'), '6.0f', '     -')} "
            f"{_f(s.get('page_occupancy'), '5.2f', '    -')} "
            f"{drops:>6d}")
    events = fleet.get("recent_events") or ()
    if events:
        lines.append("")
        lines.append("recent events:")
        for ev in list(events)[-8:]:
            at = ev.get("attrs") or {}
            extra = " ".join(f"{k}={v}" for k, v in sorted(at.items()))
            w = ev.get("wall")
            stamp = time.strftime("%H:%M:%S", time.localtime(w)) \
                if w else "--:--:--"
            lines.append(f"  {stamp} {ev.get('role')}@{ev.get('host')}"
                         f":{ev.get('pid')} {ev.get('kind')} {extra}")
    return "\n".join(lines)


def _gb(v) -> str:
    return "-" if not v else f"{float(v) / 2**30:.2f}G"


def render_perf(fleet: dict) -> str:
    """The perf pane of a ``tel_fleet`` reply: per-role MFU + step
    breakdown, compile counts, HBM/KV bytes, per-kernel margins."""
    lines = [f"{'ROLE':<16} {'HOST:PID':<22} {'MFU':>7} {'COMPILES':>9} "
             f"{'HBM used/limit':>16} {'KV':>8}  STEP BREAKDOWN (sampled)"]
    kernel_ms: dict[str, float] = {}
    any_perf = False
    for p in fleet.get("procs") or ():
        perf = (p.get("summary") or {}).get("perf") or {}
        if not perf:
            continue
        any_perf = True
        mfu = perf.get("mfu") or {}
        hbm = perf.get("hbm") or {}
        # one row per instrumented loop (engine:eN / executor), the
        # process-level columns repeated on the first row only
        loops = sorted(set(mfu)
                       | {k.split("/")[0]
                          for k in (perf.get("breakdown") or {})}) or ["-"]
        for i, name in enumerate(loops):
            bd = {k.split("/", 1)[1]: v for k, v
                  in (perf.get("breakdown") or {}).items()
                  if k.split("/")[0] == name}
            bd_s = " ".join(f"{ph}={v * 1e3:.2f}ms" for ph, v
                            in sorted(bd.items())) or "-"
            first = i == 0
            lines.append(
                f"{str(p.get('role'))[:16] if first else '':<16} "
                f"{(str(p.get('host')) + ':' + str(p.get('pid'))) if first else '':<22} "
                f"{_f(mfu.get(name), '7.4f')} "
                f"{_f(perf.get('compiles_total') if first else None, '9.0f', '        -')} "
                f"{(_gb(hbm.get('in_use')) + '/' + _gb(hbm.get('limit'))) if first else '':>16} "
                f"{_gb(perf.get('kv_cache_bytes')) if first else '':>8}  "
                f"{name}: {bd_s}")
        kernel_ms.update(perf.get("kernel_ms") or {})
    if not any_perf:
        lines.append("(no perf data yet — engines/executors report "
                     "after their first compiled step)")
    if kernel_ms:
        lines.append("")
        lines.append("kernel margins (autobench, ms per candidate):")
        by_key: dict[str, dict[str, float]] = {}
        for kc, ms in kernel_ms.items():
            key, _, cand = kc.rpartition("/")
            by_key.setdefault(key, {})[cand] = ms
        for key in sorted(by_key):
            cands = by_key[key]
            finite = {c: m for c, m in cands.items()
                      if m is not None and m == m and m != float("inf")}
            win = min(finite, key=finite.get) if finite else "-"
            row = " ".join(
                f"{c}={'' if m is None else format(m, '.3f')}"
                + ("*" if c == win else "")
                for c, m in sorted(cands.items()))
            lines.append(f"  {key}: {row}")
    return "\n".join(lines)


def render_tier(fleet: dict) -> str:
    """The tiered-PS pane of a ``tel_fleet`` reply: per-shard warm/
    cold residency, hit split, fault/demotion totals, and the by-tier
    pull latency quantiles (docs/PS_TIERED.md)."""
    lines = [f"{'ROLE':<16} {'HOST:PID':<22} {'WARM rows/bytes':>18} "
             f"{'COLD rows/bytes':>18} {'HIT warm/cold':>15} "
             f"{'FAULTS':>8} {'DEMOTE':>8} {'ERR':>5} "
             f"{'PULL p50/p99':>15}"]
    any_tier = False
    for p in fleet.get("procs") or ():
        tier = (p.get("summary") or {}).get("tier") or {}
        if not tier:
            continue
        any_tier = True
        rows = tier.get("resident_rows") or {}
        nbytes = tier.get("resident_bytes") or {}
        hits = tier.get("hits") or {}
        lines.append(
            f"{str(p.get('role'))[:16]:<16} "
            f"{p.get('host')}:{p.get('pid'):<10} "
            f"{_f(rows.get('warm'), '8.0f')}/{_gb(nbytes.get('warm')):>9} "
            f"{_f(rows.get('cold'), '8.0f')}/{_gb(nbytes.get('cold')):>9} "
            f"{_f(hits.get('warm'), '7.0f')}/{_f(hits.get('cold'), '7.0f')} "
            f"{_f(tier.get('faults'), '8.0f')} "
            f"{_f(tier.get('demotions'), '8.0f')} "
            f"{_f(tier.get('cold_read_errors'), '5.0f', '    0')} "
            f"{_ms(tier.get('pull_p50'))}/{_ms(tier.get('pull_p99')):>7}")
    if not any_tier:
        lines.append("(no tiered tables yet — PS shards report after "
                     "PADDLE_PS_TIER_WARM_BYTES opts a table in)")
    return "\n".join(lines)


def render_waterfall(trace: dict) -> str:
    """The assembled cross-process waterfall of one ``tel_trace``
    reply: spans in aligned start order, indented by span parentage,
    one rank tag per line."""
    spans = trace.get("spans") or ()
    if not spans:
        return f"trace {trace.get('trace_id')}: no spans"
    t0 = min(s["t0"] for s in spans)
    t1 = max(s["t1"] for s in spans)
    by_id = {s.get("span_id"): s for s in spans}

    def depth(s, limit=16):
        d = 0
        while d < limit:
            pid_ = s.get("parent_id")
            if not pid_ or pid_ not in by_id:
                return d
            s = by_id[pid_]
            d += 1
        return d

    head = [f"trace {trace.get('trace_id')}  "
            f"{(t1 - t0) * 1000:.2f}ms  "
            f"spans={len(spans)} procs={len(trace.get('procs') or ())}"
            f"  verdict={trace.get('verdict', 'open')}"
            f"{'' if trace.get('complete', True) else '  (incomplete)'}"]
    if trace.get("error"):
        head.append("  ** contains errors/deadline misses **")
    if trace.get("watchdog_flagged"):
        head.append("  ** watchdog flagged **")
    lines = head
    width = 30
    span_ms = max(1e-9, t1 - t0)
    for s in sorted(spans, key=lambda x: (x["t0"], x["t1"])):
        off = (s["t0"] - t0)
        dur = max(0.0, s["t1"] - s["t0"])
        a = int(width * off / span_ms)
        b = max(1, int(width * dur / span_ms))
        bar = " " * a + "#" * min(b, width - a)
        lines.append(
            f"{off * 1000:9.2f}ms {bar:<{width}} "
            f"{'  ' * depth(s)}{s['name']} ({dur * 1000:.2f}ms) "
            f"[{s.get('role')}@{s.get('host')}:{s.get('pid')}]")
    flights = trace.get("flight") or ()
    if flights:
        lines.append(f"flight events: "
                     + ", ".join(sorted({f"{e.get('tier')}/{e.get('kind')}"
                                         for e in flights})))
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.observability.top",
        description="live fleet dashboard / trace waterfall viewer")
    ap.add_argument("cmd", nargs="?", default="top",
                    choices=["top", "trace", "perf", "tier"])
    ap.add_argument("trace_id", nargs="?")
    ap.add_argument("--collector", default=os.environ.get(
        "PADDLE_TPU_TELEMETRY_COLLECTOR") or "127.0.0.1:8600")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (no ANSI)")
    ap.add_argument("--out", help="trace: write the merged Chrome "
                                  "trace JSON here")
    args = ap.parse_args(argv)

    from ..distributed.fleet.runtime.rpc import RpcClient
    cli = RpcClient(args.collector,
                    secret=os.environ.get("PADDLE_PS_SECRET") or None,
                    timeout=5.0, deadline=5.0, max_retries=0)
    try:
        if args.cmd == "trace":
            if not args.trace_id:
                print("usage: ... trace <trace_id>", file=sys.stderr)
                return 2
            rep = cli.call({"op": "tel_trace",
                            "trace_id": args.trace_id,
                            "chrome": bool(args.out)})
            tr = rep.get("trace")
            if tr is None:
                print(f"trace {args.trace_id}: not retained "
                      f"(unknown or sampled out)", file=sys.stderr)
                return 1
            print(render_waterfall(tr))
            if args.out and rep.get("chrome") is not None:
                with open(args.out, "w", encoding="utf-8") as f:
                    json.dump(rep["chrome"], f)
                print(f"chrome trace -> {args.out}")
            return 0
        # top/perf: live loop (or one shot)
        render = {"perf": render_perf,
                  "tier": render_tier}.get(args.cmd, render_fleet)
        while True:
            fleet = cli.call({"op": "tel_fleet"})["fleet"]
            text = render(fleet)
            if args.once:
                print(text)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + text + "\n")
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0
    finally:
        cli.close()


if __name__ == "__main__":
    raise SystemExit(main())
