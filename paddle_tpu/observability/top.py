"""Live terminal fleet dashboard over the telemetry collector.

``python -m paddle_tpu.observability.top --collector host:port``
renders the fleet every interval: one row per process (role,
liveness, rps, p50/p99 TTFT/ITL, queue depth, page occupancy, agent
drop counts), the tail-sampling counters, and the most recent
watchdog/bundle events with their bundle paths — the
"start from the dashboard" entry point of docs/DEBUGGING.md.

``python -m paddle_tpu.observability.top trace <id>`` prints the
assembled cross-process waterfall for one trace id and, with
``--out f.json``, exports it as ONE merged Chrome trace with
per-rank pid labels (Perfetto / chrome://tracing).

``python -m paddle_tpu.observability.top perf`` renders the perf
pane (docs/OBSERVABILITY.md perf plane): per-role MFU, the last
sampled step breakdown, compile counts (a rising number mid-run is a
compile storm), HBM/KV headroom, and the autobench per-kernel
Pallas-vs-XLA margins.

``... top history <metric>`` renders per-series unicode sparklines
from the collector's TSDB (`tsdb_query` range); ``... top alerts``
the alert pane (firing/pending + recent transitions); ``... top
tenants`` the per-tenant usage pane (`usage_report`).

Rendering is pure (``render_fleet`` / ``render_waterfall`` /
``render_perf`` / ``render_history`` / ``render_alerts`` /
``render_tenants`` take the collector reply dicts), so tests drive
them without a terminal.
"""
from __future__ import annotations

import json
import os
import sys
import time

__all__ = ["render_alerts", "render_fleet", "render_history",
           "render_perf", "render_tenants", "render_tier",
           "render_waterfall", "sparkline", "main"]


def _f(v, spec="7.1f", dash="      -") -> str:
    if v is None:
        return dash
    try:
        return format(float(v), spec)
    except (TypeError, ValueError):
        return dash


def _ms(v) -> str:
    return "-" if v is None else f"{float(v) * 1000:.1f}ms"


def render_fleet(fleet: dict) -> str:
    """One screen of fleet state from a ``tel_fleet`` reply."""
    lines = []
    t = fleet.get("time") or time.time()
    tr = fleet.get("traces") or {}
    lines.append(
        f"paddle-tpu fleet  {time.strftime('%H:%M:%S', time.localtime(t))}"
        f"  procs={len(fleet.get('procs') or ())}"
        f"  open={fleet.get('open_traces', 0)}"
        f"  kept={fleet.get('kept_traces', 0)}")
    lines.append(
        "traces: assembled=%d kept(err=%d slow=%d sampled=%d) "
        "sampled_out=%d evicted=%d" % (
            tr.get("assembled", 0), tr.get("kept_error", 0),
            tr.get("kept_slow", 0), tr.get("kept_sampled", 0),
            tr.get("sampled_out", 0), tr.get("evicted", 0)))
    lines.append("")
    lines.append(f"{'ROLE':<16} {'HOST:PID':<22} {'AGE':>5} {'RPS':>7} "
                 f"{'TTFT p50/p99':>15} {'ITL p50/p99':>15} "
                 f"{'QUEUE':>6} {'OCC':>5} {'DROPS':>6}")
    for p in fleet.get("procs") or ():
        s = p.get("summary") or {}
        drops = sum((p.get("dropped") or {}).values())
        ttft = f"{_ms(s.get('ttft_p50'))}/{_ms(s.get('ttft_p99'))}" \
            if "ttft_p50" in s else \
            (f"{_ms(s.get('latency_p50'))}/{_ms(s.get('latency_p99'))}"
             if "latency_p50" in s else "-")
        itl = f"{_ms(s.get('itl_p50'))}/{_ms(s.get('itl_p99'))}" \
            if "itl_p50" in s else "-"
        lines.append(
            f"{str(p.get('role'))[:16]:<16} "
            f"{p.get('host')}:{p.get('pid'):<10} "
            f"{_f(p.get('age_s'), '5.1f', '    -')} "
            f"{_f(s.get('rps'), '7.1f')} "
            f"{ttft:>15} {itl:>15} "
            f"{_f(s.get('queue_depth'), '6.0f', '     -')} "
            f"{_f(s.get('page_occupancy'), '5.2f', '    -')} "
            f"{drops:>6d}")
    events = fleet.get("recent_events") or ()
    if events:
        lines.append("")
        lines.append("recent events:")
        for ev in list(events)[-8:]:
            at = ev.get("attrs") or {}
            extra = " ".join(f"{k}={v}" for k, v in sorted(at.items()))
            w = ev.get("wall")
            stamp = time.strftime("%H:%M:%S", time.localtime(w)) \
                if w else "--:--:--"
            lines.append(f"  {stamp} {ev.get('role')}@{ev.get('host')}"
                         f":{ev.get('pid')} {ev.get('kind')} {extra}")
    return "\n".join(lines)


def _gb(v) -> str:
    return "-" if not v else f"{float(v) / 2**30:.2f}G"


def render_perf(fleet: dict) -> str:
    """The perf pane of a ``tel_fleet`` reply: per-role MFU + step
    breakdown, compile counts, HBM/KV bytes, per-kernel margins."""
    lines = [f"{'ROLE':<16} {'HOST:PID':<22} {'MFU':>7} {'COMPILES':>9} "
             f"{'HBM used/limit':>16} {'KV':>8}  STEP BREAKDOWN (sampled)"]
    kernel_ms: dict[str, float] = {}
    any_perf = False
    for p in fleet.get("procs") or ():
        perf = (p.get("summary") or {}).get("perf") or {}
        if not perf:
            continue
        any_perf = True
        mfu = perf.get("mfu") or {}
        hbm = perf.get("hbm") or {}
        # one row per instrumented loop (engine:eN / executor), the
        # process-level columns repeated on the first row only
        loops = sorted(set(mfu)
                       | {k.split("/")[0]
                          for k in (perf.get("breakdown") or {})}) or ["-"]
        for i, name in enumerate(loops):
            bd = {k.split("/", 1)[1]: v for k, v
                  in (perf.get("breakdown") or {}).items()
                  if k.split("/")[0] == name}
            bd_s = " ".join(f"{ph}={v * 1e3:.2f}ms" for ph, v
                            in sorted(bd.items())) or "-"
            first = i == 0
            lines.append(
                f"{str(p.get('role'))[:16] if first else '':<16} "
                f"{(str(p.get('host')) + ':' + str(p.get('pid'))) if first else '':<22} "
                f"{_f(mfu.get(name), '7.4f')} "
                f"{_f(perf.get('compiles_total') if first else None, '9.0f', '        -')} "
                f"{(_gb(hbm.get('in_use')) + '/' + _gb(hbm.get('limit'))) if first else '':>16} "
                f"{_gb(perf.get('kv_cache_bytes')) if first else '':>8}  "
                f"{name}: {bd_s}")
        kernel_ms.update(perf.get("kernel_ms") or {})
    if not any_perf:
        lines.append("(no perf data yet — engines/executors report "
                     "after their first compiled step)")
    if kernel_ms:
        lines.append("")
        lines.append("kernel margins (autobench, ms per candidate):")
        by_key: dict[str, dict[str, float]] = {}
        for kc, ms in kernel_ms.items():
            key, _, cand = kc.rpartition("/")
            by_key.setdefault(key, {})[cand] = ms
        for key in sorted(by_key):
            cands = by_key[key]
            finite = {c: m for c, m in cands.items()
                      if m is not None and m == m and m != float("inf")}
            win = min(finite, key=finite.get) if finite else "-"
            row = " ".join(
                f"{c}={'' if m is None else format(m, '.3f')}"
                + ("*" if c == win else "")
                for c, m in sorted(cands.items()))
            lines.append(f"  {key}: {row}")
    return "\n".join(lines)


def render_tier(fleet: dict) -> str:
    """The tiered-PS pane of a ``tel_fleet`` reply: per-shard warm/
    cold residency, hit split, fault/demotion totals, and the by-tier
    pull latency quantiles (docs/PS_TIERED.md)."""
    lines = [f"{'ROLE':<16} {'HOST:PID':<22} {'WARM rows/bytes':>18} "
             f"{'COLD rows/bytes':>18} {'HIT warm/cold':>15} "
             f"{'FAULTS':>8} {'DEMOTE':>8} {'ERR':>5} "
             f"{'PULL p50/p99':>15}"]
    any_tier = False
    for p in fleet.get("procs") or ():
        tier = (p.get("summary") or {}).get("tier") or {}
        if not tier:
            continue
        any_tier = True
        rows = tier.get("resident_rows") or {}
        nbytes = tier.get("resident_bytes") or {}
        hits = tier.get("hits") or {}
        lines.append(
            f"{str(p.get('role'))[:16]:<16} "
            f"{p.get('host')}:{p.get('pid'):<10} "
            f"{_f(rows.get('warm'), '8.0f')}/{_gb(nbytes.get('warm')):>9} "
            f"{_f(rows.get('cold'), '8.0f')}/{_gb(nbytes.get('cold')):>9} "
            f"{_f(hits.get('warm'), '7.0f')}/{_f(hits.get('cold'), '7.0f')} "
            f"{_f(tier.get('faults'), '8.0f')} "
            f"{_f(tier.get('demotions'), '8.0f')} "
            f"{_f(tier.get('cold_read_errors'), '5.0f', '    0')} "
            f"{_ms(tier.get('pull_p50'))}/{_ms(tier.get('pull_p99')):>7}")
    if not any_tier:
        lines.append("(no tiered tables yet — PS shards report after "
                     "PADDLE_PS_TIER_WARM_BYTES opts a table in)")
    return "\n".join(lines)


def render_prefix(fleet: dict) -> str:
    """The prefix-cache pane of a ``tel_fleet`` reply: per-engine hit
    ratio, prefill tokens the radix cache absorbed, COW/eviction churn,
    residency, and how much decode rides stochastic sampling
    (docs/SERVING.md shared-prefix section)."""
    lines = [f"{'ROLE':<16} {'HOST:PID':<22} {'HIT%':>6} {'LOOKUPS':>8} "
             f"{'TOK SAVED':>10} {'CACHED':>7} {'SHARED':>7} "
             f"{'COW':>5} {'EVICT':>6} {'SAMPLED req/tok':>16}"]
    any_prefix = False
    for p in fleet.get("procs") or ():
        pf = (p.get("summary") or {}).get("prefix") or {}
        if not pf:
            continue
        any_prefix = True
        ratio = pf.get("hit_ratio")
        lines.append(
            f"{str(p.get('role'))[:16]:<16} "
            f"{p.get('host')}:{p.get('pid'):<10} "
            f"{_f(None if ratio is None else ratio * 100, '6.1f')} "
            f"{_f(pf.get('lookups'), '8.0f')} "
            f"{_f(pf.get('tokens_saved'), '10.0f')} "
            f"{_f(pf.get('cached_pages'), '7.0f', '      0')} "
            f"{_f(pf.get('shared_pages'), '7.0f', '      0')} "
            f"{_f(pf.get('cow_copies'), '5.0f', '    0')} "
            f"{_f(pf.get('evicted'), '6.0f', '     0')} "
            f"{_f(pf.get('sampled_requests'), '7.0f', '      0')}/"
            f"{_f(pf.get('sampled_tokens'), '8.0f', '       0')}")
    if not any_prefix:
        lines.append("(no prefix-cache traffic yet — engines report "
                     "after PADDLE_TPU_PREFIX_CACHE_PAGES > 0 sees a "
                     "lookup)")
    return "\n".join(lines)


_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values, width: int = 48) -> str:
    """Unicode sparkline, min..max scaled, downsampled to `width` by
    last-value-per-cell (matching the TSDB's downsampling rule)."""
    vals = [float(v) for v in values if v is not None]
    if not vals:
        return ""
    if len(vals) > width:
        step = len(vals) / width
        vals = [vals[min(len(vals) - 1, int((i + 1) * step) - 1)]
                for i in range(width)]
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK[0] * len(vals)
    return "".join(_SPARK[min(7, int((v - lo) / span * 8))]
                   for v in vals)


def render_history(reply: dict, metric: str, window: float = 300.0) \
        -> str:
    """Sparkline pane from a ``tsdb_query`` range reply: one line per
    matching series — label set, last value, min..max, sparkline."""
    pts = reply.get("points") or ()
    if reply.get("error"):
        return f"history {metric}: {reply['error']}"
    if not pts:
        return f"history {metric}: no samples in the last " \
               f"{window:.0f}s"
    lines = [f"history {metric}  last {window:.0f}s  "
             f"series={len(pts)}"]
    for s in pts:
        vals = [v for _, v in (s.get("points") or ())]
        if not vals:
            continue
        labels = s.get("labels") or {}
        tag = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
        lines.append(
            f"  {tag[:44]:<44} {sparkline(vals)} "
            f"last={vals[-1]:g} min={min(vals):g} max={max(vals):g}")
    return "\n".join(lines)


def render_alerts(reply: dict) -> str:
    """The alert pane from an ``alerts`` verb reply: firing/pending
    instances first, then recent transitions, then the rule table."""
    st = reply.get("alerts") or {}
    active = st.get("active") or ()
    lines = [f"alerts  active={len(active)}  "
             f"rules={len(st.get('rules') or ())}"]
    if active:
        lines.append(f"  {'STATE':<8} {'SEV':<6} {'RULE':<24} "
                     f"{'INSTANCE':<28} {'VALUE':>10}  SINCE")
        for a in active:
            since = a.get("since")
            stamp = time.strftime("%H:%M:%S", time.localtime(since)) \
                if since else "-"
            lines.append(
                f"  {a.get('state', '?'):<8} "
                f"{str(a.get('severity', '-')):<6} "
                f"{str(a.get('rule'))[:24]:<24} "
                f"{str(a.get('instance'))[:28]:<28} "
                f"{_f(a.get('value'), '10.3f')}  {stamp}"
                + (f"  bundle={a['bundle']}" if a.get("bundle")
                   else ""))
    else:
        lines.append("  (quiet — nothing pending or firing)")
    hist = st.get("history") or ()
    if hist:
        lines.append("recent transitions:")
        for h in list(hist)[-8:]:
            w = h.get("at")
            stamp = time.strftime("%H:%M:%S", time.localtime(w)) \
                if w else "--:--:--"
            lines.append(f"  {stamp} {h.get('rule')} "
                         f"[{h.get('instance')}] -> {h.get('state')}")
    return "\n".join(lines)


def render_tenants(reply: dict) -> str:
    """The per-tenant usage pane from a ``usage_report`` reply."""
    usage = reply.get("usage") or {}
    tenants = usage.get("tenants") or {}
    lines = [f"tenant usage ({usage.get('scope', '?')})"
             + (f"  window={usage['window_s']:.0f}s"
                if usage.get("window_s") else "")]
    if not tenants:
        lines.append("  (no tenant traffic metered yet)")
        return "\n".join(lines)
    lines.append(f"  {'TENANT':<16} {'TIER':<4} {'TOK IN':>10} "
                 f"{'TOK OUT':>10} {'QUEUE s':>9} {'KV PAGE s':>10} "
                 f"{'GFLOPs':>9}  OUTCOMES")
    for key in sorted(tenants):
        u = tenants[key]
        outs = u.get("outcomes") or {}
        outs_s = " ".join(f"{k}={v:g}" for k, v in sorted(outs.items())
                          if v) or "-"
        gflops = (u.get("flops") or 0.0) / 1e9
        lines.append(
            f"  {str(u.get('tenant'))[:16]:<16} "
            f"{str(u.get('tier')):<4} "
            f"{_f(u.get('tokens_in'), '10.0f')} "
            f"{_f(u.get('tokens_out'), '10.0f')} "
            f"{_f(u.get('queue_seconds'), '9.1f')} "
            f"{_f(u.get('kv_page_seconds'), '10.1f')} "
            f"{gflops:9.3f}  {outs_s}")
    return "\n".join(lines)


def render_waterfall(trace: dict) -> str:
    """The assembled cross-process waterfall of one ``tel_trace``
    reply: spans in aligned start order, indented by span parentage,
    one rank tag per line."""
    spans = trace.get("spans") or ()
    if not spans:
        return f"trace {trace.get('trace_id')}: no spans"
    t0 = min(s["t0"] for s in spans)
    t1 = max(s["t1"] for s in spans)
    by_id = {s.get("span_id"): s for s in spans}

    def depth(s, limit=16):
        d = 0
        while d < limit:
            pid_ = s.get("parent_id")
            if not pid_ or pid_ not in by_id:
                return d
            s = by_id[pid_]
            d += 1
        return d

    head = [f"trace {trace.get('trace_id')}  "
            f"{(t1 - t0) * 1000:.2f}ms  "
            f"spans={len(spans)} procs={len(trace.get('procs') or ())}"
            f"  verdict={trace.get('verdict', 'open')}"
            f"{'' if trace.get('complete', True) else '  (incomplete)'}"]
    if trace.get("error"):
        head.append("  ** contains errors/deadline misses **")
    if trace.get("watchdog_flagged"):
        head.append("  ** watchdog flagged **")
    lines = head
    width = 30
    span_ms = max(1e-9, t1 - t0)
    for s in sorted(spans, key=lambda x: (x["t0"], x["t1"])):
        off = (s["t0"] - t0)
        dur = max(0.0, s["t1"] - s["t0"])
        a = int(width * off / span_ms)
        b = max(1, int(width * dur / span_ms))
        bar = " " * a + "#" * min(b, width - a)
        lines.append(
            f"{off * 1000:9.2f}ms {bar:<{width}} "
            f"{'  ' * depth(s)}{s['name']} ({dur * 1000:.2f}ms) "
            f"[{s.get('role')}@{s.get('host')}:{s.get('pid')}]")
    flights = trace.get("flight") or ()
    if flights:
        lines.append(f"flight events: "
                     + ", ".join(sorted({f"{e.get('tier')}/{e.get('kind')}"
                                         for e in flights})))
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.observability.top",
        description="live fleet dashboard / trace waterfall viewer")
    ap.add_argument("cmd", nargs="?", default="top",
                    choices=["top", "trace", "perf", "tier", "prefix",
                             "history", "alerts", "tenants"])
    ap.add_argument("trace_id", nargs="?",
                    help="trace: trace id; history: metric name")
    ap.add_argument("--collector", default=os.environ.get(
        "PADDLE_TPU_TELEMETRY_COLLECTOR") or "127.0.0.1:8600")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (no ANSI)")
    ap.add_argument("--out", help="trace: write the merged Chrome "
                                  "trace JSON here")
    ap.add_argument("--window", type=float, default=300.0,
                    help="history/tenants: trailing seconds")
    args = ap.parse_args(argv)

    from ..distributed.fleet.runtime.rpc import RpcClient
    cli = RpcClient(args.collector,
                    secret=os.environ.get("PADDLE_PS_SECRET") or None,
                    timeout=5.0, deadline=5.0, max_retries=0)
    try:
        if args.cmd == "trace":
            if not args.trace_id:
                print("usage: ... trace <trace_id>", file=sys.stderr)
                return 2
            rep = cli.call({"op": "tel_trace",
                            "trace_id": args.trace_id,
                            "chrome": bool(args.out)})
            tr = rep.get("trace")
            if tr is None:
                print(f"trace {args.trace_id}: not retained "
                      f"(unknown or sampled out)", file=sys.stderr)
                return 1
            print(render_waterfall(tr))
            if args.out and rep.get("chrome") is not None:
                with open(args.out, "w", encoding="utf-8") as f:
                    json.dump(rep["chrome"], f)
                print(f"chrome trace -> {args.out}")
            return 0
        if args.cmd == "history" and not args.trace_id:
            print("usage: ... history <metric>", file=sys.stderr)
            return 2
        # live loop (or one shot); each pane knows its own verb
        while True:
            if args.cmd == "history":
                rep = cli.call({"op": "tsdb_query", "query": "range",
                                "metric": args.trace_id,
                                "window": args.window})
                text = render_history(rep, args.trace_id, args.window)
            elif args.cmd == "alerts":
                text = render_alerts(cli.call({"op": "alerts"}))
            elif args.cmd == "tenants":
                text = render_tenants(cli.call(
                    {"op": "usage_report", "window": args.window}))
            else:
                render = {"perf": render_perf,
                          "tier": render_tier,
                          "prefix": render_prefix}.get(args.cmd,
                                                       render_fleet)
                fleet = cli.call({"op": "tel_fleet"})["fleet"]
                text = render(fleet)
            if args.once:
                print(text)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + text + "\n")
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0
    finally:
        cli.close()


if __name__ == "__main__":
    raise SystemExit(main())
