"""paddle.nn namespace (reference python/paddle/nn/)."""
from . import functional
from . import initializer
from .layers_common import *  # noqa: F401,F403
from .layers_common import __all__ as _common_all
from .transformer import (MultiHeadAttention, TransformerEncoderLayer,
                          TransformerEncoder, TransformerDecoderLayer,
                          TransformerDecoder, Transformer)
from .rnn import (RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN,
                  SimpleRNN, LSTM, GRU)
from . import decode
from .decode import beam_search
from .moe import MoELayer
from ..fluid.dygraph.layers import Layer
from ..fluid.clip import (ClipGradByValue, ClipGradByNorm,
                          ClipGradByGlobalNorm)

__all__ = ["Layer", "functional", "initializer", "ClipGradByValue",
           "ClipGradByNorm", "ClipGradByGlobalNorm", "MultiHeadAttention",
           "TransformerEncoderLayer", "TransformerEncoder",
           "TransformerDecoderLayer", "TransformerDecoder",
           "Transformer", "RNNCellBase", "SimpleRNNCell", "LSTMCell",
           "GRUCell", "RNN", "BiRNN", "SimpleRNN", "LSTM",
           "GRU", "MoELayer"] + list(_common_all)
